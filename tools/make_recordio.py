#!/usr/bin/env python
"""Pack records into (indexed) RecordIO files.

The reference ecosystem's ``im2rec``-style packing tool: reads newline
records from a text source (or length-prefixed blobs from stdin) and writes a
``.rec`` file plus an optional ``.idx`` index usable with
``type="indexed_recordio"`` splits::

    python tools/make_recordio.py --input data.txt --output data.rec --index data.idx
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True, help="text file; one record per line")
    ap.add_argument("--output", required=True, help="output .rec URI")
    ap.add_argument("--index", default="", help="optional .idx output URI")
    args = ap.parse_args()

    from dmlc_core_tpu.io.recordio import IndexedRecordIOWriter, RecordIOWriter
    from dmlc_core_tpu.io.stream import create_stream

    fo = create_stream(args.output, "w")
    writer = IndexedRecordIOWriter(fo) if args.index else RecordIOWriter(fo)
    n = 0
    with open(args.input, "rb") as fi:
        for line in fi:
            writer.write_record(line.rstrip(b"\n"))
            n += 1
    fo.close()
    if args.index:
        with create_stream(args.index, "w") as idx:
            writer.save_index(idx)
    print(f"wrote {n} records to {args.output}"
          + (f" (+ index {args.index})" if args.index else ""))


if __name__ == "__main__":
    main()
