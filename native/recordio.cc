// Native RecordIO hot paths for dmlc_core_tpu.
//
// The reference implements the RecordIO framing/scan machinery in C++
// (src/recordio.cc:11-156); this file is the TPU rebuild's equivalent for the
// two per-record loops that dominate .rec throughput:
//
//  - scan: one pass over an in-memory chunk producing per-record
//    (head offset, logical payload length, escaped?) arrays, with the same
//    resync rule as the reference's FindNextRecordIOHead
//    (src/recordio.cc:85-100): a record head is a 4-aligned magic word whose
//    following lrec has cflag 0 or 1.
//  - frame: batch-encode N payloads into the magic-framed wire format with
//    the in-band-magic escape protocol (src/recordio.cc:22-45): payloads are
//    split at each aligned magic cell into cflag 1/2/3 parts.
//
// Exposed through the same plain-C ABI / ctypes convention as parsers.cc.

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xCED7230Au;

inline uint32_t load_u32(const char* p) {
  uint32_t w;
  memcpy(&w, p, 4);
  return w;
}

inline uint32_t dec_flag(uint32_t lrec) { return (lrec >> 29u) & 7u; }
inline uint32_t dec_len(uint32_t lrec) { return lrec & ((1u << 29) - 1); }
inline uint32_t enc_lrec(uint32_t cflag, uint32_t len) {
  return (cflag << 29u) | len;
}
inline int64_t upper_align4(int64_t n) { return (n + 3) & ~int64_t(3); }

// First 4-aligned offset in [start, limit) holding a record head; limit when
// none (reference FindNextRecordIOHead).
int64_t find_head(const char* data, int64_t start, int64_t limit) {
  for (int64_t p = start; p + 8 <= limit; p += 4) {
    if (load_u32(data + p) == kMagic) {
      uint32_t cflag = dec_flag(load_u32(data + p + 4));
      if (cflag == 0 || cflag == 1) return p;
    }
  }
  return limit;
}

struct ScanResult {
  std::vector<int64_t> head;    // byte offset of each record's first part
  std::vector<int64_t> plen;    // logical payload length after unescape
  std::vector<uint8_t> escaped; // 1 when the record is multi-part
  int64_t pbegin = 0;
  int64_t pend = 0;
  std::string error_msg;
};

struct FrameResult {
  std::string out;               // framed bytes for the whole batch
  std::vector<int64_t> offsets;  // start of each record within `out`
  int64_t except_count = 0;      // number of in-band magic escapes
  std::string error_msg;
};

}  // namespace

extern "C" {

// Scan [begin, end) of a chunk after head-resync at both edges. The caller's
// partition rule matches the reference RecordIOChunkReader (recordio.cc:
// 102-117): pbegin = resync(begin), pend = resync(end), both against len.
void* dmlc_tpu_recordio_scan(const char* data, int64_t len, int64_t begin,
                             int64_t end) {
  auto* r = new ScanResult();
  if (begin < 0 || end > len || (begin & 3) || (end & 3)) {
    r->error_msg = "invalid scan bounds";
    return r;
  }
  r->pbegin = find_head(data, begin, len);
  r->pend = (end == len) ? len : find_head(data, end, len);
  int64_t p = r->pbegin;
  while (p < r->pend) {
    if (p + 8 > r->pend) {
      r->error_msg = "invalid RecordIO format: truncated header";
      return r;
    }
    if (load_u32(data + p) != kMagic) {
      r->error_msg = "invalid RecordIO format: bad magic";
      return r;
    }
    uint32_t lrec = load_u32(data + p + 4);
    uint32_t cflag = dec_flag(lrec);
    int64_t head = p;
    if (cflag == 0) {
      int64_t clen = dec_len(lrec);
      p += 8 + upper_align4(clen);
      if (p > r->pend) {
        r->error_msg = "invalid RecordIO format: truncated record";
        return r;
      }
      r->head.push_back(head);
      r->plen.push_back(clen);
      r->escaped.push_back(0);
      continue;
    }
    if (cflag != 1) {
      r->error_msg = "invalid RecordIO format: unexpected cflag";
      return r;
    }
    // multi-part record: walk cflag 1 -> 2* -> 3, logical length is the sum
    // of part lengths plus one restored magic cell between parts.
    int64_t total = 0;
    bool first = true;
    while (true) {
      if (p + 8 > r->pend) {
        r->error_msg = "invalid RecordIO format: truncated escaped record";
        return r;
      }
      if (load_u32(data + p) != kMagic) {
        r->error_msg = "invalid RecordIO format: bad magic in escaped record";
        return r;
      }
      lrec = load_u32(data + p + 4);
      cflag = dec_flag(lrec);
      if (!first && cflag != 2 && cflag != 3) {
        r->error_msg = "invalid RecordIO format: bad continuation cflag";
        return r;
      }
      int64_t clen = dec_len(lrec);
      p += 8 + upper_align4(clen);
      if (p > r->pend) {
        r->error_msg = "invalid RecordIO format: truncated escaped record";
        return r;
      }
      total += clen;
      if (cflag == 3) break;
      total += 4;  // the escaped magic cell between this part and the next
      first = false;
    }
    r->head.push_back(head);
    r->plen.push_back(total);
    r->escaped.push_back(1);
  }
  return r;
}

void dmlc_tpu_recordio_scan_dims(void* handle, int64_t* n, int64_t* pbegin,
                                 int64_t* pend) {
  auto* r = static_cast<ScanResult*>(handle);
  *n = r->error_msg.empty() ? static_cast<int64_t>(r->head.size()) : -1;
  *pbegin = r->pbegin;
  *pend = r->pend;
}

const char* dmlc_tpu_recordio_scan_error(void* handle) {
  return static_cast<ScanResult*>(handle)->error_msg.c_str();
}

void dmlc_tpu_recordio_scan_fill(void* handle, int64_t* head, int64_t* plen,
                                 uint8_t* escaped) {
  auto* r = static_cast<ScanResult*>(handle);
  if (!r->head.empty()) {
    memcpy(head, r->head.data(), r->head.size() * sizeof(int64_t));
    memcpy(plen, r->plen.data(), r->plen.size() * sizeof(int64_t));
    memcpy(escaped, r->escaped.data(), r->escaped.size());
  }
}

void dmlc_tpu_recordio_scan_free(void* handle) {
  delete static_cast<ScanResult*>(handle);
}

// Reassemble the record whose head is at byte offset `head` into `out`
// (capacity out_cap), restoring escaped in-band magic cells. Returns the
// logical length, or -1 on malformed input / overflow. Bounds are
// re-validated so this is safe to call with offsets from any source.
int64_t dmlc_tpu_recordio_extract(const char* data, int64_t len, int64_t head,
                                  char* out, int64_t out_cap) {
  int64_t p = head;
  char* dst = out;
  while (true) {
    if (p < 0 || p + 8 > len || load_u32(data + p) != kMagic) return -1;
    uint32_t lrec = load_u32(data + p + 4);
    uint32_t cflag = dec_flag(lrec);
    int64_t clen = dec_len(lrec);
    if (p + 8 + clen > len || (dst - out) + clen > out_cap) return -1;
    memcpy(dst, data + p + 8, clen);
    dst += clen;
    p += 8 + upper_align4(clen);
    if (cflag == 0 || cflag == 3) break;
    if ((dst - out) + 4 > out_cap) return -1;
    memcpy(dst, &kMagic, 4);  // restore the escaped in-band magic cell
    dst += 4;
  }
  return dst - out;
}

// Batch-frame n payloads (concatenated in `payloads`, lengths in `lens`).
void* dmlc_tpu_recordio_frame(const char* payloads, const int64_t* lens,
                              int64_t n) {
  auto* r = new FrameResult();
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += lens[i];
  r->out.reserve(total + 16 * n);
  r->offsets.reserve(n);
  const char* rec = payloads;
  char hdr[8];
  memcpy(hdr, &kMagic, 4);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t len = lens[i];
    if (len >= (int64_t(1) << 29)) {
      r->error_msg = "RecordIO only accepts records below 2^29 bytes";
      return r;
    }
    r->offsets.push_back(static_cast<int64_t>(r->out.size()));
    // scan aligned cells for in-band magic (reference recordio.cc:22-38)
    const int64_t lower_align = (len >> 2) << 2;
    int64_t dptr = 0;
    for (int64_t pos = 0; pos + 4 <= lower_align; pos += 4) {
      if (load_u32(rec + pos) == kMagic) {
        uint32_t lrec = enc_lrec(dptr == 0 ? 1 : 2,
                                 static_cast<uint32_t>(pos - dptr));
        memcpy(hdr + 4, &lrec, 4);
        r->out.append(hdr, 8);
        r->out.append(rec + dptr, pos - dptr);
        dptr = pos + 4;
        ++r->except_count;
      }
    }
    uint32_t lrec = enc_lrec(dptr == 0 ? 0 : 3,
                             static_cast<uint32_t>(len - dptr));
    memcpy(hdr + 4, &lrec, 4);
    r->out.append(hdr, 8);
    r->out.append(rec + dptr, len - dptr);
    const int64_t pad = (-(len - dptr)) & 3;
    r->out.append(pad, '\0');
    rec += len;
  }
  return r;
}

void dmlc_tpu_frame_dims(void* handle, int64_t* out_size, int64_t* n_offsets,
                         int64_t* except_count) {
  auto* r = static_cast<FrameResult*>(handle);
  *out_size = r->error_msg.empty()
                  ? static_cast<int64_t>(r->out.size()) : -1;
  *n_offsets = static_cast<int64_t>(r->offsets.size());
  *except_count = r->except_count;
}

const char* dmlc_tpu_frame_error(void* handle) {
  return static_cast<FrameResult*>(handle)->error_msg.c_str();
}

void dmlc_tpu_frame_fill(void* handle, char* out, int64_t* offsets) {
  auto* r = static_cast<FrameResult*>(handle);
  if (out && !r->out.empty()) memcpy(out, r->out.data(), r->out.size());
  if (offsets && !r->offsets.empty()) {
    memcpy(offsets, r->offsets.data(), r->offsets.size() * sizeof(int64_t));
  }
}

void dmlc_tpu_frame_free(void* handle) {
  delete static_cast<FrameResult*>(handle);
}

}  // extern "C"
