// Native input split: byte-range sharding over local files with record
// realignment at shard edges and a double-buffered prefetch thread.
//
// C++ counterpart of dmlc_core_tpu/io/input_split.py (LineSplitter,
// RecordIOSplitter, IndexedRecordIOSplitter byte paths + ThreadedInputSplit)
// and of the reference engines they mirror (src/io/input_split_base.cc
// ResetPartition/ReadChunk, src/io/line_split.cc, src/io/recordio_split.cc
// magic-resync, src/io/indexed_recordio_split.cc batch reads,
// src/io/threaded_input_split.h).  The Python layer delegates here when every
// file is local; remote URIs keep the Python path.  Semantics are kept
// bit-identical to the Python engine — the all-parts coverage tests diff the
// two implementations record by record.

#ifndef _FILE_OFFSET_BITS
#define _FILE_OFFSET_BITS 64  // make off_t/fseeko 64-bit on 32-bit targets
#endif

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

namespace {

struct FileEnt {
  std::string path;
  int64_t size;
};

// 64-bit-safe absolute seek: std::fseek takes a long, which is 32 bits on
// Windows and ILP32 builds — truncating offsets >= 2 GiB in large shards.
inline int Seek64(std::FILE *fp, int64_t off) {
#if defined(_WIN32)
  return _fseeki64(fp, off, SEEK_SET);
#else
  return fseeko(fp, static_cast<off_t>(off), SEEK_SET);
#endif
}

bool IsEol(unsigned char c) { return c == '\n' || c == '\r'; }

// RecordIO framing constants (dmlc_core_tpu/io/recordio.py, reference
// include/dmlc/recordio.h:45)
constexpr uint32_t kRecordIOMagic = 0xced7230a;
inline uint32_t CFlag(uint32_t len_word) { return (len_word >> 29) & 7u; }

enum Format { kLine = 0, kRecordIO = 1 };

// Shared double-buffered prefetch: one producer thread, queue capacity 2,
// (ok, chunk) items with an end sentinel that stays queued for repeated
// pops (reference threaded_input_split.h:23-101 / ThreadedIter cap-2).
// Used by both split engines so the protocol can't drift between them.
class PrefetchQueue {
 public:
  ~PrefetchQueue() { Stop(); }

  // next(chunk) -> true while chunks remain; false terminates the producer
  void Start(std::function<bool(std::vector<char> *)> next) {
    stop_ = false;
    producer_ = std::thread([this, next = std::move(next)] {
      while (true) {
        std::vector<char> chunk;
        bool ok = next(&chunk);
        std::unique_lock<std::mutex> lk(mu_);
        cv_space_.wait(lk, [this] { return queue_.size() < 2 || stop_; });
        if (stop_) return;
        queue_.emplace_back(ok, std::move(chunk));
        cv_data_.notify_one();
        if (!ok) return;  // end-of-data sentinel queued
      }
    });
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
      cv_space_.notify_all();
    }
    if (producer_.joinable()) producer_.join();
    producer_ = std::thread();
    queue_.clear();
  }

  // end sentinel without a producer (empty partition/plan): Pop never blocks
  void PushEnd() {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.emplace_back(false, std::vector<char>());
    cv_data_.notify_all();
  }

  bool Pop(std::vector<char> *out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_data_.wait(lk, [this] { return !queue_.empty(); });
    auto item = std::move(queue_.front());
    queue_.pop_front();
    cv_space_.notify_one();
    if (!item.first) {
      // leave the sentinel for repeated calls
      queue_.emplace_front(false, std::vector<char>());
      return false;
    }
    *out = std::move(item.second);
    return true;
  }

 private:
  std::thread producer_;
  std::mutex mu_;
  std::condition_variable cv_data_, cv_space_;
  std::deque<std::pair<bool, std::vector<char>>> queue_;
  bool stop_ = false;
};

class LineSplitEngine {
 public:
  LineSplitEngine(std::vector<FileEnt> files, int64_t buffer_size,
                  Format format = kLine)
      : files_(std::move(files)), buffer_size_(buffer_size), format_(format) {
    offsets_.push_back(0);
    for (auto &f : files_) offsets_.push_back(offsets_.back() + f.size);
  }

  ~LineSplitEngine() { queue_.Stop(); CloseFile(); }

  int64_t TotalSize() const { return offsets_.back(); }
  std::string Error() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return error_;
  }

  void ResetPartition(int64_t part, int64_t nparts) {
    queue_.Stop();
    ClearError();  // a past transient failure must not poison future resets
    if (!DoResetPartition(part, nparts)) {
      // empty partition or failure: queue the end sentinel so PopChunk
      // never blocks waiting on a producer that was never started
      queue_.PushEnd();
      return;
    }
    queue_.Start([this](std::vector<char> *c) { return NextChunk(c); });
  }

  bool DoResetPartition(int64_t part, int64_t nparts) {
    int64_t ntotal = offsets_.back();
    int64_t nstep = (ntotal + nparts - 1) / nparts;
    int64_t align = format_ == kRecordIO ? 4 : 1;
    nstep = (nstep + align - 1) / align * align;
    begin_ = std::min(nstep * part, ntotal);
    end_ = std::min(nstep * (part + 1), ntotal);
    overflow_.clear();
    if (begin_ >= end_) { curr_ = begin_; CloseFile(); return false; }
    // realign the end edge to the next record head inside its file
    size_t fend = UpperBound(end_);
    if (end_ != offsets_[fend]) {
      std::FILE *fp = std::fopen(files_[fend].path.c_str(), "rb");
      if (!fp) { Fail("cannot open " + files_[fend].path); return false; }
      Seek64(fp, end_ - offsets_[fend]);
      end_ += SeekRecordBegin(fp);
      std::fclose(fp);
    }
    // realign the begin edge likewise
    file_ptr_ = UpperBound(begin_);
    if (!OpenFile(file_ptr_)) return false;
    if (begin_ != offsets_[file_ptr_]) {
      Seek64(fp_, begin_ - offsets_[file_ptr_]);
      begin_ += SeekRecordBegin(fp_);
    }
    BeforeFirst();
    return !failed();
  }

  void BeforeFirst() {
    if (begin_ >= end_) return;
    size_t fptr = UpperBound(begin_);
    if (!fp_ || file_ptr_ != fptr) {
      file_ptr_ = fptr;
      if (!OpenFile(file_ptr_)) return;
    }
    Seek64(fp_, begin_ - offsets_[file_ptr_]);
    curr_ = begin_;
    overflow_.clear();
  }

  // next chunk of whole records into out; false at partition end
  bool NextChunk(std::vector<char> *out) {
    int64_t size = buffer_size_.load(std::memory_order_relaxed);
    while (true) {
      if (!ReadChunk(size, out)) return false;
      if (!out->empty()) return true;
      size *= 2;  // record larger than the buffer: grow and retry
    }
  }

  // grow the typical chunk size without disturbing the read position
  // (consumed by the prefetch thread at its next NextChunk)
  void HintChunkSize(int64_t size) {
    int64_t cur = buffer_size_.load(std::memory_order_relaxed);
    while (size > cur &&
           !buffer_size_.compare_exchange_weak(cur, size)) {
    }
  }

  // pops the next prefetched chunk; false at end
  bool PopChunk(std::vector<char> *out) { return queue_.Pop(out); }

  // error_ is written by the prefetch thread (Fail in Read/OpenFile) and
  // read by the consumer thread — guard it with its own mutex so a torn
  // string read can't happen
  bool failed() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return !error_.empty();
  }

  void ClearError() {
    std::lock_guard<std::mutex> lk(err_mu_);
    error_.clear();
  }

 private:
  void Fail(const std::string &msg) {
    std::lock_guard<std::mutex> lk(err_mu_);
    if (error_.empty()) error_ = msg;
  }

  size_t UpperBound(int64_t offset) const {
    // index of the file containing byte `offset` of the concatenation
    auto it = std::upper_bound(offsets_.begin(), offsets_.end(), offset);
    return static_cast<size_t>(it - offsets_.begin()) - 1;
  }

  bool OpenFile(size_t idx) {
    CloseFile();
    fp_ = std::fopen(files_[idx].path.c_str(), "rb");
    if (!fp_) { Fail("cannot open " + files_[idx].path); return false; }
    return true;
  }

  void CloseFile() {
    if (fp_) { std::fclose(fp_); fp_ = nullptr; }
  }

  // bytes to skip from the current position to the next record head
  int64_t SeekRecordBegin(std::FILE *fp) {
    return format_ == kRecordIO ? SeekRecordBeginRecordIO(fp)
                                : SeekRecordBeginLine(fp);
  }

  // (reference line_split.cc:9-26: to first EOL, then past the EOL run)
  static int64_t SeekRecordBeginLine(std::FILE *fp) {
    int64_t nstep = 0;
    bool seen_eol = false;
    char block[4096];
    while (true) {
      size_t n = std::fread(block, 1, sizeof(block), fp);
      if (n == 0) return nstep;
      for (size_t i = 0; i < n; ++i) {
        unsigned char c = static_cast<unsigned char>(block[i]);
        if (!seen_eol) {
          ++nstep;
          if (IsEol(c)) seen_eol = true;
        } else if (IsEol(c)) {
          ++nstep;
        } else {
          return nstep;
        }
      }
    }
  }

  // word-scan for magic followed by cflag 0/1 (reference
  // recordio_split.cc:9-26; mirrors RecordIOSplitter.seek_record_begin in
  // io/input_split.py — incl. consuming the word after a failed flag test)
  static int64_t SeekRecordBeginRecordIO(std::FILE *fp) {
    int64_t nstep = 0;
    bool saw_magic = false;
    char block[4096];
    while (true) {
      size_t n = std::fread(block, 1, sizeof(block), fp);
      size_t nwords = n / 4;
      if (nwords == 0) return nstep;
      for (size_t i = 0; i < nwords; ++i) {
        uint32_t w;
        std::memcpy(&w, block + i * 4, 4);
        nstep += 4;
        if (saw_magic) {
          saw_magic = false;
          uint32_t flag = CFlag(w);
          if (flag == 0 || flag == 1) return nstep - 8;
        } else if (w == kRecordIOMagic) {
          saw_magic = true;
        }
      }
      if (n != nwords * 4) return nstep;  // sub-word tail: end of data
    }
  }

  // offset of the last record head in [data, data+n) (0 if none beyond start)
  int64_t FindLastRecordBegin(const char *data, int64_t n) const {
    if (format_ == kRecordIO) {
      int64_t nwords = n / 4;
      for (int64_t i = nwords - 2; i > 0; --i) {
        uint32_t w, next;
        std::memcpy(&w, data + i * 4, 4);
        if (w != kRecordIOMagic) continue;
        std::memcpy(&next, data + (i + 1) * 4, 4);
        uint32_t flag = CFlag(next);
        if (flag == 0 || flag == 1) return i * 4;
      }
      return 0;
    }
    for (int64_t i = n - 1; i > 0; --i) {
      if (IsEol(static_cast<unsigned char>(data[i]))) return i + 1;
    }
    return 0;
  }

  // read up to `size` partition bytes, crossing file boundaries
  int64_t Read(char *buf, int64_t size) {
    if (begin_ >= end_ || !fp_) return 0;
    size = std::min(size, end_ - curr_);
    int64_t got = 0;
    while (got < size) {
      size_t n = std::fread(buf + got, 1, static_cast<size_t>(size - got),
                            fp_);
      if (n > 0) {
        got += static_cast<int64_t>(n);
        curr_ += static_cast<int64_t>(n);
        continue;
      }
      if (curr_ != offsets_[file_ptr_ + 1]) {
        Fail("file offset not calculated correctly");
        return got;
      }
      if (file_ptr_ + 1 >= files_.size()) break;
      ++file_ptr_;
      if (!OpenFile(file_ptr_)) return got;
    }
    return got;
  }

  // one chunk ending at a record boundary; false at partition end,
  // empty chunk when max_size cannot hold one record (caller grows)
  bool ReadChunk(int64_t max_size, std::vector<char> *out) {
    out->clear();
    if (max_size <= static_cast<int64_t>(overflow_.size())) return true;
    out->swap(overflow_);
    overflow_.clear();
    int64_t head = static_cast<int64_t>(out->size());
    out->resize(static_cast<size_t>(max_size));
    int64_t got = Read(out->data() + head, max_size - head);
    int64_t total = head + got;
    if (total == 0) { out->clear(); return false; }
    out->resize(static_cast<size_t>(total));
    if (total != max_size) return true;  // partition tail at realigned edge
    int64_t cut = FindLastRecordBegin(out->data(), total);
    overflow_.assign(out->begin() + cut, out->end());
    out->resize(static_cast<size_t>(cut));
    return true;
  }

  std::vector<FileEnt> files_;
  std::vector<int64_t> offsets_;
  std::atomic<int64_t> buffer_size_;
  Format format_;
  std::FILE *fp_ = nullptr;
  size_t file_ptr_ = 0;
  int64_t begin_ = 0, end_ = 0, curr_ = 0;
  std::vector<char> overflow_;
  mutable std::mutex err_mu_;
  std::string error_;
  PrefetchQueue queue_;
};

// Index-driven batch reads with prefetch (reference
// src/io/indexed_recordio_split.cc:43-227 byte path).  Policy — index
// partitioning, batch grouping, the seeded shuffle permutation — stays in
// Python (io/input_split.py IndexedRecordIOSplitter); this engine executes a
// per-epoch *plan*: a flat list of (offset, size) spans in the concatenated
// file space plus per-batch span counts, each batch concatenated into one
// chunk and read ahead by a producer thread.
class SpanReadEngine {
 public:
  explicit SpanReadEngine(std::vector<FileEnt> files)
      : files_(std::move(files)) {
    offsets_.push_back(0);
    for (auto &f : files_) offsets_.push_back(offsets_.back() + f.size);
  }

  ~SpanReadEngine() { queue_.Stop(); CloseFile(); }

  std::string Error() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return error_;
  }
  bool failed() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return !error_.empty();
  }

  void SetPlan(const int64_t *offs, const int64_t *sizes,
               const int64_t *counts, int64_t nspans, int64_t nbatches) {
    queue_.Stop();
    // a failed prior epoch may have left the OS file position ahead of
    // curr_ (short-read abort); force a clean reopen + seek
    CloseFile();
    {
      std::lock_guard<std::mutex> lk(err_mu_);
      error_.clear();
    }
    spans_.assign(nspans, {});
    for (int64_t i = 0; i < nspans; ++i) spans_[i] = {offs[i], sizes[i]};
    counts_.assign(counts, counts + nbatches);
    next_batch_ = 0;
    next_span_ = 0;
    if (nbatches == 0) {
      queue_.PushEnd();   // empty plan: Pop never blocks on a producer
      return;
    }
    queue_.Start([this](std::vector<char> *c) { return NextBatch(c); });
  }

  bool PopChunk(std::vector<char> *out) { return queue_.Pop(out); }

 private:
  bool NextBatch(std::vector<char> *out) {
    out->clear();
    if (next_batch_ >= static_cast<int64_t>(counts_.size())) return false;
    int64_t nspan = counts_[next_batch_++];
    for (int64_t k = 0; k < nspan; ++k) {
      if (next_span_ >= static_cast<int64_t>(spans_.size())) {
        Fail("span plan shorter than batch counts");
        return false;
      }
      auto span = spans_[next_span_++];
      if (!ReadSpan(span.first, span.second, out)) return false;
    }
    // real plans have >=1 record of >=8 bytes per batch; an empty batch is
    // treated as end-of-plan, matching the Python path's `data or None`
    return !out->empty();
  }

  // read [offset, offset+size) of the concatenation, crossing file bounds
  bool ReadSpan(int64_t offset, int64_t size, std::vector<char> *out) {
    size_t head = out->size();
    out->resize(head + static_cast<size_t>(size));
    char *dst = out->data() + head;
    while (size > 0) {
      size_t idx = UpperBound(offset);
      if (idx >= files_.size()) { Fail("span beyond input"); return false; }
      if (!EnsureOpen(idx)) return false;
      int64_t local = offset - offsets_[idx];
      if (curr_ != local) {
        if (Seek64(fp_, local) != 0) { Fail("seek failed"); return false; }
        curr_ = local;
      }
      int64_t avail = std::min(size, files_[idx].size - local);
      int64_t got = 0;
      while (got < avail) {
        size_t n = std::fread(dst + got, 1,
                              static_cast<size_t>(avail - got), fp_);
        if (n == 0) {
          curr_ += got;  // keep curr_ == OS position even on the error path
          Fail("short read in " + files_[idx].path);
          return false;
        }
        got += static_cast<int64_t>(n);
      }
      curr_ += got;
      dst += got;
      offset += got;
      size -= got;
    }
    return true;
  }

  size_t UpperBound(int64_t offset) const {
    auto it = std::upper_bound(offsets_.begin(), offsets_.end(), offset);
    return static_cast<size_t>(it - offsets_.begin()) - 1;
  }

  bool EnsureOpen(size_t idx) {
    if (fp_ && file_ptr_ == idx) return true;
    CloseFile();
    fp_ = std::fopen(files_[idx].path.c_str(), "rb");
    if (!fp_) { Fail("cannot open " + files_[idx].path); return false; }
    file_ptr_ = idx;
    curr_ = 0;
    return true;
  }

  void CloseFile() {
    if (fp_) { std::fclose(fp_); fp_ = nullptr; }
  }

  void Fail(const std::string &msg) {
    std::lock_guard<std::mutex> lk(err_mu_);
    if (error_.empty()) error_ = msg;
  }

  std::vector<FileEnt> files_;
  std::vector<int64_t> offsets_;
  std::vector<std::pair<int64_t, int64_t>> spans_;
  std::vector<int64_t> counts_;
  int64_t next_batch_ = 0, next_span_ = 0;
  std::FILE *fp_ = nullptr;
  size_t file_ptr_ = 0;
  int64_t curr_ = 0;
  mutable std::mutex err_mu_;
  std::string error_;
  PrefetchQueue queue_;
};

struct SplitHandle {
  LineSplitEngine *engine = nullptr;
  std::vector<char> current;  // chunk handed to Python, valid until next call
  std::string error;
};

struct SpanHandle {
  SpanReadEngine *engine = nullptr;
  std::vector<char> current;
  std::string error;
};

std::vector<FileEnt> DecodeFiles(const char *paths, const int64_t *path_lens,
                                 const int64_t *sizes, int64_t nfiles) {
  std::vector<FileEnt> files;
  const char *p = paths;
  for (int64_t i = 0; i < nfiles; ++i) {
    files.push_back({std::string(p, static_cast<size_t>(path_lens[i])),
                     sizes[i]});
    p += path_lens[i];
  }
  return files;
}

}  // namespace

extern "C" {

// paths: concatenated path bytes with per-path byte lengths in path_lens
// (length-delimited, so any legal filename byte — incl. '\n' — is safe);
// sizes: per-file byte sizes
void *dmlc_tpu_lsplit_open(const char *paths, const int64_t *path_lens,
                           const int64_t *sizes, int64_t nfiles,
                           int64_t part, int64_t nparts,
                           int64_t buffer_size) {
  auto *h = new SplitHandle();
  h->engine = new LineSplitEngine(
      DecodeFiles(paths, path_lens, sizes, nfiles), buffer_size, kLine);
  h->engine->ResetPartition(part, nparts);
  if (h->engine->failed()) h->error = h->engine->Error();
  return h;
}

// RecordIO variant: same handle/call surface as lsplit_* (hint/total/reset/
// next_chunk/error/close all apply), only the record format differs
void *dmlc_tpu_rsplit_open(const char *paths, const int64_t *path_lens,
                           const int64_t *sizes, int64_t nfiles,
                           int64_t part, int64_t nparts,
                           int64_t buffer_size) {
  auto *h = new SplitHandle();
  h->engine = new LineSplitEngine(
      DecodeFiles(paths, path_lens, sizes, nfiles), buffer_size, kRecordIO);
  h->engine->ResetPartition(part, nparts);
  if (h->engine->failed()) h->error = h->engine->Error();
  return h;
}

// ---- index-driven span reader (indexed recordio batches) -------------------

void *dmlc_tpu_span_open(const char *paths, const int64_t *path_lens,
                         const int64_t *sizes, int64_t nfiles) {
  auto *h = new SpanHandle();
  h->engine = new SpanReadEngine(DecodeFiles(paths, path_lens, sizes, nfiles));
  return h;
}

void dmlc_tpu_span_set_plan(void *handle, const int64_t *offs,
                            const int64_t *sizes, const int64_t *counts,
                            int64_t nspans, int64_t nbatches) {
  auto *h = static_cast<SpanHandle *>(handle);
  h->error.clear();
  h->engine->SetPlan(offs, sizes, counts, nspans, nbatches);
}

// returns chunk length (>0), 0 at plan end, -1 on error
int64_t dmlc_tpu_span_next_chunk(void *handle, const char **ptr) {
  auto *h = static_cast<SpanHandle *>(handle);
  if (!h->error.empty()) return -1;
  if (!h->engine->PopChunk(&h->current)) {
    if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
    return 0;
  }
  if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
  *ptr = h->current.data();
  return static_cast<int64_t>(h->current.size());
}

const char *dmlc_tpu_span_error(void *handle) {
  return static_cast<SpanHandle *>(handle)->error.c_str();
}

void dmlc_tpu_span_close(void *handle) {
  auto *h = static_cast<SpanHandle *>(handle);
  delete h->engine;
  delete h;
}

void dmlc_tpu_lsplit_hint(void *handle, int64_t chunk_size) {
  static_cast<SplitHandle *>(handle)->engine->HintChunkSize(chunk_size);
}

int64_t dmlc_tpu_lsplit_total(void *handle) {
  return static_cast<SplitHandle *>(handle)->engine->TotalSize();
}

void dmlc_tpu_lsplit_reset(void *handle, int64_t part, int64_t nparts) {
  auto *h = static_cast<SplitHandle *>(handle);
  h->error.clear();  // a reset retries cleanly after a transient failure
  h->engine->ResetPartition(part, nparts);
  if (h->engine->failed()) h->error = h->engine->Error();
}

// returns chunk length (>0), 0 at partition end, -1 on error;
// *ptr stays valid until the next call on this handle
int64_t dmlc_tpu_lsplit_next_chunk(void *handle, const char **ptr) {
  auto *h = static_cast<SplitHandle *>(handle);
  if (!h->error.empty()) return -1;
  if (!h->engine->PopChunk(&h->current)) {
    if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
    return 0;
  }
  if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
  *ptr = h->current.data();
  return static_cast<int64_t>(h->current.size());
}

const char *dmlc_tpu_lsplit_error(void *handle) {
  return static_cast<SplitHandle *>(handle)->error.c_str();
}

void dmlc_tpu_lsplit_close(void *handle) {
  auto *h = static_cast<SplitHandle *>(handle);
  delete h->engine;
  delete h;
}

}  // extern "C"
