// Native input split: byte-range sharding with record realignment at shard
// edges and a double-buffered prefetch thread.
//
// C++ counterpart of dmlc_core_tpu/io/input_split.py (LineSplitter,
// RecordIOSplitter, IndexedRecordIOSplitter byte paths + ThreadedInputSplit
// + CachedInputSplit) and of the reference engines they mirror
// (src/io/input_split_base.cc ResetPartition/ReadChunk, src/io/line_split.cc,
// src/io/recordio_split.cc magic-resync, src/io/indexed_recordio_split.cc
// batch reads, src/io/threaded_input_split.h, src/io/cached_input_split.h).
//
// Bytes arrive through a ByteSource: local files read FILE* directly; remote
// URIs read through a caller-provided read-at callback (Python supplies one
// backed by the remote SeekStream), so the chunking/realignment/prefetch hot
// path is native for EVERY filesystem.  The epoch-1 producer can tee chunks
// into a (u64-length-framed) cache file and CacheReplayEngine replays it on
// later epochs.  Semantics are kept bit-identical to the Python engine — the
// all-parts coverage tests diff the two implementations record by record.

#ifndef _FILE_OFFSET_BITS
#define _FILE_OFFSET_BITS 64  // make off_t/fseeko 64-bit on 32-bit targets
#endif

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

// read up to `size` bytes of file `file_idx` at `offset`; returns bytes read
// (0 = EOF), or <0 on error.  Implemented by Python (ctypes CFUNCTYPE over a
// remote SeekStream) for non-local filesystems; called from the prefetch
// thread (ctypes acquires the GIL per call).
extern "C" typedef int64_t (*dmlc_tpu_read_at_fn)(void *ctx, int64_t file_idx,
                                                  int64_t offset, char *buf,
                                                  int64_t size);

namespace {

struct FileEnt {
  std::string path;
  int64_t size;
};

// 64-bit-safe absolute seek: std::fseek takes a long, which is 32 bits on
// Windows and ILP32 builds — truncating offsets >= 2 GiB in large shards.
inline int Seek64(std::FILE *fp, int64_t off) {
#if defined(_WIN32)
  return _fseeki64(fp, off, SEEK_SET);
#else
  return fseeko(fp, static_cast<off_t>(off), SEEK_SET);
#endif
}

inline int64_t FileSize64(std::FILE *fp) {
  // 64-bit-safe size probe (std::ftell returns a 32-bit long on Windows
  // and ILP32 — a >2 GiB cache would read as negative/truncated)
#if defined(_WIN32)
  _fseeki64(fp, 0, SEEK_END);
  int64_t n = _ftelli64(fp);
  _fseeki64(fp, 0, SEEK_SET);
#else
  fseeko(fp, 0, SEEK_END);
  int64_t n = static_cast<int64_t>(ftello(fp));
  fseeko(fp, 0, SEEK_SET);
#endif
  return n;
}

bool IsEol(unsigned char c) { return c == '\n' || c == '\r'; }

// RecordIO framing constants (dmlc_core_tpu/io/recordio.py, reference
// include/dmlc/recordio.h:45)
constexpr uint32_t kRecordIOMagic = 0xced7230a;
inline uint32_t CFlag(uint32_t len_word) { return (len_word >> 29) & 7u; }

enum Format { kLine = 0, kRecordIO = 1 };

// ---- byte sources ----------------------------------------------------------
// Random-access reads over the job's file list; the engines are written
// against this interface so local FILE* and remote-callback inputs share
// one chunking/realignment implementation.
class ByteSource {
 public:
  virtual ~ByteSource() = default;
  // bytes read (0 = EOF of that file), <0 on error
  virtual int64_t ReadAt(size_t file_idx, int64_t offset, char *buf,
                         int64_t size) = 0;
  virtual std::string LastError() const = 0;
  // drop cached handles so the next read reopens (a reset must observe
  // renamed/replaced files, like the reopen-per-reset Python engines)
  virtual void Invalidate() {}
};

class LocalSource : public ByteSource {
 public:
  explicit LocalSource(std::vector<std::string> paths)
      : paths_(std::move(paths)) {}
  ~LocalSource() override {
    if (fp_) std::fclose(fp_);
  }

  int64_t ReadAt(size_t idx, int64_t offset, char *buf,
                 int64_t size) override {
    if (!fp_ || idx_ != idx) {
      if (fp_) std::fclose(fp_);
      fp_ = std::fopen(paths_[idx].c_str(), "rb");
      if (!fp_) {
        err_ = "cannot open " + paths_[idx];
        return -1;
      }
      idx_ = idx;
      pos_ = 0;
    }
    if (pos_ != offset) {  // sequential reads skip the syscall
      if (Seek64(fp_, offset) != 0) {
        err_ = "seek failed in " + paths_[idx];
        return -1;
      }
      pos_ = offset;
    }
    size_t got = std::fread(buf, 1, static_cast<size_t>(size), fp_);
    pos_ += static_cast<int64_t>(got);
    if (got == 0 && std::ferror(fp_)) {
      err_ = "read error in " + paths_[idx];
      return -1;
    }
    return static_cast<int64_t>(got);
  }

  std::string LastError() const override { return err_; }

  void Invalidate() override {
    if (fp_) {
      std::fclose(fp_);
      fp_ = nullptr;
    }
  }

 private:
  std::vector<std::string> paths_;
  std::FILE *fp_ = nullptr;
  size_t idx_ = 0;
  int64_t pos_ = 0;
  std::string err_;
};

class CallbackSource : public ByteSource {
 public:
  CallbackSource(dmlc_tpu_read_at_fn fn, void *ctx) : fn_(fn), ctx_(ctx) {}

  int64_t ReadAt(size_t idx, int64_t offset, char *buf,
                 int64_t size) override {
    return fn_(ctx_, static_cast<int64_t>(idx), offset, buf, size);
  }

  // the Python side records the real exception next to the callback; this
  // is only the native-visible fallback text
  std::string LastError() const override { return "reader callback failed"; }

  // Reopen sentinel: engines only call this between queue_.Stop() (which
  // joins the producer) and the new epoch's queue_.Start(), so the Python
  // side can drop cached streams AND forget a parked stale error with no
  // in-flight read to race against (the pre-r5 consumer-side flag flip
  // could clear an error an old in-flight read was about to park).
  void Invalidate() override { fn_(ctx_, -1, 0, nullptr, 0); }

 private:
  dmlc_tpu_read_at_fn fn_;
  void *ctx_;
};

std::unique_ptr<ByteSource> MakeSource(const std::vector<FileEnt> &files,
                                       dmlc_tpu_read_at_fn read_cb,
                                       void *ctx) {
  if (read_cb != nullptr) {
    return std::unique_ptr<ByteSource>(new CallbackSource(read_cb, ctx));
  }
  std::vector<std::string> paths;
  paths.reserve(files.size());
  for (auto &f : files) paths.push_back(f.path);
  return std::unique_ptr<ByteSource>(new LocalSource(std::move(paths)));
}

// little-endian u64 cache-frame header — must match the Python cache format
// (io/input_split.py CachedInputSplit: struct.pack("<Q", len))
inline void EncodeU64LE(uint64_t v, unsigned char *out) {
  for (int i = 0; i < 8; ++i) out[i] = static_cast<unsigned char>(v >> (8 * i));
}
inline uint64_t DecodeU64LE(const unsigned char *in) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; --i) v = (v << 8) | in[i];
  return v;
}

// Shared prefetch ring: one producer thread, queue capacity 2 by default
// (reference threaded_input_split.h:23-101 / ThreadedIter cap-2) or a
// deeper pre-posted ring for the batched-pop remote path, (ok, chunk)
// items with an end sentinel that stays queued for repeated pops.
// Used by both split engines so the protocol can't drift between them.
class PrefetchQueue {
 public:
  ~PrefetchQueue() { Stop(); }

  // only before Start(): the ring depth the producer fills ahead
  void SetCapacity(int64_t capacity) {
    capacity_ = capacity < 1 ? 1 : static_cast<size_t>(capacity);
  }

  // next(chunk) -> true while chunks remain; false terminates the producer
  void Start(std::function<bool(std::vector<char> *)> next) {
    stop_ = false;
    producer_ = std::thread([this, next = std::move(next)] {
      while (true) {
        std::vector<char> chunk;
        bool ok = next(&chunk);
        std::unique_lock<std::mutex> lk(mu_);
        cv_space_.wait(lk,
                       [this] { return queue_.size() < capacity_ || stop_; });
        if (stop_) return;
        queue_.emplace_back(ok, std::move(chunk));
        cv_data_.notify_one();
        if (!ok) return;  // end-of-data sentinel queued
      }
    });
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
      cv_space_.notify_all();
    }
    if (producer_.joinable()) producer_.join();
    producer_ = std::thread();
    queue_.clear();
  }

  // end sentinel without a producer (empty partition/plan): Pop never blocks
  void PushEnd() {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.emplace_back(false, std::vector<char>());
    cv_data_.notify_all();
  }

  bool Pop(std::vector<char> *out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_data_.wait(lk, [this] { return !queue_.empty(); });
    auto item = std::move(queue_.front());
    queue_.pop_front();
    cv_space_.notify_one();
    if (!item.first) {
      // leave the sentinel for repeated calls
      queue_.emplace_front(false, std::vector<char>());
      return false;
    }
    *out = std::move(item.second);
    return true;
  }

  // batched pop: block for the first chunk, then drain whatever else is
  // already buffered (never waiting on the producer) up to `cap` — one
  // consumer crossing amortizes over everything the ring had ready.
  // Returns the number popped; 0 = end of data (sentinel stays queued).
  int64_t PopMany(std::vector<std::vector<char>> *out, int64_t cap) {
    out->clear();
    std::unique_lock<std::mutex> lk(mu_);
    cv_data_.wait(lk, [this] { return !queue_.empty(); });
    while (!queue_.empty() && static_cast<int64_t>(out->size()) < cap) {
      auto &item = queue_.front();
      if (!item.first) break;  // sentinel: stays queued for the next call
      out->push_back(std::move(item.second));
      queue_.pop_front();
      cv_space_.notify_one();
    }
    return static_cast<int64_t>(out->size());
  }

 private:
  std::thread producer_;
  std::mutex mu_;
  std::condition_variable cv_data_, cv_space_;
  std::deque<std::pair<bool, std::vector<char>>> queue_;
  size_t capacity_ = 2;
  bool stop_ = false;
};

class LineSplitEngine {
 public:
  LineSplitEngine(std::vector<FileEnt> files, int64_t buffer_size,
                  Format format = kLine,
                  dmlc_tpu_read_at_fn read_cb = nullptr, void *ctx = nullptr,
                  const char *cache_path = nullptr, int64_t ring = 2)
      : files_(std::move(files)), buffer_size_(buffer_size), format_(format) {
    queue_.SetCapacity(ring);
    offsets_.push_back(0);
    for (auto &f : files_) offsets_.push_back(offsets_.back() + f.size);
    src_ = MakeSource(files_, read_cb, ctx);
    if (cache_path != nullptr && cache_path[0] != '\0') {
      cache_fo_ = std::fopen(cache_path, "wb");
      if (!cache_fo_) {
        // sticky: ClearError() on reset must not swallow it — an unusable
        // cache invalidates the whole cached-split construction
        sticky_error_ = std::string("cannot create cache ") + cache_path;
        Fail(sticky_error_);
      }
    }
  }

  ~LineSplitEngine() {
    queue_.Stop();
    if (cache_fo_) std::fclose(cache_fo_);
  }

  int64_t TotalSize() const { return offsets_.back(); }
  std::string Error() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return error_;
  }

  void ResetPartition(int64_t part, int64_t nparts) {
    queue_.Stop();
    ClearError();  // a past transient failure must not poison future resets
    src_->Invalidate();
    if (!DoResetPartition(part, nparts)) {
      // empty partition or failure: queue the end sentinel so PopChunk
      // never blocks waiting on a producer that was never started
      queue_.PushEnd();
      return;
    }
    queue_.Start([this](std::vector<char> *c) {
      bool ok = NextChunk(c);
      if (ok && cache_fo_) WriteCacheFrame(*c);
      return ok;
    });
  }

  bool DoResetPartition(int64_t part, int64_t nparts) {
    int64_t ntotal = offsets_.back();
    int64_t nstep = (ntotal + nparts - 1) / nparts;
    int64_t align = format_ == kRecordIO ? 4 : 1;
    nstep = (nstep + align - 1) / align * align;
    begin_ = std::min(nstep * part, ntotal);
    end_ = std::min(nstep * (part + 1), ntotal);
    overflow_.clear();
    if (begin_ >= end_) { curr_ = begin_; return false; }
    // realign the end edge to the next record head inside its file
    size_t fend = UpperBound(end_);
    if (end_ != offsets_[fend]) {
      end_ += SeekRecordBegin(fend, end_ - offsets_[fend]);
      if (failed()) return false;
    }
    // realign the begin edge likewise
    size_t fbegin = UpperBound(begin_);
    if (begin_ != offsets_[fbegin]) {
      begin_ += SeekRecordBegin(fbegin, begin_ - offsets_[fbegin]);
      if (failed()) return false;
    }
    BeforeFirst();
    return !failed();
  }

  void BeforeFirst() {
    curr_ = begin_;
    overflow_.clear();
  }

  // drain the remaining chunks (tee keeps writing them to the cache), then
  // flush+close the cache file — the native half of the reference's
  // cached-split preproc finish (cached_input_split.h:63-86)
  bool FinishCache() {
    std::vector<char> sink;
    while (queue_.Pop(&sink)) {
    }
    if (cache_fo_) {
      if (std::fclose(cache_fo_) != 0) Fail("cache flush failed");
      cache_fo_ = nullptr;
    }
    return !failed();
  }

  // next chunk of whole records into out; false at partition end
  bool NextChunk(std::vector<char> *out) {
    int64_t size = buffer_size_.load(std::memory_order_relaxed);
    while (true) {
      if (!ReadChunk(size, out)) return false;
      if (!out->empty()) return true;
      size *= 2;  // record larger than the buffer: grow and retry
    }
  }

  // grow the typical chunk size without disturbing the read position
  // (consumed by the prefetch thread at its next NextChunk)
  void HintChunkSize(int64_t size) {
    int64_t cur = buffer_size_.load(std::memory_order_relaxed);
    while (size > cur &&
           !buffer_size_.compare_exchange_weak(cur, size)) {
    }
  }

  // pops the next prefetched chunk; false at end
  bool PopChunk(std::vector<char> *out) { return queue_.Pop(out); }

  // pops up to `cap` buffered chunks in one call (see PrefetchQueue::PopMany)
  int64_t PopChunks(std::vector<std::vector<char>> *out, int64_t cap) {
    return queue_.PopMany(out, cap);
  }

  // error_ is written by the prefetch thread (Fail in Read/OpenFile) and
  // read by the consumer thread — guard it with its own mutex so a torn
  // string read can't happen
  bool failed() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return !error_.empty();
  }

  void ClearError() {
    std::lock_guard<std::mutex> lk(err_mu_);
    error_ = sticky_error_;  // construction-time failures survive resets
  }

 private:
  void Fail(const std::string &msg) {
    std::lock_guard<std::mutex> lk(err_mu_);
    if (error_.empty()) error_ = msg;
  }

  size_t UpperBound(int64_t offset) const {
    // index of the file containing byte `offset` of the concatenation
    auto it = std::upper_bound(offsets_.begin(), offsets_.end(), offset);
    return static_cast<size_t>(it - offsets_.begin()) - 1;
  }

  // fill `size` bytes of file idx at `offset` (looping over short reads);
  // returns bytes filled — short only at file EOF, <0 already Fail()ed
  int64_t FillAt(size_t idx, int64_t offset, char *buf, int64_t size) {
    int64_t got = 0;
    while (got < size) {
      int64_t n = src_->ReadAt(idx, offset + got, buf + got, size - got);
      if (n < 0) { Fail(src_->LastError()); return -1; }
      if (n == 0) break;
      got += n;
    }
    return got;
  }

  void WriteCacheFrame(const std::vector<char> &chunk) {
    unsigned char hdr[8];
    EncodeU64LE(static_cast<uint64_t>(chunk.size()), hdr);
    if (std::fwrite(hdr, 1, 8, cache_fo_) != 8 ||
        std::fwrite(chunk.data(), 1, chunk.size(), cache_fo_) !=
            chunk.size()) {
      Fail("cache write failed");
    }
  }

  // bytes to skip from (idx, local offset) to the next record head; the
  // scan stays within file idx (reference realigns per file)
  int64_t SeekRecordBegin(size_t idx, int64_t local) {
    return format_ == kRecordIO ? SeekRecordBeginRecordIO(idx, local)
                                : SeekRecordBeginLine(idx, local);
  }

  // (reference line_split.cc:9-26: to first EOL, then past the EOL run)
  int64_t SeekRecordBeginLine(size_t idx, int64_t local) {
    int64_t consumed = 0;  // bytes pulled from the source so far
    int64_t nstep = 0;
    bool seen_eol = false;
    char block[4096];
    while (true) {
      int64_t n = FillAt(idx, local + consumed, block, sizeof(block));
      if (n <= 0) return nstep;
      consumed += n;
      for (int64_t i = 0; i < n; ++i) {
        unsigned char c = static_cast<unsigned char>(block[i]);
        if (!seen_eol) {
          ++nstep;
          if (IsEol(c)) seen_eol = true;
        } else if (IsEol(c)) {
          ++nstep;
        } else {
          return nstep;
        }
      }
    }
  }

  // word-scan for magic followed by cflag 0/1 (reference
  // recordio_split.cc:9-26; mirrors RecordIOSplitter.seek_record_begin in
  // io/input_split.py — incl. consuming the word after a failed flag test)
  int64_t SeekRecordBeginRecordIO(size_t idx, int64_t local) {
    int64_t consumed = 0;  // bytes pulled from the source so far
    int64_t nstep = 0;
    bool saw_magic = false;
    char block[4096];
    while (true) {
      int64_t n = FillAt(idx, local + consumed, block, sizeof(block));
      if (n < 4) return nstep;
      consumed += n;
      int64_t nwords = n / 4;
      for (int64_t i = 0; i < nwords; ++i) {
        uint32_t w;
        std::memcpy(&w, block + i * 4, 4);
        nstep += 4;
        if (saw_magic) {
          saw_magic = false;
          uint32_t flag = CFlag(w);
          if (flag == 0 || flag == 1) return nstep - 8;
        } else if (w == kRecordIOMagic) {
          saw_magic = true;
        }
      }
      if (n != nwords * 4) return nstep;  // sub-word tail: end of data
    }
  }

  // offset of the last record head in [data, data+n) (0 if none beyond start)
  int64_t FindLastRecordBegin(const char *data, int64_t n) const {
    if (format_ == kRecordIO) {
      int64_t nwords = n / 4;
      for (int64_t i = nwords - 2; i > 0; --i) {
        uint32_t w, next;
        std::memcpy(&w, data + i * 4, 4);
        if (w != kRecordIOMagic) continue;
        std::memcpy(&next, data + (i + 1) * 4, 4);
        uint32_t flag = CFlag(next);
        if (flag == 0 || flag == 1) return i * 4;
      }
      return 0;
    }
    for (int64_t i = n - 1; i > 0; --i) {
      if (IsEol(static_cast<unsigned char>(data[i]))) return i + 1;
    }
    return 0;
  }

  // read up to `size` partition bytes, crossing file boundaries
  int64_t Read(char *buf, int64_t size) {
    if (begin_ >= end_ || curr_ >= end_) return 0;
    size = std::min(size, end_ - curr_);
    int64_t got_total = 0;
    while (got_total < size) {
      size_t idx = UpperBound(curr_);
      if (idx >= files_.size()) break;
      int64_t local = curr_ - offsets_[idx];
      int64_t avail = std::min(size - got_total, files_[idx].size - local);
      int64_t got = FillAt(idx, local, buf + got_total, avail);
      if (got < 0) return got_total;
      if (got < avail) {
        Fail("file shorter than its size table entry: " + files_[idx].path);
        return got_total + got;
      }
      got_total += got;
      curr_ += got;
    }
    return got_total;
  }

  // one chunk ending at a record boundary; false at partition end,
  // empty chunk when max_size cannot hold one record (caller grows)
  bool ReadChunk(int64_t max_size, std::vector<char> *out) {
    out->clear();
    if (max_size <= static_cast<int64_t>(overflow_.size())) return true;
    out->swap(overflow_);
    overflow_.clear();
    int64_t head = static_cast<int64_t>(out->size());
    out->resize(static_cast<size_t>(max_size));
    int64_t got = Read(out->data() + head, max_size - head);
    int64_t total = head + got;
    if (total == 0) { out->clear(); return false; }
    out->resize(static_cast<size_t>(total));
    if (total != max_size) return true;  // partition tail at realigned edge
    int64_t cut = FindLastRecordBegin(out->data(), total);
    overflow_.assign(out->begin() + cut, out->end());
    out->resize(static_cast<size_t>(cut));
    return true;
  }

  std::vector<FileEnt> files_;
  std::vector<int64_t> offsets_;
  std::atomic<int64_t> buffer_size_;
  Format format_;
  std::unique_ptr<ByteSource> src_;
  std::FILE *cache_fo_ = nullptr;
  int64_t begin_ = 0, end_ = 0, curr_ = 0;
  std::vector<char> overflow_;
  mutable std::mutex err_mu_;
  std::string error_;
  std::string sticky_error_;  // set at construction only (cache open)
  PrefetchQueue queue_;
};

// Replays a (u64-LE length, bytes)-framed cache file with read-ahead — the
// epoch-N half of the reference's CachedInputSplit (cached_input_split.h:
// 166-189); frame format shared with the Python cache writer.
class CacheReplayEngine {
 public:
  explicit CacheReplayEngine(std::string path) : path_(std::move(path)) {
    Reset();
  }

  ~CacheReplayEngine() {
    queue_.Stop();
    if (fp_) std::fclose(fp_);
  }

  void Reset() {
    queue_.Stop();
    {
      std::lock_guard<std::mutex> lk(err_mu_);
      error_.clear();
    }
    if (fp_) {
      std::fclose(fp_);
      fp_ = nullptr;
    }
    fp_ = std::fopen(path_.c_str(), "rb");
    if (!fp_) {
      Fail("cannot open cache " + path_);
      queue_.PushEnd();
      return;
    }
    // remaining-bytes bound for frame-length validation: a corrupt header
    // must fail cleanly, not feed a garbage u64 into vector::resize
    remaining_ = FileSize64(fp_);
    queue_.Start([this](std::vector<char> *c) { return NextFrame(c); });
  }

  bool PopChunk(std::vector<char> *out) { return queue_.Pop(out); }

  std::string Error() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return error_;
  }
  bool failed() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return !error_.empty();
  }

 private:
  bool NextFrame(std::vector<char> *out) {
    unsigned char hdr[8];
    size_t n = std::fread(hdr, 1, 8, fp_);
    if (n < 8) {
      // n == 0 is clean end-of-cache ONLY if it is a real EOF: an I/O
      // error landing exactly on a frame boundary must fail loudly, not
      // silently truncate the epoch (ADVICE r4)
      if (n != 0) Fail("truncated cache frame header");
      else if (std::ferror(fp_)) Fail("cache read error in " + path_);
      return false;
    }
    remaining_ -= 8;
    uint64_t len = DecodeU64LE(hdr);
    if (len > static_cast<uint64_t>(remaining_)) {
      Fail("corrupt cache file (frame length exceeds file size)");
      return false;
    }
    out->resize(static_cast<size_t>(len));
    if (std::fread(out->data(), 1, out->size(), fp_) != out->size()) {
      Fail("corrupt cache file (truncated frame)");
      return false;
    }
    remaining_ -= static_cast<int64_t>(len);
    return !out->empty();  // writers never emit empty frames
  }

  void Fail(const std::string &msg) {
    std::lock_guard<std::mutex> lk(err_mu_);
    if (error_.empty()) error_ = msg;
  }

  std::string path_;
  std::FILE *fp_ = nullptr;
  int64_t remaining_ = 0;  // bytes left in the file (producer thread only)
  mutable std::mutex err_mu_;
  std::string error_;
  PrefetchQueue queue_;
};

// Index-driven batch reads with prefetch (reference
// src/io/indexed_recordio_split.cc:43-227 byte path).  Policy — index
// partitioning, batch grouping, the seeded shuffle permutation — stays in
// Python (io/input_split.py IndexedRecordIOSplitter); this engine executes a
// per-epoch *plan*: a flat list of (offset, size) spans in the concatenated
// file space plus per-batch span counts, each batch concatenated into one
// chunk and read ahead by a producer thread.
class SpanReadEngine {
 public:
  explicit SpanReadEngine(std::vector<FileEnt> files,
                          dmlc_tpu_read_at_fn read_cb = nullptr,
                          void *ctx = nullptr)
      : files_(std::move(files)) {
    offsets_.push_back(0);
    for (auto &f : files_) offsets_.push_back(offsets_.back() + f.size);
    src_ = MakeSource(files_, read_cb, ctx);
  }

  ~SpanReadEngine() { queue_.Stop(); }

  std::string Error() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return error_;
  }
  bool failed() const {
    std::lock_guard<std::mutex> lk(err_mu_);
    return !error_.empty();
  }

  void SetPlan(const int64_t *offs, const int64_t *sizes,
               const int64_t *counts, int64_t nspans, int64_t nbatches) {
    queue_.Stop();
    src_->Invalidate();  // a new epoch must observe replaced files
    {
      std::lock_guard<std::mutex> lk(err_mu_);
      error_.clear();
    }
    spans_.assign(nspans, {});
    for (int64_t i = 0; i < nspans; ++i) spans_[i] = {offs[i], sizes[i]};
    counts_.assign(counts, counts + nbatches);
    next_batch_ = 0;
    next_span_ = 0;
    if (nbatches == 0) {
      queue_.PushEnd();   // empty plan: Pop never blocks on a producer
      return;
    }
    queue_.Start([this](std::vector<char> *c) { return NextBatch(c); });
  }

  bool PopChunk(std::vector<char> *out) { return queue_.Pop(out); }

 private:
  bool NextBatch(std::vector<char> *out) {
    out->clear();
    if (next_batch_ >= static_cast<int64_t>(counts_.size())) return false;
    int64_t nspan = counts_[next_batch_++];
    for (int64_t k = 0; k < nspan; ++k) {
      if (next_span_ >= static_cast<int64_t>(spans_.size())) {
        Fail("span plan shorter than batch counts");
        return false;
      }
      auto span = spans_[next_span_++];
      if (!ReadSpan(span.first, span.second, out)) return false;
    }
    // real plans have >=1 record of >=8 bytes per batch; an empty batch is
    // treated as end-of-plan, matching the Python path's `data or None`
    return !out->empty();
  }

  // read [offset, offset+size) of the concatenation, crossing file bounds
  bool ReadSpan(int64_t offset, int64_t size, std::vector<char> *out) {
    size_t head = out->size();
    out->resize(head + static_cast<size_t>(size));
    char *dst = out->data() + head;
    while (size > 0) {
      size_t idx = UpperBound(offset);
      if (idx >= files_.size()) { Fail("span beyond input"); return false; }
      int64_t local = offset - offsets_[idx];
      int64_t avail = std::min(size, files_[idx].size - local);
      int64_t got = 0;
      while (got < avail) {
        int64_t n = src_->ReadAt(idx, local + got, dst + got, avail - got);
        if (n < 0) { Fail(src_->LastError()); return false; }
        if (n == 0) { Fail("short read in " + files_[idx].path); return false; }
        got += n;
      }
      dst += got;
      offset += got;
      size -= got;
    }
    return true;
  }

  size_t UpperBound(int64_t offset) const {
    auto it = std::upper_bound(offsets_.begin(), offsets_.end(), offset);
    return static_cast<size_t>(it - offsets_.begin()) - 1;
  }

  void Fail(const std::string &msg) {
    std::lock_guard<std::mutex> lk(err_mu_);
    if (error_.empty()) error_ = msg;
  }

  std::vector<FileEnt> files_;
  std::vector<int64_t> offsets_;
  std::vector<std::pair<int64_t, int64_t>> spans_;
  std::vector<int64_t> counts_;
  int64_t next_batch_ = 0, next_span_ = 0;
  std::unique_ptr<ByteSource> src_;
  mutable std::mutex err_mu_;
  std::string error_;
  PrefetchQueue queue_;
};

struct SplitHandle {
  LineSplitEngine *engine = nullptr;
  std::vector<char> current;  // chunk handed to Python, valid until next call
  // batched-pop storage: every chunk of the last next_chunks stays valid
  // until the NEXT next_chunk/next_chunks call, so the Python side can
  // hand out views one at a time without re-crossing
  std::vector<std::vector<char>> batch;
  std::string error;
};

struct SpanHandle {
  SpanReadEngine *engine = nullptr;
  std::vector<char> current;
  std::string error;
};

struct ReplayHandle {
  CacheReplayEngine *engine = nullptr;
  std::vector<char> current;
  std::string error;
};

std::vector<FileEnt> DecodeFiles(const char *paths, const int64_t *path_lens,
                                 const int64_t *sizes, int64_t nfiles) {
  std::vector<FileEnt> files;
  const char *p = paths;
  for (int64_t i = 0; i < nfiles; ++i) {
    files.push_back({std::string(p, static_cast<size_t>(path_lens[i])),
                     sizes[i]});
    p += path_lens[i];
  }
  return files;
}

}  // namespace

extern "C" {

// paths: concatenated path bytes with per-path byte lengths in path_lens
// (length-delimited, so any legal filename byte — incl. '\n' — is safe);
// sizes: per-file byte sizes.  format: 0 = line, 1 = recordio.
// ring: prefetch-queue depth (2 = the classic double buffer; deeper rings
// feed the batched next_chunks pop on the remote callback path).
// read_cb/ctx: non-null routes ALL byte reads through the callback (remote
// filesystems); cache_path: non-empty tees epoch-1 chunks into a cache file
// (finish with dmlc_tpu_lsplit_finish_cache, replay with creplay_*).
void *dmlc_tpu_lsplit_open2(const char *paths, const int64_t *path_lens,
                            const int64_t *sizes, int64_t nfiles,
                            int64_t part, int64_t nparts,
                            int64_t buffer_size, int64_t format,
                            int64_t ring, const char *cache_path,
                            dmlc_tpu_read_at_fn read_cb, void *ctx) {
  auto *h = new SplitHandle();
  h->engine = new LineSplitEngine(
      DecodeFiles(paths, path_lens, sizes, nfiles), buffer_size,
      format == 1 ? kRecordIO : kLine, read_cb, ctx, cache_path, ring);
  h->engine->ResetPartition(part, nparts);
  if (h->engine->failed()) h->error = h->engine->Error();
  return h;
}

void *dmlc_tpu_lsplit_open(const char *paths, const int64_t *path_lens,
                           const int64_t *sizes, int64_t nfiles,
                           int64_t part, int64_t nparts,
                           int64_t buffer_size) {
  return dmlc_tpu_lsplit_open2(paths, path_lens, sizes, nfiles, part, nparts,
                               buffer_size, 0, 2, nullptr, nullptr, nullptr);
}

// RecordIO variant: same handle/call surface as lsplit_* (hint/total/reset/
// next_chunk/error/close all apply), only the record format differs
void *dmlc_tpu_rsplit_open(const char *paths, const int64_t *path_lens,
                           const int64_t *sizes, int64_t nfiles,
                           int64_t part, int64_t nparts,
                           int64_t buffer_size) {
  return dmlc_tpu_lsplit_open2(paths, path_lens, sizes, nfiles, part, nparts,
                               buffer_size, 1, 2, nullptr, nullptr, nullptr);
}

// drain the remaining partition through the cache tee and close the cache
// file; 0 on success, -1 on error (then lsplit_error has the message)
int64_t dmlc_tpu_lsplit_finish_cache(void *handle) {
  auto *h = static_cast<SplitHandle *>(handle);
  if (!h->engine->FinishCache()) {
    h->error = h->engine->Error();
    return -1;
  }
  return 0;
}

// ---- cache replay (epoch N of the cached split) ----------------------------

void *dmlc_tpu_creplay_open(const char *path) {
  auto *h = new ReplayHandle();
  h->engine = new CacheReplayEngine(path);
  if (h->engine->failed()) h->error = h->engine->Error();
  return h;
}

void dmlc_tpu_creplay_reset(void *handle) {
  auto *h = static_cast<ReplayHandle *>(handle);
  h->error.clear();
  h->engine->Reset();
  if (h->engine->failed()) h->error = h->engine->Error();
}

// returns chunk length (>0), 0 at cache end, -1 on error
int64_t dmlc_tpu_creplay_next_chunk(void *handle, const char **ptr) {
  auto *h = static_cast<ReplayHandle *>(handle);
  if (!h->error.empty()) return -1;
  if (!h->engine->PopChunk(&h->current)) {
    if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
    return 0;
  }
  if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
  *ptr = h->current.data();
  return static_cast<int64_t>(h->current.size());
}

const char *dmlc_tpu_creplay_error(void *handle) {
  return static_cast<ReplayHandle *>(handle)->error.c_str();
}

void dmlc_tpu_creplay_close(void *handle) {
  auto *h = static_cast<ReplayHandle *>(handle);
  delete h->engine;
  delete h;
}

// ---- index-driven span reader (indexed recordio batches) -------------------

void *dmlc_tpu_span_open2(const char *paths, const int64_t *path_lens,
                          const int64_t *sizes, int64_t nfiles,
                          dmlc_tpu_read_at_fn read_cb, void *ctx) {
  auto *h = new SpanHandle();
  h->engine = new SpanReadEngine(DecodeFiles(paths, path_lens, sizes, nfiles),
                                 read_cb, ctx);
  return h;
}

void *dmlc_tpu_span_open(const char *paths, const int64_t *path_lens,
                         const int64_t *sizes, int64_t nfiles) {
  return dmlc_tpu_span_open2(paths, path_lens, sizes, nfiles, nullptr,
                             nullptr);
}

void dmlc_tpu_span_set_plan(void *handle, const int64_t *offs,
                            const int64_t *sizes, const int64_t *counts,
                            int64_t nspans, int64_t nbatches) {
  auto *h = static_cast<SpanHandle *>(handle);
  h->error.clear();
  h->engine->SetPlan(offs, sizes, counts, nspans, nbatches);
}

// returns chunk length (>0), 0 at plan end, -1 on error
int64_t dmlc_tpu_span_next_chunk(void *handle, const char **ptr) {
  auto *h = static_cast<SpanHandle *>(handle);
  if (!h->error.empty()) return -1;
  if (!h->engine->PopChunk(&h->current)) {
    if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
    return 0;
  }
  if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
  *ptr = h->current.data();
  return static_cast<int64_t>(h->current.size());
}

const char *dmlc_tpu_span_error(void *handle) {
  return static_cast<SpanHandle *>(handle)->error.c_str();
}

void dmlc_tpu_span_close(void *handle) {
  auto *h = static_cast<SpanHandle *>(handle);
  delete h->engine;
  delete h;
}

void dmlc_tpu_lsplit_hint(void *handle, int64_t chunk_size) {
  static_cast<SplitHandle *>(handle)->engine->HintChunkSize(chunk_size);
}

int64_t dmlc_tpu_lsplit_total(void *handle) {
  return static_cast<SplitHandle *>(handle)->engine->TotalSize();
}

void dmlc_tpu_lsplit_reset(void *handle, int64_t part, int64_t nparts) {
  auto *h = static_cast<SplitHandle *>(handle);
  h->error.clear();  // a reset retries cleanly after a transient failure
  h->engine->ResetPartition(part, nparts);
  if (h->engine->failed()) h->error = h->engine->Error();
}

// returns chunk length (>0), 0 at partition end, -1 on error;
// *ptr stays valid until the next call on this handle
int64_t dmlc_tpu_lsplit_next_chunk(void *handle, const char **ptr) {
  auto *h = static_cast<SplitHandle *>(handle);
  if (!h->error.empty()) return -1;
  if (!h->engine->PopChunk(&h->current)) {
    if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
    return 0;
  }
  if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
  *ptr = h->current.data();
  return static_cast<int64_t>(h->current.size());
}

// batched pop: up to `cap` chunks in ONE Python->C crossing — blocks for
// the first chunk, then drains whatever the prefetch ring already buffered.
// Fills ptrs[i]/lens[i]; every view stays valid until the next
// next_chunk/next_chunks call on this handle.  Returns the count popped,
// 0 at partition end, -1 on error.
int64_t dmlc_tpu_lsplit_next_chunks(void *handle, const char **ptrs,
                                    int64_t *lens, int64_t cap) {
  auto *h = static_cast<SplitHandle *>(handle);
  if (!h->error.empty()) return -1;
  int64_t n = h->engine->PopChunks(&h->batch, cap);
  if (h->engine->failed()) { h->error = h->engine->Error(); return -1; }
  for (int64_t i = 0; i < n; ++i) {
    ptrs[i] = h->batch[static_cast<size_t>(i)].data();
    lens[i] = static_cast<int64_t>(h->batch[static_cast<size_t>(i)].size());
  }
  return n;
}

const char *dmlc_tpu_lsplit_error(void *handle) {
  return static_cast<SplitHandle *>(handle)->error.c_str();
}

void dmlc_tpu_lsplit_close(void *handle) {
  auto *h = static_cast<SplitHandle *>(handle);
  delete h->engine;
  delete h;
}

}  // extern "C"
