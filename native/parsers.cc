// Native hot-path parsers for dmlc_core_tpu.
//
// The reference keeps its byte-level machinery in C++ (src/data/*.h,
// src/data/strtonum.h); this library is the TPU-native rebuild's equivalent:
// multi-threaded chunk -> CSR parsing for libsvm/libfm and chunk -> dense for
// csv, exposed through a plain C ABI consumed via ctypes (no pybind11 in the
// image). Number parsing uses std::from_chars (C++17), which matches or beats
// the reference's hand-rolled strtof (src/data/strtonum.h:37-101).
//
// Threading model mirrors the reference's OpenMP chunk split
// (src/data/text_parser.h:89-118): the chunk is cut into nthread sub-ranges
// realigned at newlines; each worker parses into private vectors; the results
// are stitched in order.

#include <atomic>
#include <charconv>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Shard {
  std::vector<int64_t> row_nnz;
  std::vector<float> label;
  std::vector<float> weight;      // empty unless any weight seen
  std::vector<uint32_t> index;
  std::vector<uint32_t> field;    // libfm only
  std::vector<float> value;       // may stay empty for implicit 1.0 (libsvm)
  bool any_weight = false;
  bool any_value = false;
  bool error = false;
  std::string error_msg;
};

struct Result {
  std::vector<int64_t> offset;
  std::vector<float> label;
  std::vector<float> weight;
  std::vector<uint32_t> index;
  std::vector<uint32_t> field;
  std::vector<float> value;
  // csv
  std::vector<float> dense;
  int64_t n_cols = 0;
  bool is_dense = false;
  bool has_weight = false;
  bool has_value = false;
  bool has_field = false;
  std::string error_msg;
};

inline bool is_ws(char c) { return c == ' ' || c == '\t' || c == '\r'; }

inline const char* skip_ws(const char* p, const char* end) {
  while (p != end && is_ws(*p)) ++p;
  return p;
}

inline bool parse_float(const char*& p, const char* end, float* out) {
  auto res = std::from_chars(p, end, *out);
  if (res.ec != std::errc()) return false;
  p = res.ptr;
  return true;
}

inline bool parse_u32(const char*& p, const char* end, uint32_t* out) {
  auto res = std::from_chars(p, end, *out);
  if (res.ec != std::errc()) return false;
  p = res.ptr;
  return true;
}

// Split [begin, end) into n ranges ending at newlines (reference
// text_parser.h FillData realignment).
std::vector<std::pair<const char*, const char*>> split_ranges(
    const char* begin, const char* end, int n) {
  std::vector<std::pair<const char*, const char*>> out;
  int64_t total = end - begin;
  if (total <= 0) return out;
  int64_t step = (total + n - 1) / n;
  const char* cur = begin;
  while (cur < end) {
    const char* stop = cur + step < end ? cur + step : end;
    if (stop < end) {
      const char* nl = static_cast<const char*>(
          memchr(stop, '\n', end - stop));
      stop = nl ? nl + 1 : end;
    }
    out.emplace_back(cur, stop);
    cur = stop;
  }
  return out;
}

// ---------------------------------------------------------------- libsvm ----
// Grammar per line: label[:weight] (idx[:val])*   (reference
// src/data/libsvm_parser.h:35-90). Empty lines skipped.
void parse_libsvm_range(const char* begin, const char* end, Shard* s) {
  const char* p = begin;
  while (p < end) {
    const char* lend = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!lend) lend = end;
    p = skip_ws(p, lend);
    if (p < lend) {
      float label;
      if (!parse_float(p, lend, &label)) {
        s->error = true;
        s->error_msg = "invalid label in libsvm input";
        return;
      }
      float w = 1.0f;
      bool has_w = false;
      if (p < lend && *p == ':') {
        ++p;
        if (!parse_float(p, lend, &w)) {
          s->error = true;
          s->error_msg = "invalid weight in libsvm input";
          return;
        }
        has_w = true;
      }
      int64_t nnz = 0;
      while (true) {
        p = skip_ws(p, lend);
        if (p >= lend) break;
        uint32_t idx;
        if (!parse_u32(p, lend, &idx)) {
          s->error = true;
          s->error_msg = "invalid feature index in libsvm input";
          return;
        }
        float v = 1.0f;
        if (p < lend && *p == ':') {
          ++p;
          if (!parse_float(p, lend, &v)) {
            s->error = true;
            s->error_msg = "invalid feature value in libsvm input";
            return;
          }
          s->any_value = true;
        }
        s->index.push_back(idx);
        s->value.push_back(v);
        ++nnz;
      }
      s->label.push_back(label);
      s->weight.push_back(w);
      if (has_w) s->any_weight = true;
      s->row_nnz.push_back(nnz);
    }
    p = lend < end ? lend + 1 : end;
  }
}

// ---------------------------------------------------------------- libfm -----
// Grammar per line: label[:weight] (field:idx:val)*  (reference
// src/data/libfm_parser.h).
void parse_libfm_range(const char* begin, const char* end, Shard* s) {
  const char* p = begin;
  while (p < end) {
    const char* lend = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!lend) lend = end;
    p = skip_ws(p, lend);
    if (p < lend) {
      float label;
      if (!parse_float(p, lend, &label)) {
        s->error = true;
        s->error_msg = "invalid label in libfm input";
        return;
      }
      float w = 1.0f;
      bool has_w = false;
      if (p < lend && *p == ':') {
        ++p;
        if (!parse_float(p, lend, &w)) {
          s->error = true;
          s->error_msg = "invalid weight in libfm input";
          return;
        }
        has_w = true;
      }
      int64_t nnz = 0;
      while (true) {
        p = skip_ws(p, lend);
        if (p >= lend) break;
        uint32_t fld, idx;
        float v;
        if (!parse_u32(p, lend, &fld) || p >= lend || *p != ':') {
          s->error = true;
          s->error_msg = "libfm features must be field:index:value triples";
          return;
        }
        ++p;
        if (!parse_u32(p, lend, &idx) || p >= lend || *p != ':') {
          s->error = true;
          s->error_msg = "libfm features must be field:index:value triples";
          return;
        }
        ++p;
        if (!parse_float(p, lend, &v)) {
          s->error = true;
          s->error_msg = "invalid feature value in libfm input";
          return;
        }
        s->field.push_back(fld);
        s->index.push_back(idx);
        s->value.push_back(v);
        ++nnz;
      }
      s->label.push_back(label);
      s->weight.push_back(w);
      if (has_w) s->any_weight = true;
      s->row_nnz.push_back(nnz);
    }
    p = lend < end ? lend + 1 : end;
  }
}

// ------------------------------------------------------------------- csv ----
// Dense comma-separated floats (reference src/data/csv_parser.h:64-99); the
// label column is extracted on the Python side (cheap numpy slice).
struct CsvShard {
  std::vector<float> dense;
  int64_t n_rows = 0;
  int64_t n_cols = -1;
  bool error = false;
  std::string error_msg;
};

void parse_csv_range(const char* begin, const char* end, CsvShard* s,
                     float missing) {
  const char* p = begin;
  while (p < end) {
    const char* lend = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!lend) lend = end;
    const char* q = skip_ws(p, lend);
    if (q < lend) {
      int64_t cols = 0;
      while (true) {
        q = skip_ws(q, lend);
        float v;
        if (q == lend || *q == ',') {
          // empty cell: the reference's strtof parses it as 0.0 silently
          // (src/data/csv_parser.h:83); we take the configured missing
          // value (0.0 default = reference parity, NaN for sparsity-aware
          // training).  A trailing comma counts as a trailing empty cell.
          v = missing;
        } else if (!parse_float(q, lend, &v)) {
          s->error = true;
          s->error_msg = "invalid CSV number";
          return;
        }
        s->dense.push_back(v);
        ++cols;
        q = skip_ws(q, lend);
        if (q < lend && *q == ',') {
          ++q;
          continue;
        }
        break;
      }
      if (s->n_cols < 0) s->n_cols = cols;
      if (cols != s->n_cols) {
        s->error = true;
        s->error_msg = "CSV rows have inconsistent column counts";
        return;
      }
      ++s->n_rows;
    }
    p = lend < end ? lend + 1 : end;
  }
}

template <typename Fn>
Result* run_parse(const char* data, int64_t len, int nthread, Fn parse_fn,
                  bool has_field_format) {
  auto* result = new Result();
  if (nthread < 1) nthread = 1;
  auto ranges = split_ranges(data, data + len, nthread);
  std::vector<Shard> shards(ranges.size());
  {
    std::vector<std::thread> workers;
    for (size_t i = 1; i < ranges.size(); ++i) {
      workers.emplace_back(parse_fn, ranges[i].first, ranges[i].second,
                           &shards[i]);
    }
    if (!ranges.empty()) {
      parse_fn(ranges[0].first, ranges[0].second, &shards[0]);
    }
    for (auto& w : workers) w.join();
  }
  bool any_weight = false, any_value = false;
  for (auto& s : shards) {
    if (s.error) {
      result->error_msg = s.error_msg;
      return result;
    }
    any_weight |= s.any_weight;
    any_value |= s.any_value || has_field_format;  // libfm always has values
  }
  result->has_weight = any_weight;
  result->has_value = any_value;
  result->has_field = has_field_format;
  result->offset.push_back(0);
  for (auto& s : shards) {
    for (int64_t nnz : s.row_nnz) {
      result->offset.push_back(result->offset.back() + nnz);
    }
    result->label.insert(result->label.end(), s.label.begin(), s.label.end());
    if (any_weight) {
      result->weight.insert(result->weight.end(), s.weight.begin(),
                            s.weight.end());
    }
    result->index.insert(result->index.end(), s.index.begin(), s.index.end());
    if (has_field_format) {
      result->field.insert(result->field.end(), s.field.begin(),
                           s.field.end());
    }
    if (any_value) {
      result->value.insert(result->value.end(), s.value.begin(),
                           s.value.end());
    }
  }
  return result;
}

}  // namespace

extern "C" {

// All handles are Result*. On error, dims() reports n_rows = -1 and
// dmlc_tpu_error_msg returns the message.

void* dmlc_tpu_parse_libsvm(const char* data, int64_t len, int nthread) {
  return run_parse(data, len, nthread, parse_libsvm_range, false);
}

void* dmlc_tpu_parse_libfm(const char* data, int64_t len, int nthread) {
  return run_parse(data, len, nthread, parse_libfm_range, true);
}

// ABI version handshake: the ctypes bridge refuses (and rebuilds) a stale
// library whose entry points don't match what it expects.  Bump on any
// signature change.
int dmlc_tpu_abi_version() { return 3; }

void* dmlc_tpu_parse_csv(const char* data, int64_t len, int nthread,
                         float missing) {
  auto* result = new Result();
  result->is_dense = true;
  if (nthread < 1) nthread = 1;
  auto ranges = split_ranges(data, data + len, nthread);
  std::vector<CsvShard> shards(ranges.size());
  {
    std::vector<std::thread> workers;
    for (size_t i = 1; i < ranges.size(); ++i) {
      workers.emplace_back(parse_csv_range, ranges[i].first, ranges[i].second,
                           &shards[i], missing);
    }
    if (!ranges.empty()) {
      parse_csv_range(ranges[0].first, ranges[0].second, &shards[0], missing);
    }
    for (auto& w : workers) w.join();
  }
  int64_t ncols = -1;
  for (auto& s : shards) {
    if (s.error) {
      result->error_msg = s.error_msg;
      return result;
    }
    if (s.n_cols >= 0) {
      if (ncols < 0) ncols = s.n_cols;
      if (s.n_cols != ncols) {
        result->error_msg = "CSV rows have inconsistent column counts";
        return result;
      }
    }
  }
  result->n_cols = ncols < 0 ? 0 : ncols;
  int64_t nrows = 0;
  for (auto& s : shards) nrows += s.n_rows;
  result->dense.reserve(nrows * result->n_cols);
  for (auto& s : shards) {
    result->dense.insert(result->dense.end(), s.dense.begin(), s.dense.end());
  }
  // reuse offset[0] to carry the row count for dims()
  result->offset.assign(1, nrows);
  return result;
}

void dmlc_tpu_result_dims(void* handle, int64_t* n_rows, int64_t* nnz,
                          int64_t* n_cols, int32_t* flags) {
  auto* r = static_cast<Result*>(handle);
  if (!r->error_msg.empty()) {
    *n_rows = -1;
    *nnz = 0;
    *n_cols = 0;
    *flags = 0;
    return;
  }
  if (r->is_dense) {
    *n_rows = r->offset.empty() ? 0 : r->offset[0];
    *nnz = static_cast<int64_t>(r->dense.size());
    *n_cols = r->n_cols;
    *flags = 8;  // dense
    return;
  }
  *n_rows = static_cast<int64_t>(r->offset.size()) - 1;
  *nnz = static_cast<int64_t>(r->index.size());
  *n_cols = 0;
  *flags = (r->has_weight ? 1 : 0) | (r->has_value ? 2 : 0) |
           (r->has_field ? 4 : 0);
}

const char* dmlc_tpu_error_msg(void* handle) {
  return static_cast<Result*>(handle)->error_msg.c_str();
}

void dmlc_tpu_result_fill(void* handle, int64_t* offset, float* label,
                          float* weight, uint32_t* index, uint32_t* field,
                          float* value, float* dense) {
  auto* r = static_cast<Result*>(handle);
  if (dense && !r->dense.empty()) {
    memcpy(dense, r->dense.data(), r->dense.size() * sizeof(float));
    return;
  }
  if (offset && !r->offset.empty()) {
    memcpy(offset, r->offset.data(), r->offset.size() * sizeof(int64_t));
  }
  if (label && !r->label.empty()) {
    memcpy(label, r->label.data(), r->label.size() * sizeof(float));
  }
  if (weight && !r->weight.empty()) {
    memcpy(weight, r->weight.data(), r->weight.size() * sizeof(float));
  }
  if (index && !r->index.empty()) {
    memcpy(index, r->index.data(), r->index.size() * sizeof(uint32_t));
  }
  if (field && !r->field.empty()) {
    memcpy(field, r->field.data(), r->field.size() * sizeof(uint32_t));
  }
  if (value && !r->value.empty()) {
    memcpy(value, r->value.data(), r->value.size() * sizeof(float));
  }
}

void dmlc_tpu_result_free(void* handle) {
  delete static_cast<Result*>(handle);
}

// ------------------------------------------------------------- recordio -----
// 4-byte-aligned magic-cell scan used by the RecordIO writer's escape path
// (reference src/recordio.cc:22-38): writes found positions (byte offsets)
// into out (capacity out_cap); returns the count found.
int64_t dmlc_tpu_find_magic(const char* data, int64_t len, uint32_t magic,
                            int64_t* out, int64_t out_cap) {
  int64_t found = 0;
  const int64_t nwords = len / 4;
  for (int64_t i = 0; i < nwords; ++i) {
    uint32_t w;
    memcpy(&w, data + i * 4, 4);
    if (w == magic) {
      if (found < out_cap) out[found] = i * 4;
      ++found;
    }
  }
  return found;
}

}  // extern "C"
