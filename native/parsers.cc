// Native hot-path parsers for dmlc_core_tpu.
//
// The reference keeps its byte-level machinery in C++ (src/data/*.h,
// src/data/strtonum.h); this library is the TPU-native rebuild's equivalent:
// multi-threaded chunk -> CSR parsing for libsvm/libfm and chunk -> dense for
// csv, exposed through a plain C ABI consumed via ctypes (no pybind11 in the
// image). Number parsing uses a fast-path u64-mantissa decimal scan with a
// std::from_chars (C++17) fallback for exotic tokens; the combination beats
// the reference's hand-rolled strtof (src/data/strtonum.h:37-101).
//
// Threading model mirrors the reference's OpenMP chunk split
// (src/data/text_parser.h:89-118): the chunk is cut into nthread sub-ranges
// realigned at newlines; each worker parses into private vectors; the results
// are stitched in order.

#include <atomic>
#include <cerrno>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#if !(defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L)
#include <clocale>   // newlocale/locale_t for the strtof fallback
#if defined(__APPLE__)
#include <xlocale.h>  // strtof_l lives here on Darwin
#endif
#endif

namespace {

struct Shard {
  std::vector<int64_t> row_nnz;
  std::vector<float> label;
  std::vector<float> weight;      // empty unless any weight seen
  std::vector<uint32_t> index;
  std::vector<uint32_t> field;    // libfm only
  std::vector<float> value;       // may stay empty for implicit 1.0 (libsvm)
  bool any_weight = false;
  bool any_value = false;
  bool error = false;
  std::string error_msg;
};

struct CsvShard;  // fwd

// Parse results stay in the per-thread shards; fill() gathers straight
// from them into the caller's numpy buffers.  (They were previously
// merged into one set of vectors first — a full extra pass over
// data-sized arrays that bought nothing, since fill() copies again.)
struct Result {
  std::vector<Shard> shards;
  std::vector<CsvShard> csv_shards;
  int64_t total_rows = 0;
  int64_t total_nnz = 0;
  int64_t n_cols = 0;  // csv
  bool is_dense = false;
  bool has_weight = false;
  bool has_value = false;
  bool has_field = false;
  std::string error_msg;
};

inline bool is_ws(char c) { return c == ' ' || c == '\t' || c == '\r'; }

inline const char* skip_ws(const char* p, const char* end) {
  while (p != end && is_ws(*p)) ++p;
  return p;
}

// between-rows variant: newlines (and blank lines) are inter-row space
inline const char* skip_ws_nl(const char* p, const char* end) {
  while (p != end && (is_ws(*p) || *p == '\n')) ++p;
  return p;
}

#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
inline bool parse_float_slow(const char*& p, const char* end, float* out) {
  auto res = std::from_chars(p, end, *out);
  if (res.ec != std::errc()) return false;
  p = res.ptr;
  return true;
}
#else
// libstdc++ < 11 ships integer-only from_chars: emulate the float overload
// with strtof over a NUL-terminated copy of the token.  Semantics kept
// from_chars-shaped: no leading whitespace or '+', no hex floats (the
// copy stops at 'x'/'X', so "0x1p3" parses as 0 with p left on the 'x' —
// exactly what from_chars does), overflow fails (subnormals pass — glibc
// flags them ERANGE but they are representable).  The copy is unbounded
// via a heap fallback, so an over-long token can never be silently
// parsed as a truncated prefix.  strtof runs under a pinned "C" numeric
// locale: an embedder's setlocale(LC_NUMERIC, ...) must not fork parsing
// (a de_DE radix would stop "1.5" at the '.').
inline bool parse_float_slow(const char*& p, const char* end, float* out) {
  if (p == end || *p == '+' || is_ws(*p) || *p == '\n') return false;
  char buf[256];
  std::string big;                 // only touched for tokens >= 255 chars
  size_t n = 0;
  const char* q = p;
  for (; q != end; ++q) {
    char c = *q;
    bool tokenish = (c >= '0' && c <= '9') || c == '.' || c == '-' ||
                    c == '+' || (c >= 'a' && c <= 'z') ||
                    (c >= 'A' && c <= 'Z');
    if (!tokenish || c == 'x' || c == 'X') break;
    if (n < sizeof(buf) - 1) {
      buf[n++] = c;
    } else {
      if (big.empty()) big.assign(buf, n);
      big.push_back(c);
    }
  }
  buf[n] = '\0';
  const char* tok = big.empty() ? buf : big.c_str();
  char* stop = nullptr;
  errno = 0;
#if defined(__GLIBC__) || defined(__APPLE__) || defined(__FreeBSD__)
  static const locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  float v = strtof_l(tok, &stop, c_loc);
#else
  float v = std::strtof(tok, &stop);
#endif
  if (stop == tok) return false;
  // ERANGE: overflow (±HUGE_VALF) and total underflow (rounded to 0) are
  // from_chars out_of_range; a nonzero subnormal is representable and passes
  if (errno == ERANGE && (v == HUGE_VALF || v == -HUGE_VALF || v == 0.0f)) {
    return false;
  }
  *out = v;
  p += (stop - tok);
  return true;
}
#endif

// Powers of ten as one branchless table indexed by e10 + 22.  Positive
// powers up to 1e22 are exactly representable, so (double)mant * 10^e is a
// single correctly-rounded op there; negative powers as multiplies are ~1
// double ulp off the exact division but ~15 cycles faster, and the final
// double->float truncation swallows the difference (worst case stays 1
// float ulp vs from_chars).
constexpr double kPow10Signed[] = {
    1e-22, 1e-21, 1e-20, 1e-19, 1e-18, 1e-17, 1e-16, 1e-15, 1e-14,
    1e-13, 1e-12, 1e-11, 1e-10, 1e-9,  1e-8,  1e-7,  1e-6,  1e-5,
    1e-4,  1e-3,  1e-2,  1e-1,  1e0,   1e1,   1e2,   1e3,   1e4,
    1e5,   1e6,   1e7,   1e8,   1e9,   1e10,  1e11,  1e12,  1e13,
    1e14,  1e15,  1e16,  1e17,  1e18,  1e19,  1e20,  1e21,  1e22};

// SWAR digit-run helpers (the reference compiles -msse2 and hand-rolls its
// strtof; this is the same idea one word at a time): 8 (or 4) ASCII digits
// are validated and converted with three multiply-mask steps instead of a
// per-byte loop.
inline bool all8_digits(uint64_t x) {
  return ((x & 0xF0F0F0F0F0F0F0F0ull) |
          (((x + 0x0606060606060606ull) & 0xF0F0F0F0F0F0F0F0ull) >> 4)) ==
         0x3333333333333333ull;
}

inline uint32_t swar8_to_u32(uint64_t x) {
  x = (x & 0x0F0F0F0F0F0F0F0Full) * 2561 >> 8;
  x = (x & 0x00FF00FF00FF00FFull) * 6553601 >> 16;
  return static_cast<uint32_t>(
      (x & 0x0000FFFF0000FFFFull) * 42949672960001ull >> 32);
}

inline bool all4_digits(uint32_t x) {
  return ((x & 0xF0F0F0F0u) |
          (((x + 0x06060606u) & 0xF0F0F0F0u) >> 4)) == 0x33333333u;
}

inline uint32_t swar4_to_u32(uint32_t x) {
  x = (x & 0x0F0F0F0Fu) * 2561 >> 8;
  return (x & 0x00FF00FFu) * 6553601 >> 16;
}

// Append a digit run to *mant; returns one past the last digit consumed.
// Tuned for fraction runs, which are typically >= 4 digits ("%.4f"-ish
// writers): one 4-gulp attempt first (cheapest win), 8-gulps only while
// the run keeps going, single bytes for the tail.
inline const char* scan_digits(const char* q, const char* end,
                               uint64_t* mant) {
  if (end - q >= 4) {
    uint32_t x;
    std::memcpy(&x, q, 4);
    if (all4_digits(x)) {
      *mant = *mant * 10000u + swar4_to_u32(x);
      q += 4;
      // runs longer than 4 are rare; one cheap byte test gates the wide
      // gulps so the common "%.4f" case pays nothing extra
      if (q != end && static_cast<unsigned char>(*q - '0') < 10u) {
        while (end - q >= 8) {
          uint64_t y;
          std::memcpy(&y, q, 8);
          if (!all8_digits(y)) break;
          *mant = *mant * 100000000ull + swar8_to_u32(y);
          q += 8;
        }
      }
    }
  }
  while (q != end && static_cast<unsigned char>(*q - '0') < 10u)
    *mant = *mant * 10u + static_cast<unsigned>(*q++ - '0');
  return q;
}

// Plain per-byte run for positions where short runs dominate (integer
// parts and labels are usually 1-2 digits; a SWAR attempt there is pure
// overhead).
inline const char* scan_digits_short(const char* q, const char* end,
                                     uint64_t* mant) {
  while (q != end && static_cast<unsigned char>(*q - '0') < 10u)
    *mant = *mant * 10u + static_cast<unsigned>(*q++ - '0');
  return q;
}

// Fast decimal float: the overwhelmingly common token shape in ML text
// formats is a short fixed-point decimal ("%.4f"-ish), for which the
// general-purpose std::from_chars pays for machinery it never uses.  This
// accumulates the digits into a u64 mantissa (SWAR, 8 at a time) and
// applies one power-of-ten double multiply — within ~1 double ulp of the
// exactly-rounded value for <= 15 digits and |e10| <= 22, then one
// double->float conversion (worst case 1 float ulp from from_chars; the
// reference's own strtof, src/data/strtonum.h:37-101, carries a larger
// error of the same class).  Anything
// else (inf/nan, long mantissas, big exponents) falls back to from_chars,
// preserving its accept/reject semantics exactly.
inline bool parse_float(const char*& p, const char* end, float* out) {
  const char* q = p;
  // ~half the values in real ML data are negative, so a sign *branch* is a
  // guaranteed-mispredict tax; do it with arithmetic only
  const bool neg = (q != end && *q == '-');
  q += neg;
  uint64_t mant = 0;
  const char* d0 = q;
  q = scan_digits_short(q, end, &mant);
  int ndig = static_cast<int>(q - d0);
  int e10 = 0;
  if (q != end && *q == '.') {
    const char* f0 = ++q;
    q = scan_digits(q, end, &mant);
    e10 = -static_cast<int>(q - f0);
    ndig += static_cast<int>(q - f0);
  }
  if (ndig == 0 || ndig > 18) return parse_float_slow(p, end, out);
  if (q != end && (*q == 'e' || *q == 'E')) {
    const char* esave = q++;
    bool eneg = false;
    if (q != end && (*q == '+' || *q == '-')) eneg = *q++ == '-';
    const char* e0 = q;
    int ev = 0;
    while (q != end && static_cast<unsigned char>(*q - '0') < 10u && ev < 10000)
      ev = ev * 10 + (*q++ - '0');
    if (q == e0) {
      q = esave;  // "1e"/"1e+": from_chars ends the token before the 'e'
    } else {
      if (q != end && static_cast<unsigned char>(*q - '0') < 10u)
        return parse_float_slow(p, end, out);  // absurd exponent length
      e10 += eneg ? -ev : ev;
    }
  }
  if (static_cast<unsigned>(e10 + 22) > 44u)
    return parse_float_slow(p, end, out);
  double d = static_cast<double>(mant) * kPow10Signed[e10 + 22];
  if (d > 3.402823466e+38) return parse_float_slow(p, end, out);
  // (overflow beyond FLT_MAX defers to from_chars, which rejects it as
  // out_of_range exactly like the pre-rewrite parser; also avoids the UB
  // of an out-of-range double->float conversion)
  float fv = static_cast<float>(d);
  uint32_t fb;
  std::memcpy(&fb, &fv, 4);
  fb |= static_cast<uint32_t>(neg) << 31;  // branchless negate (fv >= 0)
  std::memcpy(&fv, &fb, 4);
  *out = fv;
  p = q;
  return true;
}

inline bool parse_u32(const char*& p, const char* end, uint32_t* out) {
  const char* q = p;
  uint64_t v = 0;
  while (q != end && static_cast<unsigned char>(*q - '0') < 10u) {
    v = v * 10u + static_cast<unsigned>(*q++ - '0');
    // value check, not digit count: zero-padded in-range indices must
    // still parse (from_chars semantics); v < 2^32 entering the step
    // keeps the u64 accumulator overflow-free
    if (v > 0xffffffffull) return false;  // like from_chars out_of_range
  }
  if (q == p) return false;
  *out = static_cast<uint32_t>(v);
  p = q;
  return true;
}

// Split [begin, end) into n ranges ending at newlines (reference
// text_parser.h FillData realignment).
std::vector<std::pair<const char*, const char*>> split_ranges(
    const char* begin, const char* end, int n) {
  std::vector<std::pair<const char*, const char*>> out;
  int64_t total = end - begin;
  if (total <= 0) return out;
  int64_t step = (total + n - 1) / n;
  const char* cur = begin;
  while (cur < end) {
    const char* stop = cur + step < end ? cur + step : end;
    if (stop < end) {
      const char* nl = static_cast<const char*>(
          memchr(stop, '\n', end - stop));
      stop = nl ? nl + 1 : end;
    }
    out.emplace_back(cur, stop);
    cur = stop;
  }
  return out;
}

// ---------------------------------------------------------------- libsvm ----
// Grammar per line: label[:weight] (idx[:val])*   (reference
// src/data/libsvm_parser.h:35-90). Empty lines skipped.
void parse_libsvm_range(const char* begin, const char* end, Shard* s) {
  const char* p = begin;
  const size_t len = static_cast<size_t>(end - begin);
  // capacity up front so the hot loop's push_backs never reallocate: the
  // densest legal token is ~4 bytes ("1:2 "), typical is ~10
  s->index.reserve(len / 6);
  s->value.reserve(len / 6);
  s->label.reserve(len / 64);
  s->weight.reserve(len / 64);
  s->row_nnz.reserve(len / 64);
  bool any_value = false, any_weight = false;
  // single pass, no per-line memchr: '\n' is just another terminator the
  // number scanners already stop at, so every byte is touched once
  while (p < end) {
    p = skip_ws_nl(p, end);  // blank lines too
    if (p >= end) break;
    float label;
    if (!parse_float(p, end, &label)) {
      s->error = true;
      s->error_msg = "invalid label in libsvm input";
      return;
    }
    float w = 1.0f;
    if (p < end && *p == ':') {
      ++p;
      if (!parse_float(p, end, &w)) {
        s->error = true;
        s->error_msg = "invalid weight in libsvm input";
        return;
      }
      any_weight = true;
    }
    int64_t nnz = 0;
    while (true) {
      if (p < end && *p == ' ') ++p;      // the common single separator
      p = skip_ws(p, end);
      if (p >= end || *p == '\n') break;
      uint32_t idx;
      if (!parse_u32(p, end, &idx)) {
        s->error = true;
        s->error_msg = "invalid feature index in libsvm input";
        return;
      }
      float v = 1.0f;
      if (p < end && *p == ':') {
        ++p;
        if (!parse_float(p, end, &v)) {
          s->error = true;
          s->error_msg = "invalid feature value in libsvm input";
          return;
        }
        any_value = true;
      }
      s->index.push_back(idx);
      s->value.push_back(v);
      ++nnz;
    }
    s->label.push_back(label);
    s->weight.push_back(w);
    s->row_nnz.push_back(nnz);
  }
  s->any_value |= any_value;
  s->any_weight |= any_weight;
}

// ---------------------------------------------------------------- libfm -----
// Grammar per line: label[:weight] (field:idx:val)*  (reference
// src/data/libfm_parser.h).
void parse_libfm_range(const char* begin, const char* end, Shard* s) {
  const char* p = begin;
  const size_t len = static_cast<size_t>(end - begin);
  s->field.reserve(len / 8);
  s->index.reserve(len / 8);
  s->value.reserve(len / 8);
  bool any_weight = false;
  // one pass, no per-line memchr (same restructure as the libsvm loop)
  while (p < end) {
    p = skip_ws_nl(p, end);  // blank lines too
    if (p >= end) break;
    float label;
    if (!parse_float(p, end, &label)) {
      s->error = true;
      s->error_msg = "invalid label in libfm input";
      return;
    }
    float w = 1.0f;
    if (p < end && *p == ':') {
      ++p;
      if (!parse_float(p, end, &w)) {
        s->error = true;
        s->error_msg = "invalid weight in libfm input";
        return;
      }
      any_weight = true;
    }
    int64_t nnz = 0;
    while (true) {
      p = skip_ws(p, end);
      if (p >= end || *p == '\n') break;
      uint32_t fld, idx;
      float v;
      if (!parse_u32(p, end, &fld) || p >= end || *p != ':') {
        s->error = true;
        s->error_msg = "libfm features must be field:index:value triples";
        return;
      }
      ++p;
      if (!parse_u32(p, end, &idx) || p >= end || *p != ':') {
        s->error = true;
        s->error_msg = "libfm features must be field:index:value triples";
        return;
      }
      ++p;
      if (!parse_float(p, end, &v)) {
        s->error = true;
        s->error_msg = "invalid feature value in libfm input";
        return;
      }
      s->field.push_back(fld);
      s->index.push_back(idx);
      s->value.push_back(v);
      ++nnz;
    }
    s->label.push_back(label);
    s->weight.push_back(w);
    s->row_nnz.push_back(nnz);
  }
  s->any_weight |= any_weight;
}

// ------------------------------------------------------------------- csv ----
// Dense comma-separated floats (reference src/data/csv_parser.h:64-99); the
// label column is extracted on the Python side (cheap numpy slice).
struct CsvShard {
  std::vector<float> dense;
  int64_t n_rows = 0;
  int64_t n_cols = -1;
  bool error = false;
  std::string error_msg;
};

void parse_csv_range(const char* begin, const char* end, CsvShard* s,
                     float missing) {
  const char* p = begin;
  s->dense.reserve(static_cast<size_t>(end - begin) / 6);
  // one pass, no per-line memchr: '\n' is just another cell terminator
  // (same restructure as the libsvm loop; every byte touched once)
  while (p < end) {
    p = skip_ws_nl(p, end);  // blank lines too
    if (p >= end) break;
    int64_t cols = 0;
    while (true) {
      p = skip_ws(p, end);
      float v;
      if (p == end || *p == ',' || *p == '\n') {
        // empty cell: the reference's strtof parses it as 0.0 silently
        // (src/data/csv_parser.h:83); we take the configured missing
        // value (0.0 default = reference parity, NaN for sparsity-aware
        // training).  A trailing comma counts as a trailing empty cell.
        v = missing;
      } else if (!parse_float(p, end, &v)) {
        s->error = true;
        s->error_msg = "invalid CSV number";
        return;
      }
      s->dense.push_back(v);
      ++cols;
      p = skip_ws(p, end);
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      break;
    }
    // anything after the last cell is discarded to end-of-line (the old
    // lend-bounded loop's behavior for trailing junk); for normal rows p
    // already sits on the '\n' and this is a no-op
    while (p < end && *p != '\n') ++p;
    if (s->n_cols < 0) s->n_cols = cols;
    if (cols != s->n_cols) {
      s->error = true;
      s->error_msg = "CSV rows have inconsistent column counts";
      return;
    }
    ++s->n_rows;
  }
}

template <typename Fn>
Result* run_parse(const char* data, int64_t len, int nthread, Fn parse_fn,
                  bool has_field_format) {
  auto* result = new Result();
  if (nthread < 1) nthread = 1;
  auto ranges = split_ranges(data, data + len, nthread);
  std::vector<Shard> shards(ranges.size());
  {
    std::vector<std::thread> workers;
    for (size_t i = 1; i < ranges.size(); ++i) {
      workers.emplace_back(parse_fn, ranges[i].first, ranges[i].second,
                           &shards[i]);
    }
    if (!ranges.empty()) {
      parse_fn(ranges[0].first, ranges[0].second, &shards[0]);
    }
    for (auto& w : workers) w.join();
  }
  bool any_weight = false, any_value = false;
  for (auto& s : shards) {
    if (s.error) {
      result->error_msg = s.error_msg;
      return result;
    }
    any_weight |= s.any_weight;
    any_value |= s.any_value || has_field_format;  // libfm always has values
    result->total_rows += static_cast<int64_t>(s.row_nnz.size());
    result->total_nnz += static_cast<int64_t>(s.index.size());
  }
  result->has_weight = any_weight;
  result->has_value = any_value;
  result->has_field = has_field_format;
  result->shards = std::move(shards);  // fill() gathers from these directly
  return result;
}

}  // namespace

extern "C" {

// All handles are Result*. On error, dims() reports n_rows = -1 and
// dmlc_tpu_error_msg returns the message.

void* dmlc_tpu_parse_libsvm(const char* data, int64_t len, int nthread) {
  return run_parse(data, len, nthread, parse_libsvm_range, false);
}

void* dmlc_tpu_parse_libfm(const char* data, int64_t len, int nthread) {
  return run_parse(data, len, nthread, parse_libfm_range, true);
}

// ABI version handshake: the ctypes bridge refuses (and rebuilds) a stale
// library whose entry points don't match what it expects.  Bump on any
// signature change.
// 5: lsplit_open2 grew the ring-depth arg; batched lsplit_next_chunks
int dmlc_tpu_abi_version() { return 5; }

void* dmlc_tpu_parse_csv(const char* data, int64_t len, int nthread,
                         float missing) {
  auto* result = new Result();
  result->is_dense = true;
  if (nthread < 1) nthread = 1;
  auto ranges = split_ranges(data, data + len, nthread);
  std::vector<CsvShard> shards(ranges.size());
  {
    std::vector<std::thread> workers;
    for (size_t i = 1; i < ranges.size(); ++i) {
      workers.emplace_back(parse_csv_range, ranges[i].first, ranges[i].second,
                           &shards[i], missing);
    }
    if (!ranges.empty()) {
      parse_csv_range(ranges[0].first, ranges[0].second, &shards[0], missing);
    }
    for (auto& w : workers) w.join();
  }
  int64_t ncols = -1;
  for (auto& s : shards) {
    if (s.error) {
      result->error_msg = s.error_msg;
      return result;
    }
    if (s.n_cols >= 0) {
      if (ncols < 0) ncols = s.n_cols;
      if (s.n_cols != ncols) {
        result->error_msg = "CSV rows have inconsistent column counts";
        return result;
      }
    }
  }
  result->n_cols = ncols < 0 ? 0 : ncols;
  for (auto& s : shards) {
    result->total_rows += s.n_rows;
    result->total_nnz += static_cast<int64_t>(s.dense.size());
  }
  result->csv_shards = std::move(shards);  // fill() gathers directly
  return result;
}

void dmlc_tpu_result_dims(void* handle, int64_t* n_rows, int64_t* nnz,
                          int64_t* n_cols, int32_t* flags) {
  auto* r = static_cast<Result*>(handle);
  if (!r->error_msg.empty()) {
    *n_rows = -1;
    *nnz = 0;
    *n_cols = 0;
    *flags = 0;
    return;
  }
  if (r->is_dense) {
    *n_rows = r->total_rows;
    *nnz = r->total_nnz;
    *n_cols = r->n_cols;
    *flags = 8;  // dense
    return;
  }
  *n_rows = r->total_rows;
  *nnz = r->total_nnz;
  *n_cols = 0;
  *flags = (r->has_weight ? 1 : 0) | (r->has_value ? 2 : 0) |
           (r->has_field ? 4 : 0);
}

const char* dmlc_tpu_error_msg(void* handle) {
  return static_cast<Result*>(handle)->error_msg.c_str();
}

void dmlc_tpu_result_fill(void* handle, int64_t* offset, float* label,
                          float* weight, uint32_t* index, uint32_t* field,
                          float* value, float* dense) {
  auto* r = static_cast<Result*>(handle);
  if (dense) {
    float* out = dense;
    for (auto& s : r->csv_shards) {
      if (s.dense.empty()) continue;  // memcpy from nullptr is UB even at 0
      memcpy(out, s.dense.data(), s.dense.size() * sizeof(float));
      out += s.dense.size();
    }
    return;
  }
  int64_t row = 0, nnz_base = 0;
  if (offset) offset[0] = 0;
  for (auto& s : r->shards) {
    const int64_t rows = static_cast<int64_t>(s.row_nnz.size());
    const int64_t nnz = static_cast<int64_t>(s.index.size());
    if (offset) {
      int64_t run = nnz_base;
      for (int64_t i = 0; i < rows; ++i) {
        run += s.row_nnz[i];
        offset[row + i + 1] = run;
      }
    }
    if (label && rows) {
      memcpy(label + row, s.label.data(), rows * sizeof(float));
    }
    if (weight && !s.weight.empty()) {
      memcpy(weight + row, s.weight.data(), rows * sizeof(float));
    }
    if (index && nnz) {
      memcpy(index + nnz_base, s.index.data(), nnz * sizeof(uint32_t));
    }
    if (field && !s.field.empty()) {
      memcpy(field + nnz_base, s.field.data(), nnz * sizeof(uint32_t));
    }
    if (value && !s.value.empty()) {
      memcpy(value + nnz_base, s.value.data(), nnz * sizeof(float));
    }
    row += rows;
    nnz_base += nnz;
  }
}

// One-pass label-column split of a dense CSV result: labels[i] takes
// column label_col, feats gets the remaining n_cols-1 columns row-major.
// Replaces a full extra numpy copy (np.delete) per chunk on the Python
// side.  Caller guarantees 0 <= label_col < n_cols and buffers sized
// n_rows and n_rows*(n_cols-1).
void dmlc_tpu_result_fill_csv(void* handle, int64_t label_col, float* labels,
                              float* feats) {
  auto* r = static_cast<Result*>(handle);
  const int64_t ncols = r->n_cols;
  if (ncols <= 0 || label_col < 0 || label_col >= ncols) return;
  const int64_t left = label_col;               // cols before the label
  const int64_t right = ncols - label_col - 1;  // cols after it
  int64_t base = 0;
  for (auto& s : r->csv_shards) {
    const float* src = s.dense.data();
    for (int64_t i = 0; i < s.n_rows; ++i) {
      const float* row = src + i * ncols;
      labels[base + i] = row[label_col];
      float* out = feats + (base + i) * (ncols - 1);
      if (left) memcpy(out, row, left * sizeof(float));
      if (right) {
        memcpy(out + left, row + label_col + 1, right * sizeof(float));
      }
    }
    base += s.n_rows;
  }
}

void dmlc_tpu_result_free(void* handle) {
  delete static_cast<Result*>(handle);
}

// ------------------------------------------------------------- recordio -----
// 4-byte-aligned magic-cell scan used by the RecordIO writer's escape path
// (reference src/recordio.cc:22-38): writes found positions (byte offsets)
// into out (capacity out_cap); returns the count found.
int64_t dmlc_tpu_find_magic(const char* data, int64_t len, uint32_t magic,
                            int64_t* out, int64_t out_cap) {
  int64_t found = 0;
  const int64_t nwords = len / 4;
  for (int64_t i = 0; i < nwords; ++i) {
    uint32_t w;
    memcpy(&w, data + i * 4, 4);
    if (w == magic) {
      if (found < out_cap) out[found] = i * 4;
      ++found;
    }
  }
  return found;
}

}  // extern "C"
