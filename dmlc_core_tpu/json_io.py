"""Schema-directed JSON reader/writer with line-number error reporting.

Capability parity with the reference's ``dmlc::JSONReader/JSONWriter``
(include/dmlc/json.h:41-147 reader, 152-248 writer), the struct helper
``JSONObjectReadHelper`` (json.h:266+), and type-erased ``any`` JSON via
registered type names (``AnyJSONManager`` json.h:486,
``DMLC_JSON_ENABLE_ANY`` json.h:327-340):

- event-style pull reader: ``begin_object``/``next_object_item``,
  ``begin_array``/``next_array_item``, typed ``read(spec)`` — every error
  reports the 1-based source line (json.h:116-123);
- writer with matching ``begin_*``/``write_object_keyvalue``/
  ``write_array_item`` calls and multi-line indentation;
- :class:`JSONObjectReadHelper`: declare typed fields (optional or
  required), then ``read_all_fields`` enforces unknown-key and missing-key
  policy exactly like the reference;
- :func:`register_any_type`: name-registered (to_json, from_json) pairs so
  heterogeneous ``any`` values round-trip as ``[type_name, value]`` pairs the
  way ``AnyJSONManager`` serializes them.

Type *specs* mirror the serializer module's vocabulary: a spec is ``int``,
``float``, ``bool``, ``str``, ``None`` (infer / plain tree), ``[elem_spec]``
(list), ``{key_spec: value_spec}`` (dict with string keys), ``(s1, s2, ...)``
(fixed tuple), a class with ``json_load``/``json_save`` methods, or the
string ``"any"`` for registered type-erased values.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["JSONReader", "JSONWriter", "JSONObjectReadHelper",
           "JSONError", "register_any_type", "dumps", "loads"]


# --------------------------------------------------------------------------
# type-erased any registry (reference AnyJSONManager, json.h:486+)

_ANY_BY_NAME: Dict[str, Tuple[type, Callable, Callable]] = {}
_ANY_BY_TYPE: Dict[type, str] = {}


def register_any_type(name: str, cls: type,
                      to_json: Optional[Callable[[Any], Any]] = None,
                      from_json: Optional[Callable[[Any], Any]] = None) -> None:
    """Register ``cls`` under ``name`` for type-erased JSON round-trips
    (reference ``DMLC_JSON_ENABLE_ANY``, json.h:327-340)."""
    if name in _ANY_BY_NAME and _ANY_BY_NAME[name][0] is not cls:
        raise ValueError(f"any type name {name!r} already registered")
    _ANY_BY_NAME[name] = (cls, to_json or (lambda v: v),
                          from_json or (lambda v: cls(v)))
    _ANY_BY_TYPE[cls] = name


class JSONError(ValueError):
    pass


# --------------------------------------------------------------------------
# reader

class JSONReader:
    """Event-style pull reader (reference json.h:41-147).

    Typical use::

        reader = JSONReader(text)
        reader.begin_object()
        while (key := reader.next_object_item()) is not None:
            value = reader.read(int)
    """

    def __init__(self, text: str):
        self._s = text
        self._pos = 0
        self._line = 1
        # scope_counter[-1] counts items emitted in the innermost scope
        self._scope: List[int] = []

    # -- low-level ---------------------------------------------------------
    def _error(self, msg: str) -> JSONError:
        return JSONError(f"JSON parse error at line {self._line}: {msg}")

    def _peek(self) -> str:
        """Next non-whitespace char without consuming (json.h PeekNextNonSpace)."""
        while self._pos < len(self._s):
            c = self._s[self._pos]
            if c == "\n":
                self._line += 1
            elif not c.isspace():
                return c
            self._pos += 1
        raise self._error("unexpected end of input")

    def _next(self) -> str:
        c = self._peek()
        self._pos += 1
        return c

    def _expect(self, ch: str) -> None:
        c = self._next()
        if c != ch:
            raise self._error(f"expected {ch!r}, got {c!r}")

    # -- tokens ------------------------------------------------------------
    def read_string(self) -> str:
        self._expect('"')
        out = []
        while True:
            if self._pos >= len(self._s):
                raise self._error("unterminated string")
            c = self._s[self._pos]
            self._pos += 1
            if c == '"':
                return "".join(out)
            if c == "\\":
                e = self._s[self._pos] if self._pos < len(self._s) else ""
                self._pos += 1
                mapping = {'"': '"', "\\": "\\", "/": "/", "b": "\b",
                           "f": "\f", "n": "\n", "r": "\r", "t": "\t"}
                if e == "u":
                    code = self._s[self._pos:self._pos + 4]
                    self._pos += 4
                    try:
                        cp = int(code, 16)
                    except ValueError:
                        raise self._error(f"bad unicode escape \\u{code}")
                    # combine UTF-16 surrogate pairs (stdlib-json producers
                    # emit non-BMP chars as \uD8xx\uDCxx with ensure_ascii)
                    if 0xD800 <= cp <= 0xDBFF and self._s.startswith(
                            "\\u", self._pos):
                        lo_code = self._s[self._pos + 2:self._pos + 6]
                        try:
                            lo = int(lo_code, 16)
                        except ValueError:
                            lo = -1
                        if 0xDC00 <= lo <= 0xDFFF:
                            self._pos += 6
                            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00)
                    out.append(chr(cp))
                elif e in mapping:
                    out.append(mapping[e])
                else:
                    raise self._error(f"bad escape \\{e}")
            else:
                if c == "\n":
                    self._line += 1
                out.append(c)

    def read_number(self) -> float:
        c = self._peek()
        # non-finite tokens (stdlib-json compatible: NaN/Infinity/-Infinity)
        for tok, val in (("NaN", float("nan")), ("Infinity", float("inf")),
                         ("-Infinity", float("-inf"))):
            if self._s.startswith(tok, self._pos):
                self._pos += len(tok)
                return val
        start = self._pos
        while (self._pos < len(self._s)
               and self._s[self._pos] in "+-0123456789.eE"):
            self._pos += 1
        tok = self._s[start:self._pos]
        try:
            return int(tok)
        except ValueError:
            try:
                return float(tok)
            except ValueError:
                raise self._error(f"invalid number {tok!r}")

    def read_bool(self) -> bool:
        c = self._peek()
        word = self._s[self._pos:self._pos + (4 if c == "t" else 5)]
        if word == "true":
            self._pos += 4
            return True
        if word == "false":
            self._pos += 5
            return False
        raise self._error(f"expected true/false, got {word!r}")

    def read_null(self) -> None:
        if self._s[self._pos:self._pos + 4] == "null":
            self._pos += 4
            return None
        raise self._error("expected null")

    # -- structure (reference json.h:71-105) -------------------------------
    def begin_object(self) -> None:
        self._expect("{")
        self._scope.append(0)

    def begin_array(self) -> None:
        self._expect("[")
        self._scope.append(0)

    def next_object_item(self) -> Optional[str]:
        """Key of the next item, or None at object end (json.h:98)."""
        if self._peek() == "}":
            self._pos += 1
            self._scope.pop()
            return None
        if self._scope[-1] > 0:
            self._expect(",")
        self._scope[-1] += 1
        key = self.read_string()
        self._expect(":")
        return key

    def next_array_item(self) -> bool:
        if self._peek() == "]":
            self._pos += 1
            self._scope.pop()
            return False
        if self._scope[-1] > 0:
            self._expect(",")
        self._scope[-1] += 1
        return True

    # -- typed read (reference Read<T>, json.h:113) ------------------------
    def read(self, spec: Any = None) -> Any:
        if spec is None:
            return self._read_value()
        if spec == "any":
            self.begin_array()
            if not self.next_array_item():
                raise self._error("any value must be [type_name, value]")
            name = self.read_string()
            if name not in _ANY_BY_NAME:
                raise self._error(f"any type {name!r} is not registered")
            _, _, from_json = _ANY_BY_NAME[name]
            if not self.next_array_item():
                raise self._error("any value must be [type_name, value]")
            value = self._read_value()
            if self.next_array_item():
                raise self._error("any value must have exactly 2 entries")
            return from_json(value)
        if spec is str:
            return self.read_string()
        if spec is bool:
            return self.read_bool()
        if spec is int:
            v = self.read_number()
            if not isinstance(v, int):
                raise self._error(f"expected integer, got {v}")
            return v
        if spec is float:
            return float(self.read_number())
        if isinstance(spec, list):
            out = []
            self.begin_array()
            while self.next_array_item():
                out.append(self.read(spec[0]))
            return out
        if isinstance(spec, tuple):
            self.begin_array()
            out = []
            for s in spec:
                if not self.next_array_item():
                    raise self._error(f"expected {len(spec)}-tuple")
                out.append(self.read(s))
            if self.next_array_item():
                raise self._error(f"expected {len(spec)}-tuple")
            return tuple(out)
        if isinstance(spec, dict):
            (kspec, vspec), = spec.items()
            out = {}
            self.begin_object()
            while (key := self.next_object_item()) is not None:
                out[_coerce_key(key, kspec, self)] = self.read(vspec)
            return out
        if isinstance(spec, type) and hasattr(spec, "json_load"):
            return spec.json_load(self)
        raise self._error(f"unsupported read spec {spec!r}")

    def _read_value(self) -> Any:
        c = self._peek()
        if c == "{":
            out = {}
            self.begin_object()
            while (key := self.next_object_item()) is not None:
                out[key] = self._read_value()
            return out
        if c == "[":
            out = []
            self.begin_array()
            while self.next_array_item():
                out.append(self._read_value())
            return out
        if c == '"':
            return self.read_string()
        if c in "tf":
            return self.read_bool()
        if c == "n":
            return self.read_null()
        return self.read_number()


def _coerce_key(key: str, kspec: Any, reader: JSONReader) -> Any:
    if kspec is str:
        return key
    if kspec is int:
        try:
            return int(key)
        except ValueError:
            raise reader._error(f"expected integer key, got {key!r}")
    raise reader._error(f"unsupported key spec {kspec!r}")


# --------------------------------------------------------------------------
# writer

class JSONWriter:
    """Streaming writer mirroring the reader's call structure
    (reference json.h:152-248)."""

    def __init__(self, multi_line: bool = True):
        self._out: List[str] = []
        self._scope: List[int] = []
        self._scope_multi: List[bool] = []
        self._multi_line = multi_line

    def _sep(self) -> None:
        if self._scope_multi and self._scope_multi[-1]:
            self._out.append("\n" + "  " * len(self._scope))

    def write_string(self, s: str) -> None:
        out = ['"']
        for c in s:
            if c == "\\":
                out.append("\\\\")
            elif c == '"':
                out.append('\\"')
            elif c == "\n":
                out.append("\\n")
            elif c == "\r":
                out.append("\\r")
            elif c == "\t":
                out.append("\\t")
            elif ord(c) < 0x20:
                out.append(f"\\u{ord(c):04x}")
            else:
                out.append(c)
        out.append('"')
        self._out.append("".join(out))

    def begin_object(self, multi_line: Optional[bool] = None) -> None:
        self._out.append("{")
        self._scope.append(0)
        self._scope_multi.append(self._multi_line if multi_line is None
                                 else multi_line)

    def begin_array(self, multi_line: Optional[bool] = None) -> None:
        self._out.append("[")
        self._scope.append(0)
        self._scope_multi.append(self._multi_line if multi_line is None
                                 else multi_line)

    def end_object(self) -> None:
        n = self._scope.pop()
        multi = self._scope_multi.pop()
        if n and multi:
            self._out.append("\n" + "  " * len(self._scope))
        self._out.append("}")

    def end_array(self) -> None:
        n = self._scope.pop()
        multi = self._scope_multi.pop()
        if n and multi:
            self._out.append("\n" + "  " * len(self._scope))
        self._out.append("]")

    def write_object_keyvalue(self, key: str, value: Any,
                              spec: Any = None) -> None:
        if self._scope[-1] > 0:
            self._out.append(",")
        self._scope[-1] += 1
        self._sep()
        self.write_string(key)
        self._out.append(": " if self._scope_multi[-1] else ":")
        self.write(value, spec)

    def write_array_item(self, value: Any, spec: Any = None) -> None:
        if self._scope[-1] > 0:
            self._out.append(",")
        self._scope[-1] += 1
        self._sep()
        self.write(value, spec)

    def write(self, value: Any, spec: Any = None) -> None:
        if spec == "any":
            name = _ANY_BY_TYPE.get(type(value))
            if name is None:
                raise TypeError(
                    f"type {type(value).__name__} is not registered for "
                    f"any-JSON (register_any_type)")
            _, to_json, _ = _ANY_BY_NAME[name]
            self.begin_array(multi_line=False)
            self.write_array_item(name)
            self.write_array_item(to_json(value))
            self.end_array()
            return
        if hasattr(value, "json_save") and not isinstance(value, type):
            value.json_save(self)
            return
        if isinstance(value, bool):
            self._out.append("true" if value else "false")
        elif value is None:
            self._out.append("null")
        elif isinstance(value, float):
            import math
            if math.isnan(value):
                self._out.append("NaN")          # stdlib-json compatible
            elif math.isinf(value):
                self._out.append("Infinity" if value > 0 else "-Infinity")
            else:
                self._out.append(repr(value))
        elif isinstance(value, int):
            self._out.append(repr(value))
        elif isinstance(value, str):
            self.write_string(value)
        elif isinstance(value, (list, tuple)):
            self.begin_array()
            for i, v in enumerate(value):
                if isinstance(spec, list):
                    vspec = spec[0]
                elif isinstance(spec, tuple) and i < len(spec):
                    vspec = spec[i]
                else:
                    vspec = None
                self.write_array_item(v, vspec)
            self.end_array()
        elif isinstance(value, dict):
            self.begin_object()
            for k, v in value.items():
                vspec = None
                if isinstance(spec, dict):
                    (_, vspec), = spec.items()
                self.write_object_keyvalue(str(k), v, vspec)
            self.end_object()
        else:
            raise TypeError(f"cannot JSON-write {type(value).__name__}")

    def getvalue(self) -> str:
        return "".join(self._out)


# --------------------------------------------------------------------------
# struct helper (reference JSONObjectReadHelper, json.h:266+)

class JSONObjectReadHelper:
    """Declare typed fields, then read a whole object with required/optional
    and unknown-key enforcement::

        helper = JSONObjectReadHelper()
        helper.declare_field("name", str)
        helper.declare_field_optional("size", int, default=0)
        values = helper.read_all_fields(reader)
    """

    def __init__(self):
        self._fields: Dict[str, Tuple[Any, bool, Any]] = {}

    def declare_field(self, key: str, spec: Any) -> None:
        self._fields[key] = (spec, False, None)

    def declare_field_optional(self, key: str, spec: Any,
                               default: Any = None) -> None:
        self._fields[key] = (spec, True, default)

    def read_all_fields(self, reader: JSONReader) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        reader.begin_object()
        while (key := reader.next_object_item()) is not None:
            if key not in self._fields:
                raise reader._error(f"JSONReader: unknown field {key!r}")
            if key in out:
                raise reader._error(f"JSONReader: duplicate field {key!r}")
            out[key] = reader.read(self._fields[key][0])
        for key, (_, optional, default) in self._fields.items():
            if key not in out:
                if not optional:
                    raise JSONError(
                        f"JSONReader: missing required field {key!r}")
                out[key] = default
        return out


# --------------------------------------------------------------------------
# convenience

def dumps(value: Any, spec: Any = None, multi_line: bool = True) -> str:
    writer = JSONWriter(multi_line=multi_line)
    writer.write(value, spec)
    return writer.getvalue()


def loads(text: str, spec: Any = None) -> Any:
    return JSONReader(text).read(spec)
