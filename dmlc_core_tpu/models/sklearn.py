"""Sklearn-style estimator facade over the jit-compiled hist GBDT.

The migration surface XGBoost users actually hold: ``XGBClassifier``-shaped
``fit(X, y)`` / ``predict`` / ``predict_proba`` / ``score`` with
``get_params``/``set_params`` (duck-typed — no sklearn dependency), wrapping
:class:`..models.gbdt.GBDT`.  Labels are encoded/decoded automatically,
NaNs in ``X`` switch on sparsity-aware splits unless overridden, and
``eval_set``/``early_stopping_rounds`` ride :meth:`GBDT.fit_with_eval`
(binary logloss, squared error, or multiclass mlogloss per objective).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

import numpy as np

from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["GBDTClassifier", "GBDTRegressor"]

# GBDTParam fields settable through the estimator constructor
_PARAM_KEYS = ("num_boost_round", "max_depth", "num_bins", "learning_rate",
               "reg_lambda", "reg_alpha", "min_child_weight",
               "min_split_loss", "subsample", "colsample_bytree",
               "colsample_bylevel", "colsample_bynode", "max_delta_step",
               "scale_pos_weight", "seed", "base_score",
               "monotone_constraints", "hist_method")


class _GBDTEstimator:
    """Shared fit/predict plumbing; subclasses fix the objective."""

    def __init__(self, handle_missing: Optional[bool] = None,
                 bin_sample_rows: int = 100_000,
                 importance_type: str = "gain", **params):
        for k in params:
            CHECK(k in _PARAM_KEYS,
                  f"unknown parameter {k!r}; settable: {_PARAM_KEYS}")
        self._params: Dict[str, Any] = dict(params)
        self.handle_missing = handle_missing   # None = auto (NaN in X)
        self.bin_sample_rows = bin_sample_rows
        self.importance_type = importance_type

    # -- sklearn protocol -----------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        out = dict(self._params)
        out["handle_missing"] = self.handle_missing
        out["bin_sample_rows"] = self.bin_sample_rows
        out["importance_type"] = self.importance_type
        return out

    def set_params(self, **params):
        for k, v in params.items():
            if k in ("handle_missing", "bin_sample_rows",
                     "importance_type"):
                setattr(self, k, v)
            else:
                CHECK(k in _PARAM_KEYS, f"unknown parameter {k!r}")
                self._params[k] = v
        return self

    # -- internals ------------------------------------------------------------
    def _objective_params(self, y: np.ndarray) -> Dict[str, Any]:
        raise NotImplementedError

    def _encode(self, y: np.ndarray) -> np.ndarray:
        return np.asarray(y, np.float32)

    def _make_model(self, X: np.ndarray, y: np.ndarray) -> GBDT:
        missing = self.handle_missing
        if missing is None:
            missing = bool(np.isnan(X).any())
        param = GBDTParam(handle_missing=missing,
                          **self._params, **self._objective_params(y))
        return GBDT(param, num_feature=X.shape[1])

    def fit(self, X, y, sample_weight=None, eval_set=None,
            early_stopping_rounds: int = 0, eval_metric: str = "loss",
            comm=None):
        """Train; ``eval_set=(X_val, y_val)`` (or XGBoost-style
        ``[(X_val, y_val)]``) enables loss tracking and, with
        ``early_stopping_rounds``, best-round truncation.  ``comm``
        (rabit-shaped) merges bin boundaries across data-parallel workers.
        """
        X = np.asarray(X, np.float32)
        y = np.asarray(y)
        CHECK(X.ndim == 2 and len(X) == len(y),
              f"X [{X.shape}] / y [{y.shape}] shape mismatch")
        self.model_ = self._make_model(X, y)
        self.model_.make_bins(X[: self.bin_sample_rows], comm=comm,
                              count=len(X))
        bins = self.model_.bin_features(X)
        yy = self._encode(y)
        if eval_set is not None:
            # accept bare (X, y) or the XGBoost spelling [(X0, y0), ...];
            # like XGBoost, the LAST set drives early stopping.  A bare
            # pair is recognised by its first element being a 2-D feature
            # matrix (list-of-rows X included); anything else is treated
            # as a list of pairs.
            def _is_pair(es):
                if not isinstance(es, (list, tuple)) or len(es) != 2:
                    return False
                try:
                    return np.asarray(es[0], np.float32).ndim == 2
                except Exception:
                    return False

            sets = [eval_set] if _is_pair(eval_set) else list(eval_set)
            CHECK(sets and all(_is_pair(sv) for sv in sets),
                  "eval_set must be (X_val, y_val) or a list of such pairs")
            binned = [(self.model_.bin_features(np.asarray(Xv, np.float32)),
                       self._encode(np.asarray(yv))) for Xv, yv in sets]
            ev_bins, ev_y = binned[-1]
            self.ensemble_, self.eval_history_ = self.model_.fit_with_eval(
                bins, yy, ev_bins, ev_y, weight=sample_weight,
                early_stopping_rounds=early_stopping_rounds,
                eval_metric=eval_metric)
            # per-round curves for the remaining sets, post-hoc (one
            # compiled scan each).  NOTE: computed from the FINAL (possibly
            # early-stop-truncated) ensemble, so history entries past the
            # kept rounds carry only the primary set's eval_loss
            for i, (bv, lv) in enumerate(binned[:-1]):
                # same metric as the primary set: the curves must be
                # comparable within one history dict
                curve = self.model_.staged_losses(self.ensemble_, bv, lv,
                                                  metric=eval_metric)
                for r, entry in enumerate(self.eval_history_):
                    if r < len(curve):
                        entry[f"eval{i}_loss"] = float(curve[r])
        else:
            self.ensemble_, _ = self.model_.fit_binned(bins, yy,
                                                       weight=sample_weight)
            self.eval_history_ = []
        return self

    def _check_fitted(self):
        CHECK(getattr(self, "model_", None) is not None,
              "estimator is not fitted; call fit(X, y) first")

    def _bins_for_predict(self, X):
        self._check_fitted()
        X = np.asarray(X, np.float32)
        CHECK(self.model_.param.handle_missing or not np.isnan(X).any(),
              "X contains NaN but the model was trained without missing "
              "support (no NaN seen at fit time); refit with "
              "handle_missing=True")
        return self.model_.bin_features(X)

    def _margin(self, X):
        return self.model_.predict_margin(self.ensemble_,
                                          self._bins_for_predict(X))

    @property
    def feature_importances_(self) -> np.ndarray:
        """Normalized importances of the estimator's ``importance_type``
        (default ``'gain'`` = mean gain per split, matching the XGBoost
        sklearn wrapper's default; any :meth:`GBDT.feature_importance`
        kind is accepted)."""
        self._check_fitted()
        imp = self.model_.feature_importance(self.ensemble_,
                                             self.importance_type)
        total = imp.sum()
        return imp / total if total > 0 else imp

    def _extra_payload(self) -> Dict[str, Any]:
        # enough to reconstruct the estimator: the full GBDTParam as JSON
        # bytes (uint8 leaf) + subclass extras
        blob = json.dumps(self.model_.param.to_dict()).encode()
        return {"sk_param": np.frombuffer(blob, np.uint8)}

    def save_model(self, uri: str) -> None:
        """Persist model + boundaries + estimator metadata; reload with
        ``GBDTClassifier.load_model(uri)`` / ``GBDTRegressor.load_model``."""
        self._check_fitted()
        self.model_.save_model(uri, self.ensemble_,
                               extra=self._extra_payload())

    @classmethod
    def load_model(cls, uri: str):
        """Reconstruct a fitted estimator from :meth:`save_model` output."""
        from dmlc_core_tpu.bridge.checkpoint import load_checkpoint

        flat = load_checkpoint(uri)
        key = "['sk_param']"
        CHECK(key in flat,
              f"{uri!r} was not written by an estimator's save_model "
              f"(no sk_param); load it with GBDT.load_model instead")
        pdict = json.loads(bytes(flat[key]).decode())
        param = GBDTParam()
        param.init(pdict)
        est = cls(handle_missing=param.handle_missing,
                  **{k: getattr(param, k) for k in _PARAM_KEYS})
        est._restore(param, flat)
        boundaries = np.asarray(flat["['boundaries']"], np.float32)
        model = GBDT(param, num_feature=boundaries.shape[0])
        est.model_ = model
        # restore from the dict already in hand: a second full fetch of the
        # URI would double I/O and could mix metadata/ensemble across a
        # concurrent replace
        est.ensemble_ = model.load_model_dict(flat)
        est.eval_history_ = []
        return est

    def _restore(self, param: GBDTParam, flat: Dict[str, Any]) -> None:
        """Subclass hook for estimator-specific payload (class labels)."""


class GBDTClassifier(_GBDTEstimator):
    """Binary or multiclass classifier (objective auto-selected from y)."""

    def _extra_payload(self) -> Dict[str, Any]:
        out = super()._extra_payload()
        out["sk_classes"] = np.asarray(self.classes_)
        return out

    def _objective_params(self, y: np.ndarray) -> Dict[str, Any]:
        self.classes_ = np.unique(y)
        CHECK(len(self.classes_) >= 2,
              f"need >= 2 classes, got {self.classes_!r}")
        if len(self.classes_) == 2:
            return {"objective": "logistic"}
        return {"objective": "softmax", "num_class": len(self.classes_)}

    def _restore(self, param: GBDTParam, flat: Dict[str, Any]) -> None:
        key = "['sk_classes']"
        CHECK(key in flat,
              "checkpoint has no class labels; it was saved by a regressor "
              "— load it with GBDTRegressor.load_model")
        self.classes_ = np.asarray(flat[key])

    def _encode(self, y: np.ndarray) -> np.ndarray:
        # map original labels to 0..K-1 ids; labels unseen at fit time must
        # error, not silently take an arbitrary insertion index
        unseen = ~np.isin(y, self.classes_)
        CHECK(not unseen.any(),
              f"labels {np.unique(np.asarray(y)[unseen])!r} were not in "
              f"the training classes {self.classes_!r}")
        return np.searchsorted(self.classes_, y).astype(np.float32)

    def predict(self, X) -> np.ndarray:
        bins = self._bins_for_predict(X)       # validates fitted state first
        ids = np.asarray(self.model_.predict_class(self.ensemble_, bins))
        return self.classes_[ids]

    def predict_proba(self, X) -> np.ndarray:
        bins = self._bins_for_predict(X)
        proba = np.asarray(self.model_.predict(self.ensemble_, bins),
                           np.float64)
        if proba.ndim == 2:                    # softmax [B, K]
            return proba
        return np.stack([1.0 - proba, proba], axis=1)   # logistic

    def score(self, X, y) -> float:
        """Mean accuracy."""
        return float((self.predict(X) == np.asarray(y)).mean())


class GBDTRegressor(_GBDTEstimator):
    """Squared-error regressor."""

    def _restore(self, param: GBDTParam, flat: Dict[str, Any]) -> None:
        CHECK(param.objective == "squared",
              f"checkpoint objective is {param.objective!r}; load it with "
              f"GBDTClassifier.load_model")

    def _objective_params(self, y: np.ndarray) -> Dict[str, Any]:
        return {"objective": "squared"}

    def predict(self, X) -> np.ndarray:
        return np.asarray(self._margin(X))

    def score(self, X, y) -> float:
        """R^2 (coefficient of determination), the sklearn convention."""
        y = np.asarray(y, np.float64)
        pred = np.asarray(self.predict(X), np.float64)
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        return 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0
