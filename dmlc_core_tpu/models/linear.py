"""Data-parallel linear learners (logistic / squared loss).

The minimum end-to-end slice of SURVEY.md §7: libsvm -> RowBlock -> jax.Array
-> SGD logistic regression with gradients reduced across the data axis.
Idiomatic pjit: the batch is sharded over "data", the params replicated; XLA
inserts the gradient all-reduce (the Rabit allreduce of the reference
ecosystem) automatically.  Works on both DenseBatch and SparseBatch.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np

from dmlc_core_tpu.bridge.batching import DenseBatch, SparseBatch
from dmlc_core_tpu.ops.sparse import segment_matvec, segment_transpose_matvec
from dmlc_core_tpu.param import Parameter, field

__all__ = ["LinearParam", "LinearModel"]


class LinearParam(Parameter):
    num_feature = field(int, lower=1, help="feature dimension")
    learning_rate = field(float, default=0.1, lower=0.0, help="SGD step size")
    reg_lambda = field(float, default=0.0, lower=0.0, help="L2 regularization")
    loss = field(str, default="logistic", enum=["logistic", "squared"],
                 help="objective")


def _loss_grad(margin, label, loss: str):
    import jax.numpy as jnp

    if loss == "logistic":
        p = 1.0 / (1.0 + jnp.exp(-margin))
        return p - label
    return margin - label


def _loss_value(margin, label, weight, loss: str):
    import jax.numpy as jnp

    if loss == "logistic":
        # numerically-stable weighted logloss
        ls = jnp.logaddexp(0.0, margin) - label * margin
        return jnp.sum(ls * weight) / jnp.maximum(jnp.sum(weight), 1.0)
    return jnp.sum(weight * (margin - label) ** 2) / jnp.maximum(jnp.sum(weight), 1.0)


class LinearModel:
    """SGD linear model over dense or sparse mesh batches."""

    def __init__(self, param: LinearParam):
        self.param = param

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        import jax.numpy as jnp

        rng = np.random.RandomState(seed)
        w = rng.normal(0, 0.01, self.param.num_feature).astype(np.float32)
        return {"w": jnp.asarray(w), "b": jnp.float32(0.0)}

    # -- jitted steps (cached per (loss, lr, lambda) statics) -----------------
    @functools.lru_cache(maxsize=None)
    def _dense_step(self, lr: float, lam: float, loss: str):
        import jax
        import jax.numpy as jnp

        def step(params, batch: DenseBatch):
            w, b = params["w"], params["b"]
            margin = batch.x @ w + b
            g = _loss_grad(margin, batch.label, loss) * batch.weight
            denom = jnp.maximum(batch.weight.sum(), 1.0)
            grad_w = batch.x.T @ g / denom + lam * w
            grad_b = g.sum() / denom
            new = {"w": w - lr * grad_w, "b": b - lr * grad_b}
            return new, _loss_value(margin, batch.label, batch.weight, loss)

        return jax.jit(step, donate_argnums=(0,))

    @functools.lru_cache(maxsize=None)
    def _sparse_step(self, lr: float, lam: float, loss: str):
        import jax
        import jax.numpy as jnp

        F = self.param.num_feature

        def step(params, batch: SparseBatch):
            w, b = params["w"], params["b"]
            bsz = batch.label.shape[0]
            margin = segment_matvec(w, batch.value, batch.index,
                                    batch.row_id, bsz) + b
            g = _loss_grad(margin, batch.label, loss) * batch.weight
            denom = jnp.maximum(batch.weight.sum(), 1.0)
            g_ext = jnp.append(g, 0.0)  # sentinel for padding rows
            grad_w = segment_transpose_matvec(g_ext, batch.value, batch.index,
                                              batch.row_id, F) / denom + lam * w
            grad_b = g.sum() / denom
            new = {"w": w - lr * grad_w, "b": b - lr * grad_b}
            return new, _loss_value(margin, batch.label, batch.weight, loss)

        return jax.jit(step, donate_argnums=(0,))

    def train_step(self, params, batch) -> Tuple[Dict[str, Any], Any]:
        """One SGD step; returns (new_params, loss)."""
        p = self.param
        if isinstance(batch, DenseBatch):
            fn = self._dense_step(p.learning_rate, p.reg_lambda, p.loss)
        else:
            fn = self._sparse_step(p.learning_rate, p.reg_lambda, p.loss)
        return fn(params, batch)

    def predict(self, params, batch):
        import jax.numpy as jnp

        if isinstance(batch, DenseBatch):
            margin = batch.x @ params["w"] + params["b"]
        else:
            margin = segment_matvec(params["w"], batch.value, batch.index,
                                    batch.row_id, batch.label.shape[0]) + params["b"]
        if self.param.loss == "logistic":
            return 1.0 / (1.0 + jnp.exp(-margin))
        return margin

    def fit(self, loader, num_epochs: int = 1, params=None, log_every: int = 0):
        """Train over a MeshBatchLoader; returns (params, last_loss)."""
        from dmlc_core_tpu.utils.logging import log_info

        params = params or self.init_params()
        loss = None
        step = 0
        for epoch in range(num_epochs):
            if epoch > 0:
                loader.before_first()
            for batch in loader:
                params, loss = self.train_step(params, batch)
                step += 1
                if log_every and step % log_every == 0:
                    log_info(f"step {step}: loss={float(loss):.5f}")
        return params, loss
