"""Model families on top of the data/bridge/collective stack.

The reference is the substrate *under* XGBoost/MXNet; the TPU-native rebuild
ships the two downstream workloads its north star names (BASELINE.json):

- :mod:`dmlc_core_tpu.models.linear` — (sparse/dense) linear learners with
  logistic/squared objectives, psum'd data-parallel SGD;
- :mod:`dmlc_core_tpu.models.gbdt`  — histogram-based gradient-boosted trees
  (the XGBoost hist algorithm), fully jit-compiled: binning, per-level
  scatter-add histograms, best-split search, and ensemble prediction.
"""

from dmlc_core_tpu.models.linear import LinearModel, LinearParam  # noqa: F401
from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam, TreeEnsemble  # noqa: F401
