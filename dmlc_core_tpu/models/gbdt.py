"""Histogram-based gradient-boosted trees, fully jit-compiled (XGBoost hist on TPU).

This is the BASELINE.json north star: the hist algorithm that XGBoost runs on
top of dmlc-core's data pipeline + Rabit allreduce, redesigned for XLA:

- features are pre-binned to int8-range ids (``ops.histogram.apply_bins``);
- a boosting round is ONE jit: for each tree level (static ``max_depth``
  python loop, unrolled by trace) compute the per-(node, feature, bin)
  gradient histogram with a single flat segment_sum, run the best-split scan
  (cumsum over bins = the "left sums"), and advance every row one level with
  pure gathers — no data-dependent control flow, no host sync;
- rounds are chained with ``lax.scan`` over stacked tree arrays so a full
  ``fit`` is one compiled program;
- under a mesh, rows shard over "data" (histograms become per-shard partials
  + ICI all-reduce, courtesy of GSPMD — the Rabit aggregation, compiled), and
  wide feature spaces can shard the histogram over "model"
  (``grad_histogram(model_axis=...)``).

Trees are stored level-order as flat arrays (a pytree — checkpointable via
bridge.checkpoint): ``split_feat``/``split_bin`` [n_internal] with -1 marking
"no split" (rows fall through to child 2*i), ``leaf_value`` [2**max_depth].
Prediction walks the static levels with gathers — O(depth) gathers per row,
batched over the whole batch.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Tuple

import numpy as np

from dmlc_core_tpu.ops.histogram import (apply_bins, bin_onehot,
                                         distributed_quantile_boundaries,
                                         grad_histogram, resolve_hist_method)
from dmlc_core_tpu.param import Parameter, field
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["GBDTParam", "TreeEnsemble", "GBDT"]


class GBDTParam(Parameter):
    num_boost_round = field(int, default=10, lower=1, help="number of trees")
    max_depth = field(int, default=6, lower=1, upper=14, help="tree depth")
    num_bins = field(int, default=256, lower=2, upper=1024,
                     help="feature histogram bins")
    learning_rate = field(float, default=0.3, lower=0.0, help="shrinkage eta")
    reg_lambda = field(float, default=1.0, lower=0.0, help="L2 on leaf weights")
    reg_alpha = field(float, default=0.0, lower=0.0,
                      help="L1 on leaf weights (XGBoost alpha: gradient "
                           "sums are soft-thresholded in gains and leaves)")
    scale_pos_weight = field(float, default=1.0, lower=0.0,
                             help="weight multiplier for positive rows "
                                  "(logistic class-imbalance knob)")
    min_child_weight = field(float, default=1.0, lower=0.0,
                             help="minimum hessian sum per child")
    min_split_loss = field(float, default=0.0, lower=0.0,
                           help="gamma: minimum gain to split a node")
    # XGBoost's range is (0, 1]; the inclusive field bound keeps 0 out via
    # the epsilon (subsample=0 would silently train all-empty trees)
    subsample = field(float, default=1.0, lower=1e-6, upper=1.0,
                      help="per-tree row subsampling rate")
    colsample_bytree = field(float, default=1.0, lower=1e-6, upper=1.0,
                             help="per-tree feature subsampling rate")
    colsample_bylevel = field(float, default=1.0, lower=1e-6, upper=1.0,
                              help="per-level feature subsampling rate "
                                   "(draws a fresh mask every tree depth, "
                                   "composed with colsample_bytree; a "
                                   "softmax round's K trees share the "
                                   "level draw)")
    colsample_bynode = field(float, default=1.0, lower=1e-6, upper=1.0,
                             help="per-node feature subsampling rate "
                                  "(fresh mask per (depth, node), composed "
                                  "with the tree/level draws; softmax "
                                  "rounds share it like bylevel)")
    max_delta_step = field(float, default=0.0, lower=0.0,
                           help="cap on |leaf weight| before shrinkage "
                                "(XGBoost's imbalanced-logistic stabiliser; "
                                "0 disables). Applied to leaf values AND "
                                "to split gain scoring like XGBoost; with "
                                "reg_alpha>0 AND a binding cap the gain's "
                                "alpha term is the self-consistent -2a|w| "
                                "(XGBoost's CalcGain uses +a|w| there), so "
                                "split choices can differ from XGBoost in "
                                "that corner")
    seed = field(int, default=0, help="subsampling PRNG seed")
    monotone_constraints = field(str, default="",
                                 help="per-feature monotone directions, "
                                      "XGBoost style: '(1,0,-1,...)' or "
                                      "'1,0,-1' — +1 non-decreasing, -1 "
                                      "non-increasing, 0 free; empty "
                                      "disables")
    base_score = field(float, default=0.0,
                       help="initial prediction margin (XGBoost base_score "
                            "in margin space: its default 0.5 probability "
                            "== margin 0 for logistic; for squared "
                            "objectives set e.g. the label mean). "
                            "Streaming boost_round callers must init "
                            "their margin with it themselves")
    handle_missing = field(bool, default=False,
                           help="sparsity-aware splits: NaN features take a "
                                "reserved bin and each split learns its "
                                "default direction (XGBoost semantics)")
    objective = field(str, default="logistic",
                      enum=["logistic", "squared", "softmax"], help="loss")
    num_class = field(int, default=1, lower=1,
                      help="classes for objective=softmax (K trees/round)")
    hist_method = field(str, default="auto",
                        enum=["auto", "pallas", "pallas_fused", "onehot", "scatter"],
                        help="histogram algorithm: VMEM-resident pallas "
                             "kernel (TPU; 'pallas_fused' also builds the "
                             "node-weight matrix in-kernel), one-hot MXU "
                             "matmul, or segment-sum scatter (CPU)")


class TreeEnsemble(NamedTuple):
    """Stacked level-order trees: arrays lead with the tree axis [T, ...].

    Multiclass (objective=softmax) ensembles carry a class axis after the
    tree axis — [T, K, ...] — one tree per class per round (the XGBoost
    multi:softmax layout).
    """

    split_feat: Any    # [T(, K), 2**d - 1] int32, -1 = no split
    split_bin: Any     # [T(, K), 2**d - 1] int32
    leaf_value: Any    # [T(, K), 2**d] float32 (shrinkage already applied)
    default_left: Any  # [T(, K), 2**d - 1] bool: missing rows go left here
                       # (all-False without handle_missing — legacy routing)
    # split statistics for importance (XGBoost get_score analogs); None on
    # ensembles loaded from pre-stats checkpoints — routing never reads them
    split_gain: Any = None   # [T(, K), 2**d - 1] f32 gain, 0 where no split
    split_cover: Any = None  # [T(, K), 2**d - 1] f32 hessian mass at node

    @property
    def num_trees(self) -> int:
        return self.split_feat.shape[0]


def _widen_bins(bins):
    """Accept pre-binned features in the uint8/uint16 wire dtype (the
    tunnel-frugal device feed, ``bridge/binning.py``): widen to int32 *on
    device, inside the jit*, so the host->device transfer ships the narrow
    bytes and every downstream compare/select/gather sees exactly the
    int32 the on-device ``apply_bins`` path produces — split decisions are
    bitwise-identical by construction (tests/test_device_feed.py)."""
    import jax.numpy as jnp

    bins = jnp.asarray(bins)
    return bins if bins.dtype == jnp.int32 else bins.astype(jnp.int32)


def _grad_hess(margin, label, objective: str):
    import jax.numpy as jnp

    if objective == "logistic":
        p = 1.0 / (1.0 + jnp.exp(-margin))
        return p - label, p * (1.0 - p)
    return margin - label, jnp.ones_like(margin)


def _apply_pos_weight(weight, label, p):
    """scale_pos_weight: positive-class rows count spw-times harder in
    every gradient/hessian sum (XGBoost's imbalance knob; logistic only —
    other objectives have no positive class)."""
    if p.scale_pos_weight == 1.0 or p.objective != "logistic":
        return weight
    import jax.numpy as jnp

    return weight * jnp.where(label > 0.5, p.scale_pos_weight, 1.0)


def _softmax_grad_hess(margin, label, num_class: int):
    """Per-class gradients for softmax cross-entropy: margin [B, K],
    integer labels [B] -> (g, h) each [B, K].

    Matches XGBoost's SoftmaxMultiClassObj exactly: h = max(2*p*(1-p), eps)
    — the factor 2 keeps leaf values on the same scale as the XGBoost
    baseline, and the clamp keeps -G/(H+lambda) finite at reg_lambda=0 for
    confidently-classified leaves.
    """
    import jax
    import jax.numpy as jnp

    pr = jax.nn.softmax(margin, axis=1)
    onehot = (label.astype(jnp.int32)[:, None]
              == jnp.arange(num_class, dtype=jnp.int32)).astype(jnp.float32)
    return pr - onehot, jnp.maximum(2.0 * pr * (1.0 - pr), 1e-16)


def _l1_threshold(G, alpha: float):
    """XGBoost's ThresholdL1: soft-threshold the gradient sum so both the
    split gain and the leaf value see |G| shrunk by alpha (CalcWeight /
    CalcGainGivenWeight semantics).  alpha=0 is the identity."""
    if alpha == 0.0:
        return G
    import jax.numpy as jnp

    return jnp.sign(G) * jnp.maximum(jnp.abs(G) - alpha, 0.0)


def _check_softmax_labels(label, num_class: int, what: str = "labels"):
    """Host-side class-id range check shared by every softmax entry point:
    out-of-range ids silently clamp under jit (take_along_axis / one-hot),
    so they must be rejected before tracing."""
    host = np.asarray(label)
    if host.size == 0:
        return
    CHECK(host.min() >= 0 and host.max() < num_class,
          f"softmax {what} must lie in [0, {num_class}); "
          f"got range [{host.min()}, {host.max()}]")


def _parse_monotone(spec: str, num_feature: int):
    """'(1,0,-1)' / '1,0,-1' -> int32 [F] array, or None when empty/all
    zero (the zero-cost legacy path).  Empty entries are rejected — a
    dropped comma slot would silently shift every later constraint onto
    the wrong feature."""
    spec = (spec or "").strip().strip("()")
    if not spec:
        return None
    parts = spec.replace(" ", "").split(",")
    CHECK(all(v != "" for v in parts),
          f"monotone_constraints has an empty entry: {spec!r}")
    vals = [int(v) for v in parts]
    CHECK(len(vals) == num_feature,
          f"monotone_constraints has {len(vals)} entries for "
          f"{num_feature} features")
    CHECK(all(v in (-1, 0, 1) for v in vals),
          f"monotone_constraints entries must be -1/0/+1, got {vals}")
    arr = np.asarray(vals, np.int32)
    return None if not arr.any() else arr


def _build_tree(bins, g, h, max_depth: int, num_bins: int, reg_lambda: float,
                min_child_weight: float, learning_rate: float,
                model_axis: Optional[str] = None, method: str = "scatter",
                onehot=None, min_split_loss: float = 0.0, feat_mask=None,
                missing: bool = False, reg_alpha: float = 0.0,
                monotone=None, level_mask_fn=None,
                max_delta_step: float = 0.0):
    """Grow one tree level-by-level; returns (split_feat, split_bin,
    leaf_value, default_left, split_gain, split_cover, margin_delta).
    Pure jax, shapes static in (max_depth, num_bins, F).

    ``feat_mask`` ([F] bool, optional) disables features for this tree
    (colsample); ``min_split_loss`` is the XGBoost gamma pruning threshold.

    ``missing=True`` is sparsity-aware split finding (XGBoost's algorithm
    3): rows whose feature is missing carry the reserved bin
    ``num_bins - 1``; every candidate split is scored twice from the SAME
    cumsums — missing mass on the left vs on the right — and the better
    direction is stored per node in ``default_left``.  The histogram
    kernels are untouched: the missing bin is just the last bin.

    ``monotone`` ([F] int in {-1, 0, +1}, or None) enforces monotone
    response per feature the XGBoost way: candidate splits whose child
    weights violate the direction are masked, every node carries a
    [lower, upper] weight interval, children of a constrained split split
    that interval at the clamped midpoint, and leaf weights clamp into
    their interval — together these guarantee monotonic predictions.
    (Gains are scored before the interval clamp — a mild difference from
    XGBoost's interval-aware scoring that affects split choice, never the
    monotonicity guarantee.  The ``max_delta_step`` clamp, by contrast,
    DOES enter gain scoring, via ``_score``.)
    """
    import jax.numpy as jnp

    B, F = bins.shape
    n_internal = 2 ** max_depth - 1
    split_feat = jnp.full((n_internal,), -1, dtype=jnp.int32)
    split_bin = jnp.zeros((n_internal,), dtype=jnp.int32)
    default_left = jnp.zeros((n_internal,), dtype=jnp.bool_)
    split_gain = jnp.zeros((n_internal,), dtype=jnp.float32)
    split_cover = jnp.zeros((n_internal,), dtype=jnp.float32)
    node = jnp.zeros((B,), dtype=jnp.int32)  # node id within the level
    fiota = jnp.arange(F, dtype=jnp.int32)
    miss_id = num_bins - 1
    if monotone is not None:
        mono = jnp.asarray(monotone, jnp.int32)          # [F]
        # per-node weight interval, split at the midpoint on constrained
        # splits (XGBoost's bound propagation)
        node_lo = jnp.full((1,), -jnp.inf, jnp.float32)
        node_hi = jnp.full((1,), jnp.inf, jnp.float32)

    for depth in range(max_depth):
        n_nodes = 2 ** depth
        level_off = n_nodes - 1
        G, H = grad_histogram(bins, node, g, h, n_nodes, num_bins,
                              model_axis=model_axis, method=method,
                              onehot=onehot)             # [n, F, nbins]
        GL = jnp.cumsum(G, axis=-1)
        HL = jnp.cumsum(H, axis=-1)
        GT = GL[..., -1:]
        HT = HL[..., -1:]
        lam = reg_lambda

        mds = max_delta_step

        def _clamp_w(w):
            return jnp.clip(w, -mds, mds) if mds > 0.0 else w

        def _opt_w(Gv, Hv):
            # the (possibly mds-clamped) optimum leaf weight — the ONE
            # definition shared by gain scoring, monotone masking, and the
            # monotone interval midpoints, so they can never desynchronize
            return _clamp_w(-_l1_threshold(Gv, reg_alpha) / (Hv + lam))

        def _weights(GLv, HLv):
            return _opt_w(GLv, HLv), _opt_w(GT - GLv, HT - HLv)

        def _score(Gv, Hv):
            # -2x the leaf objective at the (possibly clamped) optimum
            # weight; algebraically equal to ThresholdL1(G)^2/(H+lam)
            # when max_delta_step leaves the weight unclamped, so split
            # choices under the cap match XGBoost's CalcWeight-clamped
            # CalcGain rather than ignoring the cap.  Known deviation:
            # with reg_alpha>0 AND a binding cap, the alpha term here is
            # -2a|w| (the self-consistent -2x objective) where XGBoost's
            # CalcGain adds +a|w| — gains, and possibly argmax splits,
            # differ from XGBoost in that corner
            if mds == 0.0:
                return _l1_threshold(Gv, reg_alpha) ** 2 / (Hv + lam)
            w = _opt_w(Gv, Hv)
            return (-(2.0 * Gv * w + (Hv + lam) * w * w)
                    - 2.0 * reg_alpha * jnp.abs(w))

        def _gain(GLv, HLv):
            GRv = GT - GLv
            HRv = HT - HLv
            gn = (_score(GLv, HLv) + _score(GRv, HRv)
                  - _score(GT, HT))                      # [n, F, nbins]
            ok = (HLv >= min_child_weight) & (HRv >= min_child_weight)
            if monotone is not None:
                wl, wr = _weights(GLv, HLv)
                c = mono[None, :, None]
                ok = ok & ~(c * (wl - wr) > 0)           # violating splits
            return gn, ok

        gain, valid = _gain(GL, HL)
        if missing:
            # default-right scored above (thresholds below the missing bin
            # exclude its mass from GL, so it lands right for free); score
            # default-left by shifting the missing mass into the left sums
            gain_l, valid_l = _gain(GL + G[..., miss_id:miss_id + 1],
                                    HL + H[..., miss_id:miss_id + 1])
            gain = jnp.where(valid, gain, -jnp.inf)
            gain_l = jnp.where(valid_l, gain_l, -jnp.inf)
            go_left_default = gain_l > gain
            gain = jnp.maximum(gain, gain_l)
            valid = valid | valid_l
        # splitting on the last bin sends everything left: never valid
        # (with missing handling the last REAL threshold is num_bins - 2,
        # which separates non-missing from missing — allowed)
        valid = valid & (jnp.arange(num_bins) < num_bins - 1)[None, None, :]
        if level_mask_fn is not None:
            # the level/node draw consumes the tree mask (nested sampling)
            valid = valid & level_mask_fn(depth, n_nodes,
                                          feat_mask)[:, :, None]
        elif feat_mask is not None:
            valid = valid & feat_mask[None, :, None]
        gain = jnp.where(valid, gain, -jnp.inf)
        flat = gain.reshape(n_nodes, F * num_bins)
        best = jnp.argmax(flat, axis=-1)                 # [n]
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=-1)[:, 0]
        bf = (best // num_bins).astype(jnp.int32)
        bb = (best % num_bins).astype(jnp.int32)
        do_split = best_gain > min_split_loss
        sf = jnp.where(do_split, bf, -1)
        if missing:
            dl = jnp.take_along_axis(
                go_left_default.reshape(n_nodes, F * num_bins),
                best[:, None], axis=-1)[:, 0] & do_split
        else:
            dl = jnp.zeros((n_nodes,), jnp.bool_)
        lvl = level_off + jnp.arange(n_nodes)
        split_feat = split_feat.at[lvl].set(sf)
        split_bin = split_bin.at[lvl].set(bb)
        default_left = default_left.at[lvl].set(dl)
        split_gain = split_gain.at[lvl].set(
            jnp.where(do_split, best_gain, 0.0))
        split_cover = split_cover.at[lvl].set(
            jnp.where(do_split, HT[:, 0, 0], 0.0))
        if monotone is not None:
            # child intervals: the chosen split's child weights set the
            # midpoint; constrained features split the node interval there
            def _at_best(a):
                return jnp.take_along_axis(
                    a.reshape(n_nodes, F * num_bins), best[:, None],
                    axis=-1)[:, 0]

            # gather the chosen split's sums first: wl/wr become
            # [n]-sized math instead of full [n, F, nbins] passes
            GLb, HLb = _at_best(GL), _at_best(HL)
            if missing:
                GLb = jnp.where(dl, _at_best(GL + G[..., miss_id:miss_id + 1]),
                                GLb)
                HLb = jnp.where(dl, _at_best(HL + H[..., miss_id:miss_id + 1]),
                                HLb)
            GTn, HTn = GT[:, 0, 0], HT[:, 0, 0]
            wl = _opt_w(GLb, HLb)
            wr = _opt_w(GTn - GLb, HTn - HLb)
            wl = jnp.clip(wl, node_lo, node_hi)
            wr = jnp.clip(wr, node_lo, node_hi)
            mid = 0.5 * (wl + wr)
            c_node = jnp.where(do_split, mono[bf], 0)    # [n]
            # c=+1: left subtree weights <= mid <= right subtree weights
            lo_l = node_lo
            hi_l = jnp.where(c_node > 0, jnp.minimum(node_hi, mid), node_hi)
            lo_r = jnp.where(c_node > 0, jnp.maximum(node_lo, mid), node_lo)
            hi_r = node_hi
            lo_l = jnp.where(c_node < 0, jnp.maximum(node_lo, mid), lo_l)
            hi_r = jnp.where(c_node < 0, jnp.minimum(node_hi, mid), hi_r)
            node_lo = jnp.stack([lo_l, lo_r], axis=1).reshape(-1)
            node_hi = jnp.stack([hi_l, hi_r], axis=1).reshape(-1)
        # advance every row one level.  The per-row feature pick is a
        # compare-select-reduce over the (28-lane) feature axis, NOT a
        # take_along_axis gather: profiled on v5e the gather lowering costs
        # ~1.7 ms/level (52% of the whole round) while this select-sum is
        # ~0.1 ms — rows' split features come from a tiny per-node table, so
        # the one-hot select is the TPU-shaped formulation.
        nf = sf[node]                                    # [B]
        row_bin = jnp.sum(jnp.where(nf[:, None] == fiota[None, :], bins, 0),
                          axis=1)
        go_right = (row_bin > bb[node]) & (nf >= 0)
        if missing:
            # missing rows sit at bin num_bins-1 > any threshold, so they
            # already go right; default-left overrides that
            go_right = go_right & ~((row_bin == miss_id) & dl[node])
        node = node * 2 + go_right.astype(jnp.int32)

    import jax

    n_leaf = 2 ** max_depth
    if method in ("onehot", "pallas", "pallas_fused"):
        # leaf sums as a (tiny) f32 matmul — TPU scatter-adds serialise
        leafhot = (node[:, None] == jnp.arange(n_leaf, dtype=node.dtype)
                   ).astype(jnp.float32)                 # [B, n_leaf]
        gh = jnp.stack([g, h], axis=1)                   # [B, 2]
        sums = jax.lax.dot_general(leafhot, gh, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        Gl, Hl = sums[:, 0], sums[:, 1]
    else:
        Gl = jax.ops.segment_sum(g, node, num_segments=n_leaf)
        Hl = jax.ops.segment_sum(h, node, num_segments=n_leaf)
    leaf_w = -_l1_threshold(Gl, reg_alpha) / (Hl + reg_lambda)
    if max_delta_step > 0.0:
        leaf_w = jnp.clip(leaf_w, -max_delta_step, max_delta_step)
    if monotone is not None:
        leaf_w = jnp.clip(leaf_w, node_lo, node_hi)
    leaf_value = leaf_w * learning_rate
    margin_delta = leaf_value[node]
    return (split_feat, split_bin, leaf_value, default_left, split_gain,
            split_cover, margin_delta)


def _tree_sampling(p: "GBDTParam", rnd, B: int, F: int, class_index: int = 0):
    """Per-tree (row_weight, feature_mask) for subsample/colsample; both
    None at the default rates so the bench path traces unchanged.  ``rnd``
    is the (traced) round index; sampling is deterministic in
    (seed, rnd, class_index) — each of a softmax round's K trees draws its
    own subset, as XGBoost samples per tree, not per round.
    """
    import jax
    import jax.numpy as jnp

    row_w = None
    fmask = None
    if p.subsample < 1.0 or p.colsample_bytree < 1.0:
        key = jax.random.fold_in(jax.random.PRNGKey(p.seed),
                                 jnp.asarray(rnd, jnp.uint32))
        if class_index:
            key = jax.random.fold_in(key, class_index)
        if p.subsample < 1.0:
            row_w = (jax.random.uniform(jax.random.fold_in(key, 0), (B,))
                     < p.subsample).astype(jnp.float32)
        if p.colsample_bytree < 1.0:
            u = jax.random.uniform(jax.random.fold_in(key, 1), (F,))
            fmask = u < p.colsample_bytree
            # never mask every feature: the cheapest column always stays
            fmask = fmask.at[jnp.argmin(u)].set(True)
    return row_w, fmask


def _level_mask_fn(p, rnd, F: int):
    """colsample_bylevel / colsample_bynode: fresh feature masks per tree
    depth (and per node for bynode), seeded by (seed, rnd, depth) —
    deterministic, trace-safe, never empty (each node's cheapest column
    always stays).  Returns ``mask(depth, n_nodes) -> [n_nodes, F]`` bool,
    or None when both rates are 1.0.  A softmax round's K trees share the
    draw (the grow closure has no class identity)."""
    if p.colsample_bylevel >= 1.0 and p.colsample_bynode >= 1.0:
        return None
    import jax
    import jax.numpy as jnp

    base = jax.random.fold_in(jax.random.PRNGKey(p.seed),
                              jnp.asarray(rnd, jnp.uint32))
    base = jax.random.fold_in(base, 7)   # domain-separate from row/col draws

    def mask(depth: int, n_nodes: int, tree_mask=None):
        # NESTED draws (XGBoost semantics): bylevel samples from the
        # bytree survivors, bynode from the bylevel survivors — independent
        # draws could intersect to an empty per-node feature set, silently
        # truncating the node into a leaf
        key = jax.random.fold_in(base, depth)
        allowed = (tree_mask if tree_mask is not None
                   else jnp.ones((F,), bool))
        if p.colsample_bylevel < 1.0:
            u = jnp.where(allowed, jax.random.uniform(key, (F,)), jnp.inf)
            allowed = ((u < p.colsample_bylevel) & allowed
                       ).at[jnp.argmin(u)].set(True)
        m = jnp.broadcast_to(allowed[None, :], (n_nodes, F))
        if p.colsample_bynode < 1.0:
            un = jnp.where(allowed[None, :],
                           jax.random.uniform(jax.random.fold_in(key, 1),
                                              (n_nodes, F)), jnp.inf)
            m = ((un < p.colsample_bynode) & m
                 ).at[jnp.arange(n_nodes), jnp.argmin(un, axis=1)].set(True)
        return m

    return mask


def _row_sampling(p, rnd, n_rows: int, B: int, F: int, class_index=0):
    """Per-tree sampling drawn over the UNPADDED row count, then padded to
    the working batch: the subsample draw must not depend on kernel row
    padding, or padded and unpadded entry points (fit_binned vs
    boost_round) would select different row subsets for the same data.
    Padding rows carry weight 0 regardless; the pad is shape-only."""
    import jax.numpy as jnp

    row_w, fmask = _tree_sampling(p, rnd, n_rows, F,
                                  class_index=class_index)
    if row_w is not None and B != n_rows:
        row_w = jnp.pad(row_w, (0, B - n_rows))
    return row_w, fmask


def _softmax_round(p, bins, margin, label, weight, rnd, grow,
                   n_rows=None):
    """One multiclass boosting round: K trees from one margin snapshot
    (XGBoost multi:softmax — gradients evaluated before any of the round's
    K updates land), each tree drawing its own row/feature subset.
    ``grow`` is the caller's _build_tree closure."""
    import jax.numpy as jnp

    K = p.num_class
    B = bins.shape[0]
    n_rows = B if n_rows is None else n_rows
    g_all, h_all = _softmax_grad_hess(margin, label, K)
    trees = []
    for k in range(K):
        row_w, fmask = _row_sampling(p, rnd, n_rows, B, bins.shape[1],
                                     class_index=k)
        w = weight if row_w is None else weight * row_w
        trees.append(grow(bins, g_all[:, k] * w, h_all[:, k] * w, rnd,
                          fmask))
    delta = jnp.stack([t[6] for t in trees], axis=1)     # [B, K]
    return margin + delta, tuple(
        jnp.stack([t[i] for t in trees]) for i in range(6))


def _route_tree(split_feat, split_bin, default_left, bins,
                max_depth: int, miss_id: int = -1):
    """Leaf slot of every row in one tree (static-depth gathers).

    ``miss_id`` >= 0 enables sparsity-aware routing: rows whose split
    feature carries that bin follow the node's learned default direction
    instead of the threshold compare.
    """
    import jax.numpy as jnp

    B, F = bins.shape
    node = jnp.zeros((B,), dtype=jnp.int32)
    fiota = jnp.arange(F, dtype=jnp.int32)
    for depth in range(max_depth):
        level_off = 2 ** depth - 1
        sf = split_feat[level_off + node]
        sb = split_bin[level_off + node]
        # select-sum instead of take_along_axis: see _build_tree routing note
        row_bin = jnp.sum(jnp.where(sf[:, None] == fiota[None, :], bins, 0),
                          axis=1)
        go_right = (row_bin > sb) & (sf >= 0)
        if miss_id >= 0:
            dl = default_left[level_off + node]
            go_right = go_right & ~((row_bin == miss_id) & dl)
        node = node * 2 + go_right.astype(jnp.int32)
    return node


def _predict_tree(split_feat, split_bin, leaf_value, default_left, bins,
                  max_depth: int, miss_id: int = -1):
    """Route every row down one tree and read its leaf value."""
    return leaf_value[_route_tree(split_feat, split_bin, default_left, bins,
                                  max_depth, miss_id)]


def _per_tree(fn, arrays, multiclass: bool):
    """Apply a per-tree function over one round's arrays, stacking the K
    class trees on axis 1 for softmax ensembles — the single definition of
    the multiclass tree layout used by predict / staged losses / leaves."""
    import jax.numpy as jnp

    if multiclass:
        K = arrays[0].shape[0]
        return jnp.stack([fn(*(a[k] for a in arrays)) for k in range(K)],
                         axis=1)
    return fn(*arrays)


class GBDT:
    """Histogram gradient-boosted trees over binned dense features."""

    def __init__(self, param: GBDTParam, num_feature: int,
                 model_axis: Optional[str] = None):
        CHECK(param.objective != "softmax" or param.num_class >= 2,
              "objective=softmax needs num_class >= 2")
        CHECK(param.scale_pos_weight == 1.0 or param.objective == "logistic",
              f"scale_pos_weight={param.scale_pos_weight} only applies to "
              f"objective=logistic (got {param.objective!r}); it would "
              f"silently do nothing here")
        self._monotone = _parse_monotone(param.monotone_constraints,
                                         num_feature)
        self.param = param
        self.num_feature = num_feature
        self.model_axis = model_axis
        self.boundaries: Optional[np.ndarray] = None  # [F, num_bins-1]

    # -- binning --------------------------------------------------------------
    def make_bins(self, sample: np.ndarray, comm=None,
                  count: Optional[int] = None) -> np.ndarray:
        """Fit quantile boundaries from a host sample; returns them.

        ``comm`` (rabit-shaped, e.g. ``dmlc_core_tpu.collective``) makes the
        boundaries consistent across data-parallel workers via the merged
        quantile summary (:func:`..ops.histogram.distributed_quantile_
        boundaries`) — every rank must call with its own shard's sample.
        Without it, each worker bins on its local sample only, which forks
        split semantics across shards.  When ``sample`` is a capped
        subsample of the shard, pass the shard's true row count as
        ``count`` so imbalanced shards merge with their real mass.
        """
        CHECK(sample.shape[1] == self.num_feature, "sample feature dim mismatch")
        # sparsity-aware mode reserves the last bin id for missing values:
        # finite values quantile-bin into [0, num_bins - 2]
        eff_bins = (self.param.num_bins - 1 if self.param.handle_missing
                    else self.param.num_bins)
        # safe publication, not a race: the continuous trainer fits edges
        # once on its ingest thread and only then publishes the ensemble
        # under its lock; the publish clock cannot reach a boundaries read
        # until it observes that ensemble under the same lock
        # dmlclint: disable=race-unlocked-shared-write
        self.boundaries = distributed_quantile_boundaries(
            sample, eff_bins, comm=comm, count=count)
        return self.boundaries

    def set_boundaries(self, boundaries: np.ndarray) -> None:
        """Install externally computed quantile boundaries — e.g. a
        streaming :class:`~dmlc_core_tpu.bridge.binning.HostBinner`'s
        (``model.set_boundaries(binner.boundaries)``) — instead of
        :meth:`make_bins`' sample fit.  The shape contract is the same:
        ``[num_feature, eff_bins - 1]`` where the sparsity-aware mode
        reserves the last bin id for missing values."""
        boundaries = np.asarray(boundaries, dtype=np.float32)
        eff_bins = (self.param.num_bins - 1 if self.param.handle_missing
                    else self.param.num_bins)
        CHECK(boundaries.shape == (self.num_feature, eff_bins - 1),
              f"boundaries shape {boundaries.shape} != "
              f"{(self.num_feature, eff_bins - 1)} (num_bins="
              f"{self.param.num_bins}, handle_missing="
              f"{self.param.handle_missing})")
        self.boundaries = boundaries

    def bin_features(self, x):
        CHECK(self.boundaries is not None, "call make_bins first")
        miss = (self.param.num_bins - 1 if self.param.handle_missing
                else None)
        return apply_bins(x, self.boundaries, missing_bin=miss)

    # -- compiled round/predict ----------------------------------------------
    def _method(self, *arrays, batch: Optional[int] = None) -> str:
        method = resolve_hist_method(self.param.hist_method, *arrays)
        if method in ("pallas", "pallas_fused"):
            from dmlc_core_tpu.ops.hist_pallas import (hist_node_block,
                                                       sharded_hist_plan)

            # the kernel keeps a [2n, F*nbins] f32 accumulator resident in
            # VMEM; deeper levels sweep node blocks (plain kernel only), and
            # the onehot fallback kicks in only when even an 8-node block
            # overflows.  Decide up front so the fallback still amortises
            # its matmul RHS across rounds.  ``batch`` is the row count
            # grad_histogram will actually see (padded for fit, raw for
            # boost_round) so this gate and the in-trace one in
            # grad_histogram cannot disagree.
            deepest = 2 ** (self.param.max_depth - 1)
            if self.model_axis is not None:
                # model-sharded hist keeps the kernel via shard_map when an
                # ambient mesh is set and features split evenly; each shard
                # then only holds an F/mp slice of the accumulator
                mesh = sharded_hist_plan(self.model_axis, self.num_feature,
                                         deepest, self.param.num_bins,
                                         batch=batch)
                if mesh is None:
                    method = "onehot"
                elif method == "pallas_fused":
                    mp = mesh.shape[self.model_axis]
                    if hist_node_block(deepest, self.num_feature // mp,
                                       self.param.num_bins) < deepest:
                        method = "pallas"
            else:
                block = hist_node_block(deepest, self.num_feature,
                                        self.param.num_bins)
                if block is None:
                    method = "onehot"
                elif block < deepest and method == "pallas_fused":
                    method = "pallas"   # blocked sweeps have no fused variant
        return method

    @functools.lru_cache(maxsize=None)
    def _round_fn(self, method: str = "scatter"):
        import jax

        p = self.param

        def one_round(margin, bins, label, weight, rnd):
            bins = _widen_bins(bins)
            onehot = (bin_onehot(bins, p.num_bins)
                      if method == "onehot" else None)

            def grow(bins_, g, h, rnd_, fmask):
                return _build_tree(
                    bins_, g, h, p.max_depth, p.num_bins, p.reg_lambda,
                    p.min_child_weight, p.learning_rate, self.model_axis,
                    method=method, onehot=onehot,
                    min_split_loss=p.min_split_loss, feat_mask=fmask,
                    missing=p.handle_missing, reg_alpha=p.reg_alpha,
                    monotone=self._monotone,
                    level_mask_fn=_level_mask_fn(p, rnd_, bins_.shape[1]),
                    max_delta_step=p.max_delta_step)

            if p.objective == "softmax":
                return _softmax_round(p, bins, margin, label, weight, rnd,
                                      grow)
            g, h = _grad_hess(margin, label, p.objective)
            row_w, fmask = _tree_sampling(p, rnd, bins.shape[0],
                                          bins.shape[1])
            if row_w is not None:
                weight = weight * row_w
            sf, sb, lv, dl, sg, sc, delta = grow(bins, g * weight,
                                                 h * weight, rnd, fmask)
            return margin + delta, (sf, sb, lv, dl, sg, sc)

        return jax.jit(one_round)

    @functools.lru_cache(maxsize=None)
    def _fit_fn(self, num_rounds: int, method: str = "scatter"):
        return self._build_fit(num_rounds, method, with_eval=False)

    @functools.lru_cache(maxsize=None)
    def _fit_eval_fn(self, num_rounds: int, method: str = "scatter",
                     eval_metric: str = "loss"):
        """:meth:`_fit_fn` + per-round eval-margin accumulation and
        train/eval losses — the whole eval-tracked fit is ONE compiled
        program (the round-by-round host loop costs ~a round-trip per
        round; early stopping becomes a host post-pass over the losses)."""
        return self._build_fit(num_rounds, method, with_eval=True,
                               eval_metric=eval_metric)

    def _build_fit(self, num_rounds: int, method: str, with_eval: bool,
                   eval_metric: str = "loss"):
        """One jitted scan-fit builder serving both entry points — the
        training body (padding, sampling, grow) must never fork between
        the plain and eval-tracked fits."""
        import jax
        import jax.lax as lax

        p = self.param
        d = p.max_depth
        miss_id = p.num_bins - 1 if p.handle_missing else -1

        def fit(bins, label, weight, ev_bins=None, ev_label=None):
            import jax.numpy as jnp

            bins = _widen_bins(bins)
            if ev_bins is not None:
                ev_bins = _widen_bins(ev_bins)
            n_rows = bins.shape[0]
            if method in ("pallas", "pallas_fused"):
                from dmlc_core_tpu.ops.hist_pallas import BLOCK_ROWS

                # pad rows to the kernel's tile multiple ONCE per fit (padded
                # rows carry weight 0, so they vanish from every histogram);
                # per-call padding inside the kernel wrapper then no-ops
                pad = -n_rows % BLOCK_ROWS
                if pad:
                    bins = jnp.pad(bins, ((0, pad), (0, 0)))
                    label = jnp.pad(label, (0, pad))
                    weight = jnp.pad(weight, (0, pad))
            B = bins.shape[0]
            weight = _apply_pos_weight(weight, label, p)
            # the bin one-hot (the matmul RHS) is invariant across rounds and
            # levels: materialise once, outside the scan
            onehot = (bin_onehot(bins, p.num_bins)
                      if method == "onehot" else None)
            K = p.num_class if p.objective == "softmax" else 1

            def grow(bins_, g, h, rnd, fmask):
                return _build_tree(
                    bins_, g, h, p.max_depth, p.num_bins, p.reg_lambda,
                    p.min_child_weight, p.learning_rate, self.model_axis,
                    method=method, onehot=onehot,
                    min_split_loss=p.min_split_loss, feat_mask=fmask,
                    missing=p.handle_missing, reg_alpha=p.reg_alpha,
                    monotone=self._monotone,
                    level_mask_fn=_level_mask_fn(p, rnd, bins_.shape[1]),
                    max_delta_step=p.max_delta_step)

            def round_step(margin, rnd):
                if K == 1:
                    row_w, fmask = _row_sampling(p, rnd, n_rows, B,
                                                 bins.shape[1])
                    w = weight if row_w is None else weight * row_w
                    g, h = _grad_hess(margin, label, p.objective)
                    sf, sb, lv, dl, sg, sc, delta = grow(bins, g * w,
                                                         h * w, rnd, fmask)
                    return margin + delta, (sf, sb, lv, dl, sg, sc)
                return _softmax_round(p, bins, margin, label, weight, rnd,
                                      grow, n_rows=n_rows)

            margin0 = jnp.full((B,) if K == 1 else (B, K), p.base_score,
                               jnp.float32)
            rounds = jnp.arange(num_rounds, dtype=jnp.uint32)

            if not with_eval:
                margin, trees = lax.scan(round_step, margin0, rounds)
                return TreeEnsemble(*trees), margin[:n_rows]

            def eval_body(carry, rnd):
                margin, ev_margin = carry
                margin, trees = round_step(margin, rnd)
                sf, sb, lv, dl = trees[:4]
                if K == 1:
                    ev_delta = _predict_tree(sf, sb, lv, dl, ev_bins, d,
                                             miss_id)
                else:
                    ev_delta = jnp.stack(
                        [_predict_tree(sf[k], sb[k], lv[k], dl[k], ev_bins,
                                       d, miss_id) for k in range(K)],
                        axis=1)
                ev_margin = ev_margin + ev_delta
                # losses on the REAL rows (padded rows carry weight 0 but
                # _logloss is unweighted)
                tr_loss = _logloss(margin[:n_rows], label[:n_rows],
                                   p.objective)
                ev_loss = _eval_metric_fn(eval_metric,
                                          p.objective)(ev_margin, ev_label)
                return (margin, ev_margin), (trees, tr_loss, ev_loss)

            ev0 = jnp.full((ev_bins.shape[0],) if K == 1
                           else (ev_bins.shape[0], K), p.base_score,
                           jnp.float32)
            (margin, _), (trees, trl, evl) = lax.scan(
                eval_body, (margin0, ev0), rounds)
            return TreeEnsemble(*trees), margin[:n_rows], trl, evl

        return jax.jit(fit)

    @functools.lru_cache(maxsize=None)
    def _predict_fn(self):
        import jax
        import jax.lax as lax
        import jax.numpy as jnp

        d = self.param.max_depth
        miss_id = (self.param.num_bins - 1 if self.param.handle_missing
                   else -1)

        def predict(ensemble: TreeEnsemble, bins):
            bins = _widen_bins(bins)
            B = bins.shape[0]
            multiclass = ensemble.split_feat.ndim == 3

            def body(acc, tree):
                delta = _per_tree(
                    lambda sf, sb, lv, dl: _predict_tree(sf, sb, lv, dl,
                                                         bins, d, miss_id),
                    tree, multiclass)
                return acc + delta, None

            shape = ((B, ensemble.split_feat.shape[1]) if multiclass
                     else (B,))
            out, _ = lax.scan(body,
                              jnp.full(shape, self.param.base_score,
                                       jnp.float32),
                              (ensemble.split_feat, ensemble.split_bin,
                               ensemble.leaf_value, ensemble.default_left))
            return out

        return jax.jit(predict)

    # -- public API ------------------------------------------------------------
    def fit_binned(self, bins, label, weight=None) -> Tuple[TreeEnsemble, Any]:
        """Train on pre-binned features; returns (ensemble, final margin)."""
        import jax.numpy as jnp

        if self.param.objective == "softmax":
            _check_softmax_labels(label, self.param.num_class)
        weight = (jnp.ones(bins.shape[0], jnp.float32)
                  if weight is None else jnp.asarray(weight))
        bins = jnp.asarray(bins)
        from dmlc_core_tpu.ops.hist_pallas import BLOCK_ROWS

        # fit pads rows to the kernel tile before the hist sees them
        padded = -(-bins.shape[0] // BLOCK_ROWS) * BLOCK_ROWS
        return self._fit_fn(self.param.num_boost_round,
                            self._method(bins, batch=padded))(
            bins, jnp.asarray(label, jnp.float32), weight)

    def boost_round(self, margin, bins, label, weight,
                    round_index: Optional[int] = None):
        """One boosting round (the unit train step for streaming/bench).

        ``round_index`` seeds the per-tree subsample/colsample draw (traced
        scalar: varying it does not recompile).  It is REQUIRED when
        sampling is enabled — otherwise every streamed round would silently
        draw the identical row/feature subset.
        """
        import jax.numpy as jnp

        if round_index is None:
            CHECK(self.param.subsample >= 1.0
                  and self.param.colsample_bytree >= 1.0
                  and self.param.colsample_bylevel >= 1.0
                  and self.param.colsample_bynode >= 1.0,
                  "boost_round needs round_index= when subsample/"
                  "colsample_by* are enabled (each tree must draw fresh "
                  "subsets)")
            round_index = 0
        weight = _apply_pos_weight(jnp.asarray(weight),
                                   jnp.asarray(label), self.param)
        return self._round_fn(self._method(bins, margin,
                                           batch=bins.shape[0]))(
            margin, bins, label, weight,
            jnp.asarray(round_index, jnp.uint32))

    def append_rounds(self, ensemble: Optional[TreeEnsemble], bins, label,
                      weight=None, *, num_rounds: int = 1,
                      margin=None, start_round: Optional[int] = None
                      ) -> Tuple[TreeEnsemble, Any]:
        """Append ``num_rounds`` boosting rounds trained on fresh (binned)
        data — the warm-start step of the continuous training ring
        (docs/training.md).  Returns ``(extended ensemble, final margin)``.

        The margin is seeded from the existing ensemble's own predictions
        on ``bins`` (pass ``margin`` to chain calls over the same batch
        without re-predicting).  The bin boundaries are NOT refit: the
        restored edges stay frozen, so the serving-side uint8 wire stays
        bitwise identical across refreshes.  ``start_round`` seeds the
        per-tree subsample/colsample draw — it defaults to
        ``ensemble.num_trees`` so appended trees continue the fresh-fit
        draw sequence instead of repeating it.

        ``ensemble=None`` starts a new ensemble from the base margin (the
        trainer's cold start: same sequence a fresh streaming fit runs).
        """
        import jax.numpy as jnp

        CHECK(num_rounds >= 1, "append_rounds needs num_rounds >= 1")
        bins = jnp.asarray(bins)
        label = jnp.asarray(label, jnp.float32)
        weight = (jnp.ones(bins.shape[0], jnp.float32)
                  if weight is None else jnp.asarray(weight))
        K = (self.param.num_class if self.param.objective == "softmax"
             else 1)
        if margin is None:
            if ensemble is None:
                shape = (bins.shape[0], K) if K > 1 else (bins.shape[0],)
                margin = jnp.full(shape, self.param.base_score, jnp.float32)
            else:
                margin = self.predict_margin(ensemble, bins)
        if start_round is None:
            start_round = 0 if ensemble is None else ensemble.num_trees
        new = []
        for r in range(num_rounds):
            margin, tree = self.boost_round(margin, bins, label, weight,
                                            round_index=start_round + r)
            new.append(tree)

        def stack(i):
            return np.stack([np.asarray(t[i]) for t in new], axis=0)

        def cat(old, i, dtype=None):
            fresh = stack(i)
            if dtype is not None:
                fresh = fresh.astype(dtype)
            if old is None:      # ensemble=None: the fresh trees ARE it
                return fresh
            old = np.asarray(old)
            return np.concatenate([old, fresh.astype(old.dtype)], axis=0)

        if ensemble is None:
            ensemble = TreeEnsemble(None, None, None, None, None, None)
        # pre-stats ensembles (old checkpoints) carry split_gain/cover =
        # None: keep them None — mixing absent and present stats would
        # fork the checkpoint schema mid-stream
        has_stats = (ensemble.split_feat is None
                     or ensemble.split_gain is not None)
        return TreeEnsemble(
            cat(ensemble.split_feat, 0),
            cat(ensemble.split_bin, 1),
            cat(ensemble.leaf_value, 2),
            cat(ensemble.default_left, 3, dtype=bool),
            cat(ensemble.split_gain, 4) if has_stats else None,
            cat(ensemble.split_cover, 5) if has_stats else None,
        ), margin

    def predict_margin(self, ensemble: TreeEnsemble, bins):
        return self._predict_fn()(ensemble, bins)

    def predict(self, ensemble: TreeEnsemble, bins):
        import jax
        import jax.numpy as jnp

        margin = self.predict_margin(ensemble, bins)
        if self.param.objective == "logistic":
            return 1.0 / (1.0 + jnp.exp(-margin))
        if self.param.objective == "softmax":
            return jax.nn.softmax(margin, axis=1)     # [B, K] probabilities
        return margin

    def predict_class(self, ensemble: TreeEnsemble, bins):
        """Hard class labels: argmax over classes (softmax) or the 0.5
        threshold (logistic); int32 [B]."""
        import jax.numpy as jnp

        CHECK(self.param.objective != "squared",
              "predict_class needs a classification objective")
        margin = self.predict_margin(ensemble, bins)
        if self.param.objective == "softmax":
            return jnp.argmax(margin, axis=1).astype(jnp.int32)
        return (margin > 0).astype(jnp.int32)

    # -- training with eval / early stopping ----------------------------------
    @functools.lru_cache(maxsize=None)
    def _tree_margin_fn(self):
        import jax

        d = self.param.max_depth
        miss_id = (self.param.num_bins - 1 if self.param.handle_missing
                   else -1)

        def one_tree(sf, sb, lv, dl, bins):
            return _predict_tree(sf, sb, lv, dl, _widen_bins(bins), d,
                                 miss_id)

        return jax.jit(one_tree)

    def fit_with_eval(self, bins, label, eval_bins=None, eval_label=None,
                      weight=None, early_stopping_rounds: int = 0,
                      compiled: bool = True, eval_metric: str = "loss"):
        """Boosting with validation loss tracking and early stopping.

        Returns (ensemble, history) where history is a list of per-round dicts
        (train margin loss and, when an eval set is given, eval loss).  With
        ``early_stopping_rounds`` > 0, stops when eval loss hasn't improved
        for that many rounds and truncates the ensemble to the best round.

        ``compiled=True`` (default, needs an eval set) runs the WHOLE
        eval-tracked fit as one jit — per-round losses come back as arrays
        and the sequential stopping rule is applied on the host afterwards,
        giving bit-identical results to the round-by-round loop at scan-fit
        speed (rounds past the stopping point are computed then discarded:
        on accelerators the flops are cheaper than per-round host syncs).
        ``compiled=False`` keeps the host-driven loop (debugging, or when
        per-round side effects are wanted).
        """
        import jax.numpy as jnp

        K = (self.param.num_class if self.param.objective == "softmax"
             else 1)
        if K > 1:
            _check_softmax_labels(label, K)
            if eval_label is not None:
                _check_softmax_labels(eval_label, K, what="eval labels")
        weight = (jnp.ones(bins.shape[0], jnp.float32)
                  if weight is None else jnp.asarray(weight))
        bins = jnp.asarray(bins)
        label = jnp.asarray(label, jnp.float32)
        if compiled and eval_bins is not None:
            return self._fit_with_eval_compiled(
                bins, label, jnp.asarray(eval_bins),
                jnp.asarray(eval_label, jnp.float32), weight,
                early_stopping_rounds, eval_metric)
        mshape = (bins.shape[0],) if K == 1 else (bins.shape[0], K)
        margin = jnp.full(mshape, self.param.base_score, jnp.float32)
        eval_margin = None
        if eval_bins is not None:
            eval_bins = jnp.asarray(eval_bins)
            eval_label = jnp.asarray(eval_label, jnp.float32)
            eshape = ((eval_bins.shape[0],) if K == 1
                      else (eval_bins.shape[0], K))
            eval_margin = jnp.full(eshape, self.param.base_score,
                                   jnp.float32)
        trees = []
        history = []
        stopper = _EarlyStop(early_stopping_rounds)
        metric_fn = _eval_metric_fn(eval_metric, self.param.objective)
        tree_margin = self._tree_margin_fn()
        for r in range(self.param.num_boost_round):
            margin, (sf, sb, lv, dl, sg, sc) = self.boost_round(
                margin, bins, label, weight, round_index=r)
            trees.append((sf, sb, lv, dl, sg, sc))
            entry = {"round": r,
                     "train_loss": float(_logloss(margin, label,
                                                  self.param.objective))}
            if eval_margin is not None:
                if K == 1:
                    delta = tree_margin(sf, sb, lv, dl, eval_bins)
                else:
                    # softmax rounds carry K trees: [K, ...] arrays
                    delta = jnp.stack(
                        [tree_margin(sf[k], sb[k], lv[k], dl[k], eval_bins)
                         for k in range(K)], axis=1)
                eval_margin = eval_margin + delta
                eval_loss = float(metric_fn(eval_margin, eval_label))
                entry["eval_loss"] = eval_loss
                if stopper.update(r, eval_loss):
                    trees = trees[:stopper.best_round + 1]
                    history.append(entry)
                    break
            history.append(entry)
        stacked = [jnp.stack([t[i] for t in trees]) for i in range(6)]
        return TreeEnsemble(*stacked), history

    def _fit_with_eval_compiled(self, bins, label, eval_bins, eval_label,
                                weight, early_stopping_rounds: int,
                                eval_metric: str = "loss"):
        """One-jit eval-tracked fit + host-side sequential stopping rule
        (see :meth:`fit_with_eval`); returns identical (ensemble, history)
        to the round-by-round loop."""
        from dmlc_core_tpu.ops.hist_pallas import BLOCK_ROWS

        R = self.param.num_boost_round
        padded = -(-bins.shape[0] // BLOCK_ROWS) * BLOCK_ROWS
        method = self._method(bins, batch=padded)
        ens, _, trl, evl = self._fit_eval_fn(R, method, eval_metric)(
            bins, label, weight, eval_bins, eval_label)
        trl = np.asarray(trl)
        evl = np.asarray(evl)
        history = []
        stopper = _EarlyStop(early_stopping_rounds)
        stop_after = R
        for r in range(R):
            history.append({"round": r, "train_loss": float(trl[r]),
                            "eval_loss": float(evl[r])})
            if stopper.update(r, float(evl[r])):
                stop_after = stopper.best_round + 1
                break
        if stop_after < R:
            ens = TreeEnsemble(*(None if a is None
                                 else np.asarray(a)[:stop_after]
                                 for a in ens))
        return ens, history

    @functools.lru_cache(maxsize=None)
    def _staged_losses_fn(self, metric: str = "loss"):
        import jax
        import jax.lax as lax
        import jax.numpy as jnp

        p = self.param
        d = p.max_depth
        miss_id = p.num_bins - 1 if p.handle_missing else -1
        K = p.num_class if p.objective == "softmax" else 1

        def staged(ensemble, bins, label):
            bins = _widen_bins(bins)
            B = bins.shape[0]

            def body(margin, tree):
                delta = _per_tree(
                    lambda sf, sb, lv, dl: _predict_tree(sf, sb, lv, dl,
                                                         bins, d, miss_id),
                    tree, K > 1)
                margin = margin + delta
                return margin, _eval_metric_fn(metric, p.objective)(margin,
                                                                    label)

            margin0 = jnp.full((B,) if K == 1 else (B, K), p.base_score,
                               jnp.float32)
            _, losses = lax.scan(body, margin0,
                                 (ensemble.split_feat, ensemble.split_bin,
                                  ensemble.leaf_value,
                                  ensemble.default_left))
            return losses

        return jax.jit(staged)

    @functools.lru_cache(maxsize=None)
    def _predict_leaf_fn(self):
        import jax
        import jax.lax as lax
        import jax.numpy as jnp

        d = self.param.max_depth
        miss_id = (self.param.num_bins - 1 if self.param.handle_missing
                   else -1)

        def leaves(ensemble, bins):
            bins = _widen_bins(bins)
            multiclass = ensemble.split_feat.ndim == 3

            def body(_, tree):
                out = _per_tree(
                    lambda sf, sb, dl: _route_tree(sf, sb, dl, bins, d,
                                                   miss_id),
                    tree, multiclass)
                return 0, out

            _, ids = lax.scan(body, 0,
                              (ensemble.split_feat, ensemble.split_bin,
                               ensemble.default_left))
            # scan stacks on axis 0 ([T, B(, K)]); XGBoost's pred_leaf is
            # row-major [B, T(, K)]
            return jnp.moveaxis(ids, 0, 1)

        return jax.jit(leaves)

    def predict_leaf(self, ensemble: TreeEnsemble, bins) -> np.ndarray:
        """Leaf index of every row in every tree (XGBoost pred_leaf):
        int32 [B, T] (or [B, T, K] for softmax), ids in [0, 2**max_depth).
        The standard input for leaf-embedding feature engineering."""
        import jax.numpy as jnp

        return np.asarray(self._predict_leaf_fn()(ensemble,
                                                  jnp.asarray(bins)))

    def staged_losses(self, ensemble: TreeEnsemble, bins, label,
                      metric: str = "loss") -> np.ndarray:
        """Per-round cumulative metric of the ensemble on any dataset —
        the learning curve, post-hoc, as one compiled scan over the tree
        axis.  ``metric``: loss (objective's own) | error | rmse | mae.
        [num_trees] f32."""
        import jax.numpy as jnp

        if self.param.objective == "softmax":
            _check_softmax_labels(label, self.param.num_class)
        return np.asarray(self._staged_losses_fn(metric)(
            ensemble, jnp.asarray(bins), jnp.asarray(label, jnp.float32)))

    # -- introspection / persistence ------------------------------------------
    def feature_importance(self, ensemble: TreeEnsemble,
                           kind: str = "weight") -> np.ndarray:
        """Per-feature importance (the XGBoost importance_type set):
        'weight' = split count, 'gain'/'total_gain' = mean/summed split
        gain, 'cover'/'total_cover' = mean/summed hessian mass at splits.
        Gain/cover need the split statistics recorded at fit time (absent
        on ensembles loaded from pre-stats checkpoints)."""
        kinds = ("weight", "gain", "total_gain", "cover", "total_cover")
        CHECK(kind in kinds, f"importance kind {kind!r} not in {kinds}")
        sf = np.asarray(ensemble.split_feat).reshape(-1)
        mask = sf >= 0
        counts = np.bincount(sf[mask], minlength=self.num_feature)
        if kind == "weight":
            return counts.astype(np.float64)
        stat = (ensemble.split_gain if "gain" in kind
                else ensemble.split_cover)
        CHECK(stat is not None,
              f"{kind} importance needs split statistics; this ensemble "
              f"was loaded from a checkpoint without them — refit to get "
              f"them")
        stat = np.asarray(stat).reshape(-1)
        totals = np.bincount(sf[mask], weights=stat[mask],
                             minlength=self.num_feature).astype(np.float64)
        if kind.startswith("total_"):
            return totals
        return np.divide(totals, counts, out=np.zeros_like(totals),
                         where=counts > 0)

    def dump_trees(self, ensemble: TreeEnsemble,
                   feature_names=None) -> str:
        """Human-readable text dump of every tree (XGBoost get_dump
        style): internal nodes show the split feature, the REAL threshold
        value (bin id mapped back through the binning boundaries; routing
        is strict — rows with value < threshold go left, ties go right,
        matching apply_bins' side='right' searchsorted), the
        missing-row default direction, and the recorded gain/cover; leaves
        show their values.  No-split nodes collapse into their left
        subtree, matching the routing semantics."""
        CHECK(self.boundaries is not None,
              "dump_trees needs the binning boundaries; call make_bins or "
              "load_model first")
        sf_all = np.asarray(ensemble.split_feat)
        sb_all = np.asarray(ensemble.split_bin)
        lv_all = np.asarray(ensemble.leaf_value)
        dl_all = np.asarray(ensemble.default_left)
        sg_all = (None if ensemble.split_gain is None
                  else np.asarray(ensemble.split_gain))
        sc_all = (None if ensemble.split_cover is None
                  else np.asarray(ensemble.split_cover))
        multiclass = sf_all.ndim == 3
        lines = []

        def one_tree(sf, sb, lv, dl, sg, sc, title):
            lines.append(f"booster[{title}]:")
            d = self.param.max_depth

            def walk(node, depth, indent):
                if depth < d:
                    i = 2 ** depth - 1 + node    # flat level-order id
                    if sf[i] >= 0:
                        f = int(sf[i])
                        b = int(sb[i])
                        bounds = self.boundaries[f]
                        thr = (float(bounds[b]) if b < len(bounds)
                               else float("inf"))
                        name = (feature_names[f]
                                if feature_names is not None else f"f{f}")
                        miss = "yes" if (dl is not None and dl[i]) else "no"
                        extra = ""
                        if sg is not None:
                            extra = (f",gain={sg[i]:.6g}"
                                     f",cover={sc[i]:.6g}")
                        lines.append(f"{indent}{i}:[{name}<{thr:.6g}] "
                                     f"missing_left={miss}{extra}")
                        walk(node * 2, depth + 1, indent + "  ")
                        walk(node * 2 + 1, depth + 1, indent + "  ")
                        return
                # leaf or collapsed no-split subtree: rows fall through
                # left to the leaf slot
                leaf = node
                for _ in range(depth, d):
                    leaf = leaf * 2
                lines.append(f"{indent}leaf={lv[leaf]:.6g}")

            walk(0, 0, "  ")

        for t in range(ensemble.num_trees):
            if multiclass:
                for k in range(sf_all.shape[1]):
                    one_tree(sf_all[t, k], sb_all[t, k], lv_all[t, k],
                             dl_all[t, k],
                             None if sg_all is None else sg_all[t, k],
                             None if sc_all is None else sc_all[t, k],
                             f"{t}.class{k}")
            else:
                one_tree(sf_all[t], sb_all[t], lv_all[t], dl_all[t],
                         None if sg_all is None else sg_all[t],
                         None if sc_all is None else sc_all[t], str(t))
        return "\n".join(lines) + "\n"

    def save_model(self, uri: str, ensemble: TreeEnsemble,
                   extra: Optional[dict] = None) -> None:
        """Persist the model + binning boundaries to any URI.

        ``extra`` adds caller-owned numpy leaves to the payload (e.g. the
        sklearn facade's class labels); keys must not clash with the core
        schema.
        """
        from dmlc_core_tpu.bridge.checkpoint import save_checkpoint

        save_checkpoint(uri, self._model_payload(ensemble, extra))

    def _model_payload(self, ensemble: TreeEnsemble,
                       extra: Optional[dict] = None) -> dict:
        """The checkpoint pytree ``save_model`` writes (trees + binning
        boundaries + routing contract), as a dict — the single schema both
        the URI writer and :meth:`serving_state` build from."""
        CHECK(self.boundaries is not None, "model has no bin boundaries")
        payload = {
            "split_feat": np.asarray(ensemble.split_feat),
            "split_bin": np.asarray(ensemble.split_bin),
            "leaf_value": np.asarray(ensemble.leaf_value),
            "default_left": np.asarray(ensemble.default_left),
            "boundaries": np.asarray(self.boundaries),
            # binning contract: loading into a param with a different
            # missing-mode would silently mis-bin NaNs and ignore the
            # learned default directions — record it so load can refuse
            "handle_missing": np.array([int(self.param.handle_missing)]),
            # predict-time contract: _predict_fn adds the loader's
            # base_score, so a mismatch silently shifts every margin
            "base_score": np.array([self.param.base_score], np.float32),
        }
        # omit absent stats (ensembles loaded from pre-stats checkpoints):
        # np.asarray(None) would write an object-dtype leaf that can never
        # be loaded back
        if ensemble.split_gain is not None:
            payload["split_gain"] = np.asarray(ensemble.split_gain)
        if ensemble.split_cover is not None:
            payload["split_cover"] = np.asarray(ensemble.split_cover)
        for k, v in (extra or {}).items():
            CHECK(k not in payload, f"extra key {k!r} clashes with the "
                                    f"model schema")
            arr = np.asarray(v)
            # object arrays serialize as raw pointers and can never load
            # back (e.g. pandas .to_numpy() labels); reject at save time
            CHECK(arr.dtype != object,
                  f"extra key {k!r} has object dtype; convert to a "
                  f"numeric or fixed-width string array first")
            payload[k] = arr
        return payload

    def load_model(self, uri: str) -> TreeEnsemble:
        from dmlc_core_tpu.bridge.checkpoint import load_checkpoint

        return self.load_model_dict(load_checkpoint(uri))

    def load_model_dict(self, flat: dict) -> TreeEnsemble:
        """Restore from an already-loaded checkpoint dict — callers that
        read extra payload keys themselves (the sklearn facade) avoid a
        second full fetch of the URI (and the old/new-mix race a re-read
        of a concurrently replaced remote object would open)."""
        # keys are jax.tree_util.keystr paths; save_model writes a flat dict,
        # so each key is exactly "['<name>']" — match it exactly (a substring
        # match would alias e.g. 'split_feat' with any future key containing
        # that text).  default=... marks keys older checkpoints lack.
        _REQUIRED = object()

        def get(name, default=_REQUIRED):
            key = f"['{name}']"
            if key not in flat:
                CHECK(default is not _REQUIRED,
                      f"checkpoint is missing required key {name!r}")
                return default
            return flat[key]

        self.boundaries = np.asarray(get("boundaries"), dtype=np.float32)
        sf = get("split_feat")
        # models saved before sparsity-aware splits have no default_left /
        # handle_missing keys: all-False + non-missing reproduces their
        # exact routing
        dl = get("default_left", default=None)
        dl = (np.asarray(dl).astype(bool) if dl is not None
              else np.zeros(np.asarray(sf).shape, dtype=bool))
        hm = get("handle_missing", default=None)
        saved_hm = bool(hm[0]) if hm is not None else False
        CHECK(saved_hm == self.param.handle_missing,
              f"model was saved with handle_missing={saved_hm} but this "
              f"GBDT has handle_missing={self.param.handle_missing}; the "
              f"binning and routing contracts differ — construct the "
              f"loader with the matching GBDTParam")
        bs = get("base_score", default=None)
        saved_bs = float(bs[0]) if bs is not None else 0.0
        CHECK(abs(saved_bs - self.param.base_score) < 1e-9,
              f"model was saved with base_score={saved_bs} but this GBDT "
              f"has base_score={self.param.base_score}; predictions would "
              f"silently shift — construct the loader with the matching "
              f"GBDTParam")
        sg = get("split_gain", default=None)
        sc = get("split_cover", default=None)
        return TreeEnsemble(sf, get("split_bin"), get("leaf_value"), dl,
                            None if sg is None else np.asarray(sg),
                            None if sc is None else np.asarray(sc))

    def serving_state(self, ensemble: TreeEnsemble,
                      extra: Optional[dict] = None) -> dict:
        """Self-describing checkpoint pytree for the model-lifecycle path
        (docs/serving.md): the :meth:`save_model` payload plus a
        ``serve_meta`` leaf recording everything a loader needs to rebuild
        this GBDT *without* knowing its params up front — num_feature,
        num_bins, max_depth, objective, num_class.  The binner edges
        (``set_boundaries`` contract) ride the same blob, so a swapped-in
        model always serves through the exact bins it trained on.

        Feed this to :class:`~dmlc_core_tpu.bridge.checkpoint.
        CheckpointManager`.save and restore with :meth:`from_serving_state`.
        ``extra`` adds caller-owned leaves on top (the continuous trainer's
        ingest cursor rides the same atomic blob as the trees it trained);
        unknown keys are ignored by every loader.
        """
        merged = {
            _SERVE_META_KEY: np.array(
                [_SERVE_SCHEMA, self.num_feature, self.param.num_bins,
                 self.param.max_depth,
                 _OBJECTIVE_CODES[self.param.objective],
                 self.param.num_class],
                np.int64)}
        for k, v in (extra or {}).items():
            CHECK(k != _SERVE_META_KEY, "extra must not override serve_meta")
            merged[k] = v
        return self._model_payload(ensemble, merged)

    @classmethod
    def from_serving_state(cls, flat: dict) -> Tuple["GBDT", TreeEnsemble]:
        """Rebuild (GBDT, ensemble) from a flat :func:`~dmlc_core_tpu.
        bridge.checkpoint.load_checkpoint` dict written by
        :meth:`serving_state` — boundaries installed, predictions
        bitwise-equal to the saver's (round-trip asserted in
        tests/test_lifecycle.py)."""
        meta = flat.get(f"['{_SERVE_META_KEY}']")
        CHECK(meta is not None,
              "checkpoint has no serve_meta leaf — not a serving_state "
              "blob (train-side save_model checkpoints need their "
              "GBDTParam known to the loader)")
        meta = np.asarray(meta).reshape(-1)
        CHECK(meta.shape[0] == 6 and int(meta[0]) == _SERVE_SCHEMA,
              f"unsupported serve_meta schema {meta!r}")
        _, num_feature, num_bins, max_depth, obj_code, num_class = (
            int(v) for v in meta)
        CHECK(obj_code in _OBJECTIVE_FROM_CODE,
              f"serve_meta names unknown objective code {obj_code}")
        hm = flat.get("['handle_missing']")
        bs = flat.get("['base_score']")
        split_feat = flat.get("['split_feat']")
        CHECK(split_feat is not None, "checkpoint is missing split_feat")
        param = GBDTParam(
            objective=_OBJECTIVE_FROM_CODE[obj_code],
            num_bins=num_bins, max_depth=max_depth, num_class=num_class,
            num_boost_round=max(1, int(np.asarray(split_feat).shape[0])),
            handle_missing=bool(hm[0]) if hm is not None else False,
            base_score=float(bs[0]) if bs is not None else 0.0)
        gbdt = cls(param, num_feature)
        return gbdt, gbdt.load_model_dict(flat)

    @classmethod
    def resume(cls, flat: dict,
               param: Optional[GBDTParam] = None
               ) -> Tuple["GBDT", TreeEnsemble]:
        """Warm-start restore for continuous training: rebuild
        ``(GBDT, ensemble)`` from a :meth:`serving_state` checkpoint with
        the binner edges frozen from the restored state, ready for
        :meth:`append_rounds` against fresh data.

        ``serve_meta`` records only the structural contract (bins, depth,
        objective, classes) — not training hyperparameters like
        learning_rate or regularisation.  Pass ``param`` to supply those
        for the appended rounds; its structural fields must match the
        checkpoint (they define the routing + binning contract the uint8
        serving wire depends on — the whole point of resume over refit is
        that the wire stays bitwise skew-free).
        """
        gbdt, ensemble = cls.from_serving_state(flat)
        if param is None:
            return gbdt, ensemble
        for f in ("objective", "num_bins", "max_depth", "num_class"):
            CHECK(getattr(param, f) == getattr(gbdt.param, f),
                  f"resume param {f}={getattr(param, f)!r} != checkpoint "
                  f"{f}={getattr(gbdt.param, f)!r}; the structural "
                  f"contract is frozen by the serving checkpoint")
        # handle_missing/base_score mismatches are refused inside
        # load_model_dict (the binning/margin contracts)
        out = cls(param, gbdt.num_feature)
        return out, out.load_model_dict(flat)


# serving_state schema: bump when the serve_meta layout changes
_SERVE_SCHEMA = 1
_SERVE_META_KEY = "serve_meta"
_OBJECTIVE_CODES = {"logistic": 0, "squared": 1, "softmax": 2}
_OBJECTIVE_FROM_CODE = {v: k for k, v in _OBJECTIVE_CODES.items()}


class _EarlyStop:
    """The sequential stopping rule shared by the host loop and the
    compiled post-pass: improvement = loss drop > 1e-9; stop once
    ``patience`` rounds pass without one.  One implementation — the
    compiled path's bit-identical-history guarantee depends on it."""

    def __init__(self, patience: int):
        self.patience = patience
        self.best_round = -1
        self.best_loss = float("inf")

    def update(self, r: int, loss: float) -> bool:
        """Record round r's eval loss; True = stop after this round."""
        if loss < self.best_loss - 1e-9:
            self.best_loss, self.best_round = loss, r
            return False
        return bool(self.patience) and r - self.best_round >= self.patience


def _eval_metric_fn(metric: str, objective: str):
    """In-graph eval metric for fit_with_eval: 'loss' = the objective's
    own loss (logloss/mlogloss/MSE), 'error' = classification error rate
    (0.5 threshold / argmax), 'rmse' / 'mae' = regression errors.  All
    are minimized by early stopping."""
    import jax.numpy as jnp

    if metric == "loss":
        return lambda m, y: _logloss(m, y, objective)
    if metric == "error":
        CHECK(objective in ("logistic", "softmax"),
              f"eval_metric='error' needs a classification objective, "
              f"got {objective!r}")
        if objective == "softmax":
            return lambda m, y: jnp.mean(
                (jnp.argmax(m, axis=1) != y.astype(jnp.int32)).astype(
                    jnp.float32))
        return lambda m, y: jnp.mean(((m > 0) != (y > 0.5)).astype(
            jnp.float32))
    if metric in ("rmse", "mae"):
        CHECK(objective == "squared",
              f"eval_metric={metric!r} compares margins to targets "
              f"directly — only meaningful for objective='squared', got "
              f"{objective!r} (classification margins are log-odds)")
        if metric == "rmse":
            return lambda m, y: jnp.sqrt(jnp.mean((m - y) ** 2))
        return lambda m, y: jnp.mean(jnp.abs(m - y))
    CHECK(False, f"unknown eval_metric {metric!r}; "
                 f"use loss|error|rmse|mae")


def _logloss(margin, label, objective: str):
    import jax
    import jax.numpy as jnp

    if objective == "logistic":
        return jnp.mean(jnp.logaddexp(0.0, margin) - label * margin)
    if objective == "softmax":
        # mlogloss: mean cross-entropy of the true class
        logp = jax.nn.log_softmax(margin, axis=1)
        ids = label.astype(jnp.int32)
        return -jnp.mean(jnp.take_along_axis(logp, ids[:, None],
                                             axis=1)[:, 0])
    return jnp.mean((margin - label) ** 2)
