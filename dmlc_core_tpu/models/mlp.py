"""MLP classifier/regressor: the generic NN path over the bridge + mesh.

The reference underpins MXNet's NN workloads; the rebuild's generic
deep-learning path is this model: dense batches from the data pipeline, bf16
matmuls on the MXU, optax optimizers, data-parallel batches with optional
tensor-parallel hidden layers (weights sharding-constrained over a "model"
mesh axis so XLA partitions the matmuls and inserts the collectives).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu.bridge.batching import DenseBatch
from dmlc_core_tpu.param import Parameter, field
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["MLPParam", "MLP"]


class MLPParam(Parameter):
    num_feature = field(int, lower=1, help="input dimension")
    hidden = field(str, default="128,128",
                   help="comma-separated hidden layer widths")
    num_class = field(int, default=2, lower=1,
                      help="output classes (1 = regression)")
    learning_rate = field(float, default=1e-3, lower=0.0, help="adam lr")
    activation = field(str, default="relu", enum=["relu", "tanh", "gelu"],
                       help="nonlinearity")
    bf16 = field(bool, default=True, help="bfloat16 matmuls (MXU-friendly)")

    def hidden_sizes(self) -> List[int]:
        return [int(w) for w in self.hidden.split(",") if w.strip()]


class MLP:
    """Plain-jax MLP with optax optimizer state."""

    def __init__(self, param: MLPParam, model_axis: Optional[str] = None):
        self.param = param
        self.model_axis = model_axis
        sizes = [param.num_feature] + param.hidden_sizes()
        out_dim = 1 if param.num_class == 1 else param.num_class
        self._dims = list(zip(sizes, sizes[1:] + [out_dim]))
        self._dims[-1] = (sizes[-1], out_dim)

    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        import jax.numpy as jnp

        rng = np.random.RandomState(seed)
        layers = []
        sizes = [self.param.num_feature] + self.param.hidden_sizes()
        out_dim = 1 if self.param.num_class == 1 else self.param.num_class
        dims = list(zip(sizes, sizes[1:])) + [(sizes[-1], out_dim)]
        for fan_in, fan_out in dims:
            scale = np.sqrt(2.0 / fan_in)
            layers.append({
                "w": jnp.asarray(rng.normal(0, scale, (fan_in, fan_out))
                                 .astype(np.float32)),
                "b": jnp.zeros((fan_out,), jnp.float32),
            })
        return {"layers": layers}

    def _apply(self, params, x):
        import jax
        import jax.numpy as jnp

        act = {"relu": jax.nn.relu, "tanh": jnp.tanh, "gelu": jax.nn.gelu}[
            self.param.activation]
        compute_dtype = jnp.bfloat16 if self.param.bf16 else jnp.float32
        h = x.astype(compute_dtype)
        layers = params["layers"]
        for i, layer in enumerate(layers):
            w = layer["w"].astype(compute_dtype)
            if self.model_axis is not None and 0 < i < len(layers) - 1:
                from jax.sharding import PartitionSpec as P

                w = jax.lax.with_sharding_constraint(
                    w, P(None, self.model_axis))
            h = h @ w + layer["b"].astype(compute_dtype)
            if i < len(layers) - 1:
                h = act(h)
        return h.astype(jnp.float32)

    def _loss(self, params, batch: DenseBatch):
        import jax
        import jax.numpy as jnp

        logits = self._apply(params, batch.x)
        w = batch.weight
        denom = jnp.maximum(w.sum(), 1.0)
        if self.param.num_class == 1:
            err = (logits[:, 0] - batch.label) ** 2
            return jnp.sum(err * w) / denom
        labels = batch.label.astype(jnp.int32)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        return jnp.sum(nll * w) / denom

    @functools.lru_cache(maxsize=None)
    def _train_step(self):
        import jax
        import optax

        tx = optax.adam(self.param.learning_rate)

        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(self._loss)(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return jax.jit(step, donate_argnums=(0, 1)), tx

    def init_optimizer(self, params):
        _, tx = self._train_step()
        return tx.init(params)

    def train_step(self, params, opt_state, batch: DenseBatch):
        fn, _ = self._train_step()
        return fn(params, opt_state, batch)

    @functools.lru_cache(maxsize=None)
    def _predict_fn(self):
        import jax

        # memoized like _train_step: `jax.jit(self._apply)(x)` per call
        # built a fresh wrapper (and a fresh bound method) each predict,
        # so the compile cache never hit and every call retraced
        return jax.jit(self._apply)

    def predict(self, params, x):
        import jax
        import jax.numpy as jnp

        logits = self._predict_fn()(params, jnp.asarray(x))
        if self.param.num_class == 1:
            return logits[:, 0]
        return jax.nn.softmax(logits, axis=-1)
