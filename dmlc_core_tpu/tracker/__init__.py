"""Distributed job layer: launch + rendezvous (the reference's L7).

Capability parity with tracker/dmlc_tracker/ (reference):

- :mod:`rendezvous` — the Rabit tracker: TCP rank rendezvous
  (wire-compatible with Rabit clients: magic 0xff99, framed int/str protocol),
  tree+ring topology service, jobid-based rank recovery, PS bootstrap;
- :mod:`submit`/:mod:`opts` — the ``dmlc-submit`` CLI and option schema;
- backends: :mod:`local` (process-per-worker with retry), :mod:`ssh`,
  :mod:`mpi`, :mod:`sge`, and the new :mod:`tpu_vm` backend that launches one
  process per TPU-VM host and wires ``jax.distributed`` coordination;
- :mod:`launcher` — container-side bootstrap.

TPU-native recast (SURVEY.md §5.8): the tracker keeps its launch/retry/
observability duties, adds a ``jax.distributed`` coordinator to the env
contract (``DMLC_COORDINATOR_URI/PORT``), and the data plane the topology
used to serve moves into XLA collectives over ICI/DCN.
"""

from dmlc_core_tpu.tracker.rendezvous import RabitTracker, PSTracker  # noqa: F401
from dmlc_core_tpu.tracker.submit import submit_job  # noqa: F401
