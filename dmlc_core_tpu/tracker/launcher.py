"""Container-side bootstrap (reference tracker/dmlc_tracker/launcher.py).

Prepares the environment inside a freshly-scheduled container and execs the
worker command: copies job files (``DMLC_JOB_FILES``) and unpacks job
archives (``DMLC_JOB_ARCHIVES``) into the task cwd, assembles
``LD_LIBRARY_PATH``/``PYTHONPATH``, infers the role on SGE, then replaces
itself with the command.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile

__all__ = ["main"]


def materialize_files(spec: str) -> None:
    """Copy '#'-renamable files listed in DMLC_JOB_FILES into the cwd
    (sources must be container-visible, e.g. on a shared filesystem).
    Copies land via a temp file + atomic replace so concurrent tasks in a
    shared cwd never see a half-written file."""
    for item in spec.split(":"):
        if not item:
            continue
        src, _, dest = item.partition("#")
        dest = dest or os.path.basename(src)
        if os.path.exists(src) and not os.path.exists(dest):
            fd, tmp = tempfile.mkstemp(prefix=".dmlc-file-",
                                       dir=os.path.dirname(dest) or ".")
            os.close(fd)
            shutil.copy2(src, tmp)
            os.replace(tmp, dest)


def unpack_archives(spec: str) -> None:
    """Unzip '#'-renamable archives listed in DMLC_JOB_ARCHIVES
    (atomic-rename extraction: safe under concurrent tasks sharing a
    cwd, e.g. SGE array jobs)."""
    from dmlc_core_tpu.tracker.filecache import extract_archive_atomic

    for item in spec.split(":"):
        if not item:
            continue
        src, _, dest = item.partition("#")
        dest = dest or os.path.splitext(os.path.basename(src))[0]
        if os.path.exists(src):
            extract_archive_atomic(src, dest)


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: python -m dmlc_core_tpu.tracker.launcher CMD [ARGS...]",
              file=sys.stderr)
        return 2
    env = os.environ
    cwd = env.get("DMLC_JOB_CWD")
    if cwd:
        os.makedirs(cwd, exist_ok=True)   # per-job sandboxes (tpu-vm)
        os.chdir(cwd)
    materialize_files(env.get("DMLC_JOB_FILES", ""))
    unpack_archives(env.get("DMLC_JOB_ARCHIVES", ""))
    # library paths
    extra_lib = [p for p in (env.get("DMLC_HDFS_OPTS", ""),) if p]
    ld = env.get("LD_LIBRARY_PATH", "")
    for p in (os.path.join(sys.prefix, "lib"),):
        if p not in ld:
            ld = f"{ld}:{p}" if ld else p
    env["LD_LIBRARY_PATH"] = ld
    if extra_lib:
        env["LIBHDFS_OPTS"] = " ".join(extra_lib)
    # role inference on SGE array jobs (reference launcher.py)
    if "SGE_TASK_ID" in env and "DMLC_TASK_ID" not in env:
        env["DMLC_TASK_ID"] = str(int(env["SGE_TASK_ID"]) - 1)
    return subprocess.call(argv, env=env)


if __name__ == "__main__":
    sys.exit(main())
