"""Rank rendezvous + topology service, wire-compatible with Rabit clients.

Reimplements the reference tracker protocol (tracker/dmlc_tracker/tracker.py):

- framed socket protocol: native-endian int32s and length-prefixed strings
  (ExSocket, tracker.py:24-47), handshake magic 0xff99 (tracker.py:50);
- commands: ``start`` / ``recover`` / ``print`` / ``shutdown``
  (tracker.py:269-291);
- batch rank assignment sorted by host (tracker.py:295-311) with
  jobid -> rank recovery (``WorkerEntry.resolve_rank``; reference
  tracker.py:73-78);
- topology: binary tree + parent map (tracker.py:185-191) and the
  tree-sharing data-recovery ring (tracker.py:193-225), relabeled so ring
  order is rank order (get_link_map, tracker.py:227-252);
- the link-brokering rounds that repeat until every rank reports all its
  links connected (``WorkerEntry.send_topology`` + ``broker_links``; same
  wire sequence as reference tracker.py:80-135, restructured here as
  topology push / brokering rounds / accept-registry bookkeeping).

Unlike the reference, the control plane here is **deadline-hardened**
(docs/robustness.md): wire-protocol violations raise :class:`ProtocolError`
and are rejected per-connection (never an ``assert`` — one malformed client
must not kill the daemon thread, and validation must survive ``python -O``);
``DMLC_TRACKER_SOCK_TIMEOUT`` bounds every per-socket wait so a hung client
cannot freeze the accept loop; ``DMLC_TRACKER_RENDEZVOUS_DEADLINE`` bounds
the whole rendezvous with a clean shutdown; and a worker dying mid-brokering
fails *that* rank with a structured entry in
:attr:`RabitTracker.failed_ranks` instead of hanging the world.  The fault
sites ``tracker.framed.recv`` / ``tracker.framed.send`` / ``tracker.accept``
(:mod:`dmlc_core_tpu.fault`) let the chaos suite prove all of this under
injected resets, truncation, and stalls.

On TPU the data plane no longer consumes these links (XLA collectives do the
reduction), but the tracker stays wire-compatible so existing Rabit clients
(XGBoost binaries) can rendezvous against it unchanged; our own workers use
only the env contract + ``jax.distributed`` coordination.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import subprocess
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.param import get_env
from dmlc_core_tpu.telemetry import clock, tracecontext

logger = logging.getLogger("dmlc_core_tpu.tracker")

MAGIC = 0xFF99
# shard-lease control-plane handshake (ShardLeaseCoordinator): distinct from
# the rabit MAGIC so a worker dialing the wrong port is rejected at byte 4
LEASE_MAGIC = 0xFF9A
# the one lease/heartbeat budget default BOTH sides of the lease protocol
# derive from (DMLC_FLEET_LEASE_TIMEOUT overrides; fleet_ingest imports
# this so the coordinator and the workers can never drift apart silently)
DEFAULT_LEASE_TIMEOUT = 10.0
# wire sanity bounds: strings in this protocol are job ids / commands /
# hostnames and peer counts are world-sized — anything past these is a
# corrupt or hostile frame, not a big job
MAX_FRAME = 1 << 20
MAX_PEERS = 1 << 16
# brokering rounds before the tracker gives up on a conversation: an honest
# client converges in a handful of rounds; an endless nerr!=0 loop means its
# dial targets are gone (e.g. a peer process died after registering)
MAX_BROKER_ROUNDS = 256


class ProtocolError(Exception):
    """A peer violated the rendezvous wire protocol (bad magic, malformed
    frame, impossible count).  Raised — never asserted — so validation
    survives ``python -O`` and the accept loop can reject just that peer."""


class TrackerError(RuntimeError):
    """Structured tracker-level failure surfaced by :meth:`RabitTracker.join`
    (rendezvous deadline exceeded, or workers failed mid-rendezvous)."""


class FramedSocket:
    """int32/length-prefixed-string framing (reference ExSocket).

    ``timeout`` (seconds) bounds every blocking op on the underlying socket;
    inbound string frames are validated against :data:`MAX_FRAME` and UTF-8
    before they reach any caller.
    """

    def __init__(self, sock: socket.socket, timeout: Optional[float] = None):
        self.sock = sock
        if timeout:
            sock.settimeout(timeout)

    def recvall(self, nbytes: int) -> bytes:
        budget = nbytes
        if fault.enabled():
            fault.inject("tracker.framed.recv", nbytes=nbytes)
            budget = fault.truncate("tracker.framed.recv", nbytes)
        chunks = []
        nread = 0
        while nread < budget:
            chunk = self.sock.recv(min(budget - nread, 1024))
            if not chunk:
                raise ConnectionError(
                    f"peer closed during recvall ({nread}/{nbytes} bytes)")
            nread += len(chunk)
            chunks.append(chunk)
        if budget < nbytes:
            # injected truncation models the peer vanishing mid-frame
            raise ConnectionError(
                f"peer closed during recvall ({budget}/{nbytes} bytes)")
        return b"".join(chunks)

    def recvint(self) -> int:
        return struct.unpack("@i", self.recvall(4))[0]

    def _sendall(self, data: bytes) -> None:
        if fault.enabled():
            fault.inject("tracker.framed.send", nbytes=len(data))
        self.sock.sendall(data)

    def sendint(self, n: int) -> None:
        self._sendall(struct.pack("@i", n))

    def sendstr(self, s: str) -> None:
        # length prefix counts encoded BYTES: len(s) would under-count any
        # non-ASCII hostname/jobid and truncate the frame at the receiver
        # (byte-identical to the reference for the ASCII protocol strings)
        data = s.encode()
        self.sendint(len(data))
        self._sendall(data)

    def recvstr(self) -> str:
        n = self.recvint()
        if n < 0 or n > MAX_FRAME:
            raise ProtocolError(
                f"invalid string length {n} on the wire (bounds [0, "
                f"{MAX_FRAME}])")
        data = self.recvall(n)
        try:
            return data.decode()
        except UnicodeDecodeError as exc:
            raise ProtocolError(f"non-UTF-8 string payload: {exc}") from None


def _resolve_ip(host: str) -> str:
    return socket.getaddrinfo(host, None)[0][4][0]


class WorkerEntry:
    """One connected worker: the handshake state plus the per-worker half of
    the link-brokering conversation (wire-compatible with Rabit's client
    side; message sequence documented on each method)."""

    def __init__(self, sock: socket.socket, addr,
                 timeout: Optional[float] = None):
        connect_start = clock.monotonic()
        self.sock = FramedSocket(sock, timeout=timeout)
        self.host = _resolve_ip(addr[0])
        magic = self.sock.recvint()
        if magic != MAGIC:
            raise ProtocolError(f"invalid magic {magic:#x} from {self.host}")
        self.sock.sendint(MAGIC)
        self.rank = self.sock.recvint()
        self.world_size = self.sock.recvint()
        self.jobid = self.sock.recvstr()
        self.cmd = self.sock.recvstr()
        # connect-phase bracket, attributed to a rank once one is assigned
        # (assign_rank emits the span) — the per-rank rendezvous timeline is
        # connect -> assign -> barrier in the exported trace
        self.connect_span = (connect_start, clock.monotonic())
        # inbound links this worker still expects peers to dial (it stays in
        # the tracker's accept registry until this reaches zero)
        self.pending_accepts = 0
        # the worker's own listening port, reported at the end of brokering
        self.port: Optional[int] = None

    def resolve_rank(self, jobid_ranks: Dict[str, int]) -> int:
        """Keep a self-reported rank, else restore a restarted worker's old
        rank by job id, else -1 (rank to be assigned in host order)."""
        if self.rank >= 0:
            return self.rank
        return jobid_ranks.get(self.jobid, -1) if self.jobid != "NULL" else -1

    def send_topology(self, rank: int, world: int, tree_links: List[int],
                      parent: int, ring_prev: int, ring_next: int) -> set:
        """Push the assigned rank and its neighborhood down the wire.

        Wire order (fixed by the Rabit client): rank, parent, world size,
        tree-degree, each tree neighbor, ring-prev, ring-next — the ring
        slots carry -1 when absent or self-referential.  Returns the full
        link set (tree + real ring hops) this worker must establish.
        """
        self.rank = rank
        links = set(tree_links)
        self.sock.sendint(rank)
        self.sock.sendint(parent)
        self.sock.sendint(world)
        self.sock.sendint(len(links))
        # iterate the SET, not the list: the neighbor block is a set on the
        # wire, and the reference tracker emits it in set-iteration order —
        # doing the same keeps conversations byte-identical to it
        # (tests/test_tracker_conformance.py)
        for peer in links:
            self.sock.sendint(peer)
        for hop in (ring_prev, ring_next):
            if hop in (-1, rank):
                self.sock.sendint(-1)
            else:
                self.sock.sendint(hop)
                links.add(hop)
        return links

    def broker_links(self, links: set,
                     accept_registry: Dict[int, "WorkerEntry"]) -> List[int]:
        """Run brokering rounds until this worker's dial attempts all
        succeed.

        Each round: the worker reports which peers it already reached; the
        tracker answers with the subset of its missing peers that are
        listening right now (count, then host/port/rank triples) plus how
        many peers are not yet dialable (the worker must accept those
        inbound later).  A round that ends with connect errors repeats;
        a clean round ends with the worker reporting its own listening
        port.  Bookkeeping after a clean round: every peer this worker was
        told to dial has one fewer inbound accept outstanding — peers that
        reach zero are fully linked and leave ``accept_registry``; this
        worker records its own outstanding inbound count.  Returns the
        ranks that became fully linked.

        Everything the peer reports is validated (counts bounded, reported
        peers must be assigned links) and a conversation that never
        converges is cut off after :data:`MAX_BROKER_ROUNDS` — both raise
        :class:`ProtocolError`, which the accept loop turns into a failed
        rank rather than a dead tracker.
        """
        for _ in range(MAX_BROKER_ROUNDS):
            nreached = self.sock.recvint()
            if nreached < 0 or nreached > MAX_PEERS:
                raise ProtocolError(
                    f"rank {self.rank} reported {nreached} reached peers")
            reached = {self.sock.recvint() for _ in range(nreached)}
            if not reached <= links:
                raise ProtocolError(
                    f"rank {self.rank} reported links it was never "
                    f"assigned: {sorted(reached - links)}")
            missing = links - reached
            dialable = [peer for peer in missing if peer in accept_registry]
            self.sock.sendint(len(dialable))
            self.sock.sendint(len(missing) - len(dialable))
            for peer in dialable:
                listener = accept_registry[peer]
                self.sock.sendstr(listener.host)
                self.sock.sendint(listener.port)
                self.sock.sendint(peer)
            dial_errors = self.sock.recvint()
            if dial_errors != 0:
                continue
            self.port = self.sock.recvint()
            fully_linked = []
            for peer in dialable:
                listener = accept_registry[peer]
                listener.pending_accepts -= 1
                if listener.pending_accepts == 0:
                    fully_linked.append(peer)
            for peer in fully_linked:
                accept_registry.pop(peer, None)
            self.pending_accepts = len(missing) - len(dialable)
            return fully_linked
        raise ProtocolError(
            f"rank {self.rank} brokering did not converge within "
            f"{MAX_BROKER_ROUNDS} rounds (dial targets unreachable?)")

    def assign_rank(self, rank: int,
                    accept_registry: Dict[int, "WorkerEntry"],
                    tree_map, parent_map, ring_map) -> List[int]:
        telemetry.record_span("rendezvous.connect", *self.connect_span,
                              rank=rank, host=self.host, cmd=self.cmd)
        assign_start = clock.monotonic()
        ring_prev, ring_next = ring_map[rank]
        links = self.send_topology(rank, len(tree_map), tree_map[rank],
                                   parent_map[rank], ring_prev, ring_next)
        out = self.broker_links(links, accept_registry)
        telemetry.record_span("rendezvous.assign", assign_start,
                              clock.monotonic(), rank=rank,
                              links=len(links))
        if telemetry.enabled():
            telemetry.observe("dmlc_rendezvous_assign_seconds",
                              clock.elapsed(assign_start))
            telemetry.count("dmlc_rendezvous_workers_total", cmd=self.cmd)
        return out


def bind_free_port(host: str, port: int = 9091,
                   port_end: int = 9999) -> Tuple[socket.socket, int]:
    """Bind the first free port in [port, port_end) (reference tracker.py:141-152).

    The probe socket is closed on every failure path (exhausted range or a
    non-EADDRINUSE bind error) — only a successful bind transfers ownership
    to the caller.
    """
    family = socket.getaddrinfo(host, None)[0][0]
    sock = socket.socket(family, socket.SOCK_STREAM)
    try:
        for p in range(port, port_end):
            try:
                sock.bind((host, p))
                return sock, p
            except socket.error as err:
                if err.errno in (98, 48):  # EADDRINUSE linux/mac
                    continue
                raise
        raise OSError(f"no free port in [{port}, {port_end})")
    except BaseException:
        sock.close()
        raise


class RabitTracker:
    """The rendezvous server (reference RabitTracker, tracker.py:137-334).

    Robustness knobs (docs/robustness.md; constructor args override env):

    - ``sock_timeout`` / ``DMLC_TRACKER_SOCK_TIMEOUT`` (seconds, 0 = off):
      applied to every accepted socket, so a client that connects and goes
      silent times out instead of freezing the single-threaded accept loop;
    - ``rendezvous_deadline`` / ``DMLC_TRACKER_RENDEZVOUS_DEADLINE``
      (seconds, 0 = off): armed when the first worker knocks, disarmed once
      all ranks started; while armed, every accepted socket's timeout is
      additionally clamped to the remaining deadline, so even a hung
      conversation cannot block the loop past it.  On expiry the tracker
      closes every pending worker's socket (they observe a connection
      error — a structured failure, not a hang), records :attr:`error`,
      and shuts down cleanly; :meth:`join` then raises
      :class:`TrackerError`.

    After the run, :attr:`failed_ranks` maps each rank that died
    mid-rendezvous to a structured message; :meth:`join` raises
    :class:`TrackerError` when any exist, so callers cannot mistake a
    degraded rendezvous for a clean one.
    """

    def __init__(self, host_ip: str, num_workers: int, port: int = 9091,
                 port_end: int = 9999,
                 sock_timeout: Optional[float] = None,
                 rendezvous_deadline: Optional[float] = None):
        self.host_ip = host_ip
        self.num_workers = num_workers
        self.thread: Optional[threading.Thread] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        self.sock_timeout = (sock_timeout if sock_timeout is not None
                             else get_env("DMLC_TRACKER_SOCK_TIMEOUT",
                                          float, 0.0))
        self.rendezvous_deadline = (
            rendezvous_deadline if rendezvous_deadline is not None
            else get_env("DMLC_TRACKER_RENDEZVOUS_DEADLINE", float, 0.0))
        # rank -> structured message for every worker that died mid-rendezvous
        self.failed_ranks: Dict[int, str] = {}
        # tracker-fatal condition (rendezvous deadline); join() raises it
        self.error: Optional[str] = None
        # the rendezvous trace: the accept loop runs under this context
        # (its connect/assign/barrier spans parent to the root span below),
        # and worker_envs() exports it as DMLC_TRACKER_TRACEPARENT so every
        # launched worker's spans join the same trace from its side of the
        # wire — one assembled timeline for the whole cold start
        self.trace = tracecontext.TraceContext(tracecontext.new_trace_id(),
                                               tracecontext.new_span_id())
        self._constructed_at = clock.monotonic()
        # the port is bound LAST: a constructor failure after the bind
        # would orphan the listening socket (the caller never receives the
        # instance, so the accept loop's own close can never run)
        self.sock, self.port = bind_free_port(host_ip, port, port_end)
        try:
            self.sock.listen(256)
        except BaseException:
            self.sock.close()
            raise
        logger.info("start listening on %s:%d", host_ip, self.port)

    # -- topology (tracker.py:165-252) ---------------------------------------
    @staticmethod
    def _tree_neighbors(rank: int, n: int) -> List[int]:
        rank = rank + 1
        out = []
        if rank > 1:
            out.append(rank // 2 - 1)
        if rank * 2 - 1 < n:
            out.append(rank * 2 - 1)
        if rank * 2 < n:
            out.append(rank * 2)
        return out

    @classmethod
    def get_tree(cls, n: int):
        tree_map = {r: cls._tree_neighbors(r, n) for r in range(n)}
        parent_map = {r: (r + 1) // 2 - 1 for r in range(n)}
        return tree_map, parent_map

    @classmethod
    def _share_ring_order(cls, tree_map, parent_map, r: int) -> List[int]:
        """DFS order that keeps ring hops close to tree links (used to recover
        local data, reference tracker.py:193-214)."""
        children = set(tree_map[r]) - {parent_map[r]}
        if not children:
            return [r]
        out = [r]
        for i, v in enumerate(sorted(children)):
            sub = cls._share_ring_order(tree_map, parent_map, v)
            if i == len(children) - 1:
                sub.reverse()
            out += sub
        return out

    @classmethod
    def get_ring(cls, tree_map, parent_map):
        order = cls._share_ring_order(tree_map, parent_map, 0)
        assert len(order) == len(tree_map)
        n = len(tree_map)
        ring_map = {}
        for i in range(n):
            ring_map[order[i]] = (order[(i - 1) % n], order[(i + 1) % n])
        return ring_map

    @classmethod
    def get_link_map(cls, n: int):
        """Relabel ranks so ring order == rank order (tracker.py:227-252)."""
        tree_map, parent_map = cls.get_tree(n)
        ring_map = cls.get_ring(tree_map, parent_map)
        rmap = {0: 0}
        k = 0
        for i in range(n - 1):
            k = ring_map[k][1]
            rmap[k] = i + 1
        ring_out = {rmap[k]: (rmap[v[0]], rmap[v[1]]) for k, v in ring_map.items()}
        tree_out = {rmap[k]: [rmap[x] for x in v] for k, v in tree_map.items()}
        parent_out = {rmap[k]: (rmap[v] if k != 0 else -1)
                      for k, v in parent_map.items()}
        return tree_out, parent_out, ring_out

    # -- env contract ---------------------------------------------------------
    def worker_envs(self) -> Dict[str, str]:
        return {"DMLC_TRACKER_URI": self.host_ip,
                "DMLC_TRACKER_PORT": str(self.port),
                tracecontext.TRACKER_TRACEPARENT_ENV:
                    tracecontext.format_traceparent(self.trace)}

    # -- accept loop (tracker.py:254-320) -------------------------------------
    def _reject(self, sock: socket.socket, reason: str, detail) -> None:
        """Reject one bad connection: log, count, close, carry on."""
        logger.warning("rejected connection (%s): %s", reason, detail)
        telemetry.count("dmlc_tracker_protocol_errors_total", reason=reason)
        try:
            sock.close()
        except OSError:
            pass

    def _fail_worker(self, worker: WorkerEntry, rank: int,
                     err: BaseException) -> None:
        """A worker died mid-rendezvous: fail THAT rank, keep the world."""
        msg = (f"rank {rank} ({worker.host}) failed during rendezvous: "
               f"{type(err).__name__}: {err}")
        logger.error("%s", msg)
        self.failed_ranks[rank] = msg
        telemetry.count("dmlc_tracker_worker_failures_total")
        try:
            worker.sock.sock.close()
        except OSError:
            pass

    def _assign(self, worker: WorkerEntry, rank: int, accept_registry,
                tree_map, parent_map, ring_map) -> bool:
        """assign_rank with per-worker exception isolation."""
        try:
            worker.assign_rank(rank, accept_registry, tree_map, parent_map,
                               ring_map)
        except (ProtocolError, OSError) as err:
            self._fail_worker(worker, rank, err)
            return False
        # a recovered rank is live again
        self.failed_ranks.pop(rank, None)
        return True

    def _accept_workers(self, n: int) -> None:
        try:
            # the loop thread runs under the rendezvous trace context, so
            # every span recorded inside (connect/assign/barrier) parents
            # to the tracker.rendezvous root span recorded below
            with tracecontext.activate(self.trace):
                self._accept_workers_inner(n)
        except Exception as exc:  # noqa: BLE001 — ferried to join()
            # the accept loop is the whole control plane: a crash here must
            # surface as a structured tracker error, never a silently dead
            # daemon thread with every worker blocked on it
            logger.exception("tracker accept loop died")
            self.error = (f"tracker accept loop died: "
                          f"{type(exc).__name__}: {exc}")
        finally:
            # recorded on EVERY exit path — clean finish, deadline expiry,
            # loop crash — as a child of the root span start() already
            # flushed (the loop may block forever when workers coordinate
            # via jax.distributed and never dial back; the root must not
            # depend on it exiting)
            telemetry.record_span(
                "tracker.rendezvous", self._constructed_at, clock.monotonic(),
                trace=(self.trace.trace_id, tracecontext.new_span_id(),
                       self.trace.span_id),
                world=n, error=self.error or "",
                failed_ranks=len(self.failed_ranks))
            # clean shutdown on every exit path: the port is freed and no
            # late client can block on a listener nobody serves
            try:
                self.sock.close()
            except OSError:
                pass

    def _accept_workers_inner(self, n: int) -> None:
        shutdown: Dict[int, WorkerEntry] = {}
        accept_registry: Dict[int, WorkerEntry] = {}
        jobid_ranks: Dict[str, int] = {}
        pending: List[WorkerEntry] = []
        tree_map = None
        todo_nodes: List[int] = []
        barrier_start: Optional[float] = None
        deadline_at: Optional[float] = None
        if self.rendezvous_deadline:
            # poll accept so the deadline fires even with nobody knocking
            self.sock.settimeout(0.1)
        # a rank that failed mid-rendezvous is terminal unless it recovers;
        # counting it lets the world finish instead of waiting forever for
        # a shutdown that will never come
        while len(set(shutdown) | set(self.failed_ranks)) < n:
            if deadline_at is not None and clock.monotonic() > deadline_at:
                self._rendezvous_expired(pending, todo_nodes, n)
                # the deadline exit must drop served shutdown connections
                # too, or their fds stay pinned exactly like the normal
                # exit used to leave them
                self._close_worker_socks(shutdown.values())
                return
            try:
                fd, addr = self.sock.accept()
            except socket.timeout:
                continue
            if deadline_at is None and self.rendezvous_deadline \
                    and tree_map is None:
                # armed by the first knock; disarmed once all ranks started
                deadline_at = clock.monotonic() + self.rendezvous_deadline
            # per-socket budget: the explicit sock_timeout, further clamped
            # to the remaining rendezvous deadline — without this a single
            # hung conversation would block the single-threaded loop PAST
            # the deadline it is supposed to enforce
            timeout = self.sock_timeout or None
            if deadline_at is not None:
                remaining = max(0.1, deadline_at - clock.monotonic())
                timeout = remaining if timeout is None \
                    else min(timeout, remaining)
            try:
                fault.inject("tracker.accept", host=addr[0])
                s = WorkerEntry(fd, addr, timeout=timeout)
            except (ProtocolError, OSError) as err:
                self._reject(fd, "handshake", err)
                continue
            if s.cmd == "print":
                try:
                    msg = s.sock.recvstr()
                except (ProtocolError, OSError) as err:
                    self._reject(fd, "print", err)
                    continue
                logger.info(msg.strip())
                try:
                    # one connection per print message: dropping the fd
                    # here used to park it on the GC (one leaked fd per
                    # print for the life of the rendezvous)
                    fd.close()
                except OSError:
                    pass
                continue
            if s.cmd == "shutdown":
                # rank must name a real slot: out-of-world shutdowns would
                # otherwise count toward loop termination and end the
                # rendezvous "cleanly" with the honest workers unserved
                if s.rank < 0 or s.rank >= n or s.rank in shutdown:
                    self._reject(fd, "shutdown",
                                 f"bad shutdown rank {s.rank} from {s.host} "
                                 f"(world {n})")
                    continue
                shutdown[s.rank] = s
                logger.debug("shutdown signal from %d", s.rank)
                continue
            if s.cmd not in ("start", "recover"):
                self._reject(fd, "bad-cmd",
                             f"unknown command {s.cmd!r} from {s.host}")
                continue
            if barrier_start is None:
                # barrier = first worker knocking until all n are started
                barrier_start = s.connect_span[0]
            if tree_map is None:
                if s.cmd != "start":
                    self._reject(fd, "recover-before-start",
                                 f"{s.cmd!r} from {s.host} before any "
                                 "worker started")
                    continue
                if s.world_size > MAX_PEERS:
                    # the announced world sizes topology dicts and the todo
                    # list: an unbounded value is a corrupt frame, not a
                    # big job — reject it before it allocates
                    self._reject(fd, "world-out-of-range",
                                 f"{s.host} announced world {s.world_size} "
                                 f"(max {MAX_PEERS})")
                    continue
                if s.world_size > 0:
                    n = s.world_size
                tree_map, parent_map, ring_map = self.get_link_map(n)
                todo_nodes = list(range(n))
            else:
                if s.world_size not in (-1, n):
                    self._reject(fd, "world-mismatch",
                                 f"{s.host} announced world {s.world_size}, "
                                 f"expected {n}")
                    continue
            if s.cmd == "recover" and s.rank < 0:
                self._reject(fd, "bad-recover-rank",
                             f"recover without a rank from {s.host}")
                continue
            if s.rank >= n:
                # a self-reported rank outside the world would index the
                # topology maps (KeyError) — reject the frame, keep the loop
                self._reject(fd, "rank-out-of-range",
                             f"{s.host} reported rank {s.rank} outside "
                             f"world {n}")
                continue
            rank = s.resolve_rank(jobid_ranks)
            if rank == -1:
                if not todo_nodes:
                    self._reject(fd, "extra-worker",
                                 f"no rank slots left for {s.host} "
                                 f"(world {n})")
                    continue
                pending.append(s)
                if len(pending) == len(todo_nodes):
                    pending.sort(key=lambda x: x.host)
                    for p in pending:
                        prank = todo_nodes.pop(0)
                        if p.jobid != "NULL":
                            jobid_ranks[p.jobid] = prank
                        if not self._assign(p, prank, accept_registry,
                                            tree_map, parent_map, ring_map):
                            continue
                        if p.pending_accepts > 0:
                            accept_registry[prank] = p
                        logger.debug("%s from %s; assigned rank %d",
                                     p.cmd, p.host, p.rank)
                    pending = []
                if not todo_nodes:
                    logger.info("@tracker all of %d nodes started", n)
                    self.start_time = time.time()
                    deadline_at = None  # rendezvous over; workers may run long
                    if barrier_start is not None:
                        telemetry.record_span("rendezvous.barrier",
                                              barrier_start, clock.monotonic(),
                                              world=n)
                        telemetry.observe("dmlc_rendezvous_barrier_seconds",
                                          clock.elapsed(barrier_start))
            else:
                if self._assign(s, rank, accept_registry, tree_map,
                                parent_map, ring_map):
                    logger.debug("%s signal from %d", s.cmd, s.rank)
                    if s.pending_accepts > 0:
                        accept_registry[rank] = s
        self.end_time = time.time()
        self._close_worker_socks(shutdown.values())
        logger.info("@tracker all nodes finished; %.3f secs between start and finish",
                    (self.end_time - (self.start_time or self.end_time)))

    @staticmethod
    def _close_worker_socks(entries) -> None:
        """Close served connections; they pin one fd per rank until the
        tracker object is collected otherwise."""
        for entry in entries:
            try:
                entry.sock.sock.close()
            except OSError:
                pass

    def _rendezvous_expired(self, pending: List[WorkerEntry],
                            todo_nodes: List[int], n: int) -> None:
        """Deadline hit mid-rendezvous: fail the stragglers, shut down clean.

        Every pending worker's socket is closed so its client observes a
        connection error (a structured failure on its side, within the
        deadline) instead of blocking forever on a tracker that gave up.
        """
        missing = len(todo_nodes) if todo_nodes else n
        self.error = (f"rendezvous deadline ({self.rendezvous_deadline:g}s) "
                      f"exceeded: {len(pending)} worker(s) pending, "
                      f"{missing} of {n} rank(s) never started")
        logger.error("%s", self.error)
        telemetry.count("dmlc_tracker_deadline_exceeded_total")
        self._close_worker_socks(pending)

    def start(self, num_workers: Optional[int] = None) -> None:
        n = num_workers if num_workers is not None else self.num_workers
        # the trace's root span is recorded HERE, not at loop exit: workers
        # that coordinate via the env contract + jax.distributed never dial
        # the rabit sockets, the accept loop then blocks until process
        # exit, and a root recorded only on loop exit would leave every
        # worker-side span (parented to it via DMLC_TRACKER_TRACEPARENT)
        # an orphan in the assembled trace
        telemetry.record_span(
            "tracker.start", self._constructed_at, clock.monotonic(),
            trace=(self.trace.trace_id, self.trace.span_id, None),
            world=n, host=self.host_ip, port=self.port)
        self.thread = threading.Thread(target=self._accept_workers, args=(n,),
                                       daemon=True)
        self.thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.time() + timeout
        while self.thread.is_alive():
            self.thread.join(0.1)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("tracker did not finish in time")
        if self.error:
            raise TrackerError(self.error)
        if self.failed_ranks:
            detail = "; ".join(self.failed_ranks[r]
                               for r in sorted(self.failed_ranks))
            raise TrackerError(
                f"rendezvous completed with {len(self.failed_ranks)} failed "
                f"rank(s): {detail}")

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()


class ShardLeaseCoordinator:
    """Dynamic shard-lease control plane for fleet-scale ingest.

    The data-plane half lives in :mod:`dmlc_core_tpu.parallel.fleet_ingest`;
    this side owns the authoritative unit ledger.  The input is split into
    many more **work units** than workers (byte-range shards or columnar
    row-group units — opaque spec strings here); workers acquire units as
    **heartbeat-renewed leases** over the same framed wire protocol as the
    rabit rendezvous (:class:`FramedSocket`, one short conversation per
    request), and a lease whose holder stops renewing — a worker that died
    mid-unit, or a process wedged hard enough (GC pause, suspended VM,
    partition) that its heartbeat thread misses the lease window — expires
    and is **reassigned** to the next worker that asks.  (A worker whose
    *processor* alone wedges keeps heartbeating and keeps its lease: only
    whole-process trouble triggers handoff, by design — re-ingesting a
    unit someone is still working on would be waste, and the commit
    discipline below makes the race safe if it happens anyway.)
    Coverage is exactly-once-per-committed-unit
    by construction: a unit's first commit wins, a commit from a worker
    that lost its lease is rejected (the worker discards those rows), and a
    commit retry from the committed worker is acked idempotently.

    ``mode="dynamic"`` is the work-stealing scheduler.  ``mode="static"``
    serves the classic ``k % n`` assignment through the *same* wire path
    (each worker may only acquire units with ``unit_id % world_size ==
    worker_index``, and expired leases are never handed to another worker)
    so the ``fleet-ab`` bench A/Bs scheduling policy, not transport.

    Wire conversation (one per TCP connection, any order, any number):

    - handshake: ``int LEASE_MAGIC`` both ways, then ``str worker_id``,
      ``str cmd``;
    - ``acquire``: ``int worker_index`` (used in static mode, ``-1``
      otherwise) -> ``int unit_id`` then, when ``unit_id >= 0``, the
      ``str`` unit spec.  ``-1`` = nothing grantable right now (leases
      outstanding; poll again), ``-2`` = this worker is done (all units —
      all *its* units in static mode — committed);
    - ``renew``: -> ``int`` count of this worker's leases renewed (the
      heartbeat; cadence ``lease_timeout / 3`` on the worker side);
    - ``commit``: ``int unit_id``, ``str payload-json`` (must carry
      ``rows``) -> ``int`` 1 accepted / 0 rejected.

    Like the rendezvous loop, wire violations raise :class:`ProtocolError`
    and reject that connection only; a worker whose lease expired lands in
    :attr:`failed_workers` with a structured message (the
    ``failed_ranks`` idiom from the rendezvous hardening) and is cleared
    if it comes back.  ``DMLC_FLEET_LEASE_TIMEOUT`` (seconds, default
    :data:`DEFAULT_LEASE_TIMEOUT`) is the lease/heartbeat budget;
    per-socket timeouts default to a third of it so one hung
    conversation cannot stall the single-threaded serve loop past a
    heartbeat interval (which would let healthy workers' leases expire
    behind it).
    """

    PENDING, LEASED, COMMITTED = 0, 1, 2

    def __init__(self, host_ip: str, units: List[str], port: int = 9091,
                 port_end: int = 9999, *, mode: str = "dynamic",
                 world_size: int = 0,
                 lease_timeout: Optional[float] = None,
                 sock_timeout: Optional[float] = None):
        if mode not in ("dynamic", "static"):
            raise ValueError(f"mode must be 'dynamic' or 'static', got {mode!r}")
        if mode == "static" and world_size < 1:
            raise ValueError("static mode needs world_size >= 1")
        if not units:
            raise ValueError("no work units to schedule")
        self.host_ip = host_ip
        self.mode = mode
        self.world_size = world_size
        self.lease_timeout = (lease_timeout if lease_timeout is not None
                              else get_env("DMLC_FLEET_LEASE_TIMEOUT",
                                           float, DEFAULT_LEASE_TIMEOUT))
        if self.lease_timeout <= 0:
            raise ValueError("lease_timeout must be > 0")
        # per-connection budget: the serve loop is single-threaded, so one
        # stalled conversation must not outlive a heartbeat interval
        # (lease/3) — otherwise every other worker's renew queues behind
        # it long enough for their leases to expire and be spuriously
        # stolen.  A conversation is a handful of tiny frames sent
        # back-to-back; a third of a lease is generous.
        self.sock_timeout = (sock_timeout if sock_timeout is not None
                             else min(max(self.lease_timeout / 3.0, 0.1),
                                      30.0))
        self._units: List[Dict[str, Any]] = [
            {"spec": str(spec), "status": self.PENDING, "worker": None,
             "deadline": 0.0, "rows": 0, "payload": None, "assigned": 0}
            for spec in units]
        self._lock = threading.Lock()
        self.assigned_total = 0
        self.committed_total = 0
        self.reassigned_total = 0
        self.rejected_total = 0
        # worker id -> structured message for every lease that expired on it
        # (cleared when the worker successfully acquires/renews again — the
        # failed_ranks recover discipline)
        self.failed_workers: Dict[str, str] = {}
        self.error: Optional[str] = None
        self.thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # same trace discipline as RabitTracker: worker_envs() exports this
        # so every worker's ingest.lease/ingest.unit spans join one timeline
        self.trace = tracecontext.TraceContext(tracecontext.new_trace_id(),
                                               tracecontext.new_span_id())
        self._constructed_at = clock.monotonic()
        n_units = len(self._units)
        # bound LAST (the RabitTracker discipline): a constructor failure
        # after the bind would orphan the listening socket
        self.sock, self.port = bind_free_port(host_ip, port, port_end)
        try:
            self.sock.listen(128)
        except BaseException:
            self.sock.close()
            raise
        logger.info("shard-lease coordinator on %s:%d (%d units, %s)",
                    host_ip, self.port, n_units, mode)

    # -- env contract ---------------------------------------------------------
    def worker_envs(self) -> Dict[str, str]:
        return {"DMLC_FLEET_LEASE_URI": self.host_ip,
                "DMLC_FLEET_LEASE_PORT": str(self.port),
                tracecontext.TRACKER_TRACEPARENT_ENV:
                    tracecontext.format_traceparent(self.trace)}

    # -- serve loop -----------------------------------------------------------
    def start(self) -> None:
        # root span recorded NOW (the tracker.start discipline): worker
        # spans parent to it via the exported traceparent and must not
        # depend on the serve loop ever exiting
        telemetry.record_span(
            "ingest.fleet", self._constructed_at, clock.monotonic(),
            trace=(self.trace.trace_id, self.trace.span_id, None),
            units=len(self._units), mode=self.mode, host=self.host_ip,
            port=self.port)
        self.thread = threading.Thread(target=self._serve_loop, daemon=True)
        self.thread.start()

    def _serve_loop(self) -> None:
        try:
            with tracecontext.activate(self.trace):
                self._serve_inner()
        except Exception as exc:  # noqa: BLE001 — ferried to result()
            logger.exception("shard-lease serve loop died")
            # result() polls error from the caller's thread (no join):
            # the crash report rides the same lock as the ledger
            with self._lock:
                self.error = (f"shard-lease serve loop died: "
                              f"{type(exc).__name__}: {exc}")
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def _serve_inner(self) -> None:
        # poll accept so stop() (and a closed listener) ends the loop
        self.sock.settimeout(0.1)
        while not self._stop.is_set():
            try:
                fd, addr = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us by stop()
            try:
                self._serve_one(fd, addr)
            except (ProtocolError, ConnectionError, OSError) as err:
                logger.warning("lease request from %s rejected: %s",
                               addr[0], err)
                telemetry.count("dmlc_tracker_protocol_errors_total",
                                reason="lease")
            finally:
                try:
                    fd.close()
                except OSError:
                    pass

    def _serve_one(self, fd: socket.socket, addr) -> None:
        sk = FramedSocket(fd, timeout=self.sock_timeout)
        magic = sk.recvint()
        if magic != LEASE_MAGIC:
            raise ProtocolError(f"invalid lease magic {magic:#x} from {addr[0]}")
        sk.sendint(LEASE_MAGIC)
        worker = sk.recvstr()
        cmd = sk.recvstr()
        if cmd == "acquire":
            widx = sk.recvint()
            unit_id, spec = self._grant(worker, widx)
            sk.sendint(unit_id)
            if unit_id >= 0:
                sk.sendstr(spec)
        elif cmd == "renew":
            sk.sendint(self._renew(worker))
        elif cmd == "commit":
            unit_id = sk.recvint()
            payload = sk.recvstr()
            sk.sendint(1 if self._commit(worker, unit_id, payload) else 0)
        else:
            raise ProtocolError(
                f"unknown lease command {cmd!r} from worker {worker!r}")

    # -- scheduling core (all state under self._lock, no blocking inside) ----
    def _candidates(self, worker_index: int):
        if self.mode == "static":
            if worker_index < 0 or worker_index >= self.world_size:
                raise ProtocolError(
                    f"static acquire needs worker_index in [0, "
                    f"{self.world_size}), got {worker_index}")
            return range(worker_index, len(self._units), self.world_size)
        return range(len(self._units))

    def _grant(self, worker: str, worker_index: int):
        """(unit_id, spec) to serve for an acquire: the worker's own
        already-held lease first (a retry of a lost grant reply must get
        the SAME unit back — see below), else a pending unit, else an
        expired lease (dynamic: stolen from the dead/straggling holder;
        static: only the worker's own), else -1 poll-again / -2 done."""
        candidates = self._candidates(worker_index)
        now = clock.monotonic()
        reassigned_from: Optional[str] = None
        with self._lock:
            # idempotent re-delivery: the worker loop holds at most one
            # lease at a time, so an acquire from a worker that already
            # holds one means the previous grant's reply was lost and the
            # client retried.  Handing out a DIFFERENT unit would orphan
            # the held lease — kept alive forever by the renew-all
            # heartbeat, wedging the epoch — so re-deliver the held unit
            # (deadline refreshed, no counters: it is one grant, retried).
            for i in range(len(self._units)):
                unit = self._units[i]
                if unit["status"] == self.LEASED and unit["worker"] == worker:
                    unit["deadline"] = now + self.lease_timeout
                    logger.debug("re-delivering unit %d to %s (grant retry)",
                                 i, worker)
                    return i, unit["spec"]
            grant = None
            for i in candidates:
                unit = self._units[i]
                if unit["status"] == self.PENDING:
                    grant = i
                    break
                if (unit["status"] == self.LEASED and unit["deadline"] < now
                        and (self.mode == "dynamic"
                             or unit["worker"] == worker)):
                    grant = i
                    if unit["worker"] != worker:
                        reassigned_from = unit["worker"]
                        self.reassigned_total += 1
                        self.failed_workers.setdefault(
                            reassigned_from,
                            f"worker {reassigned_from} lease on unit {i} "
                            f"expired after {self.lease_timeout:g}s; "
                            f"reassigned to {worker}")
                    break
            if grant is None:
                done = all(self._units[i]["status"] == self.COMMITTED
                           for i in candidates)
                return (-2 if done else -1), None
            unit = self._units[grant]
            unit["status"] = self.LEASED
            unit["worker"] = worker
            unit["deadline"] = now + self.lease_timeout
            unit["assigned"] += 1
            self.assigned_total += 1
            # a worker holding a fresh lease is live again
            self.failed_workers.pop(worker, None)
            spec = unit["spec"]
        if reassigned_from is not None:
            logger.warning("unit %d lease expired on %s; reassigned to %s",
                           grant, reassigned_from, worker)
            telemetry.count("dmlc_fleet_units_reassigned_total")
        telemetry.count("dmlc_fleet_units_assigned_total", mode=self.mode)
        return grant, spec

    def _renew(self, worker: str) -> int:
        """Heartbeat: extend every lease this worker still holds.  A lease
        past its deadline but not yet reassigned is revived — the holder is
        demonstrably alive and still the only owner."""
        now = clock.monotonic()
        with self._lock:
            n = 0
            for unit in self._units:
                if unit["status"] == self.LEASED and unit["worker"] == worker:
                    unit["deadline"] = now + self.lease_timeout
                    n += 1
            if n:
                self.failed_workers.pop(worker, None)
        return n

    def _commit(self, worker: str, unit_id: int, payload_json: str) -> bool:
        if unit_id < 0 or unit_id >= len(self._units):
            raise ProtocolError(
                f"commit for unit {unit_id} outside [0, {len(self._units)})")
        try:
            payload = json.loads(payload_json)
            rows = int(payload["rows"])
        except (ValueError, TypeError, KeyError) as exc:
            raise ProtocolError(
                f"malformed commit payload for unit {unit_id}: {exc}") \
                from None
        if rows < 0:
            raise ProtocolError(f"commit for unit {unit_id} with {rows} rows")
        reason = None
        first_commit = False
        with self._lock:
            unit = self._units[unit_id]
            if unit["status"] == self.LEASED and unit["worker"] == worker:
                unit["status"] = self.COMMITTED
                unit["rows"] = rows
                unit["payload"] = payload
                self.committed_total += 1
                first_commit = True
            elif (unit["status"] == self.COMMITTED
                  and unit["worker"] == worker):
                # idempotent ack: the worker's commit landed but the reply
                # was lost and it retried — the ledger already holds the
                # unit exactly once (and the committed counter must not
                # tick again: its contract is units, not acks)
                pass
            else:
                reason = ("already-committed"
                          if unit["status"] == self.COMMITTED
                          else "not-leaseholder")
                self.rejected_total += 1
        if reason is not None:
            logger.warning("rejected commit of unit %d from %s (%s)",
                           unit_id, worker, reason)
            telemetry.count("dmlc_fleet_commits_rejected_total",
                            reason=reason)
            return False
        if first_commit:
            telemetry.count("dmlc_fleet_units_committed_total")
        return True

    # -- results --------------------------------------------------------------
    def coverage(self) -> Tuple[int, int]:
        """(committed units, total units)."""
        with self._lock:
            done = sum(1 for u in self._units
                       if u["status"] == self.COMMITTED)
            return done, len(self._units)

    def ledger(self) -> Dict[int, Dict[str, Any]]:
        """unit_id -> {worker, rows, payload, assigned} for committed units —
        the authoritative exactly-once record."""
        with self._lock:
            return {i: {"worker": u["worker"], "rows": u["rows"],
                        "payload": u["payload"], "assigned": u["assigned"]}
                    for i, u in enumerate(self._units)
                    if u["status"] == self.COMMITTED}

    def result(self, timeout: Optional[float] = None) -> Dict[int, Dict[str, Any]]:
        """Wait for full coverage; return the ledger.  Raises
        :class:`TrackerError` on serve-loop death or when coverage is still
        incomplete at ``timeout`` (naming the uncommitted units and any
        failed workers — a degraded ingest must never read as a clean one)."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            with self._lock:
                error = self.error
                missing = [i for i, u in enumerate(self._units)
                           if u["status"] != self.COMMITTED]
                # snapshot under the lock: the serve thread pops entries
                # when a failed worker comes back, and a raced read here
                # would trade the coverage diagnostic for a KeyError
                failed = dict(self.failed_workers)
            if error:
                raise TrackerError(error)
            if not missing:
                return self.ledger()
            if deadline is not None and time.time() > deadline:
                detail = "; ".join(failed[w] for w in sorted(failed))
                raise TrackerError(
                    f"shard coverage incomplete: {len(missing)} of "
                    f"{len(self._units)} unit(s) uncommitted "
                    f"(e.g. {missing[:8]})"
                    + (f"; failed workers: {detail}" if detail else ""))
            time.sleep(0.05)

    def stop(self) -> None:
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass
        if self.thread is not None:
            self.thread.join(timeout=5.0)

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()


class PSTracker:
    """Parameter-server scheduler bootstrap (reference PSTracker,
    tracker.py:336-386): starts the ps-lite scheduler process locally and
    exports the DMLC_PS_ROOT env contract."""

    def __init__(self, host_ip: str, cmd: Optional[str], port: int = 9091,
                 port_end: int = 9999, envs: Optional[dict] = None):
        self.host_ip = host_ip
        self.cmd = cmd
        self._error: Optional[BaseException] = None
        if cmd:
            sock, self.port = bind_free_port(host_ip, port, port_end)
            sock.close()  # scheduler process rebinds it
            env = dict(os.environ)
            env.update({k: str(v) for k, v in (envs or {}).items()})
            env["DMLC_ROLE"] = "scheduler"
            env["DMLC_PS_ROOT_URI"] = str(host_ip)
            env["DMLC_PS_ROOT_PORT"] = str(self.port)

            def _run_scheduler() -> None:
                try:
                    subprocess.check_call(cmd, shell=True, env=env)
                except BaseException as exc:  # noqa: BLE001 - ferried to join
                    logger.error("ps scheduler failed: %s", exc)
                    self._error = exc

            self.thread = threading.Thread(target=_run_scheduler, daemon=True)
            self.thread.start()
        else:
            self.port = None
            self.thread = None

    def worker_envs(self) -> Dict[str, str]:
        if self.cmd:
            return {"DMLC_PS_ROOT_URI": self.host_ip,
                    "DMLC_PS_ROOT_PORT": str(self.port)}
        return {}

    def join(self) -> None:
        if self.thread is not None:
            self.thread.join()
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                f"ps-lite scheduler {self.cmd!r} failed") from err
