"""Rank rendezvous + topology service, wire-compatible with Rabit clients.

Reimplements the reference tracker protocol (tracker/dmlc_tracker/tracker.py):

- framed socket protocol: native-endian int32s and length-prefixed strings
  (ExSocket, tracker.py:24-47), handshake magic 0xff99 (tracker.py:50);
- commands: ``start`` / ``recover`` / ``print`` / ``shutdown``
  (tracker.py:269-291);
- batch rank assignment sorted by host (tracker.py:295-311) with
  jobid -> rank recovery (``WorkerEntry.resolve_rank``; reference
  tracker.py:73-78);
- topology: binary tree + parent map (tracker.py:185-191) and the
  tree-sharing data-recovery ring (tracker.py:193-225), relabeled so ring
  order is rank order (get_link_map, tracker.py:227-252);
- the link-brokering rounds that repeat until every rank reports all its
  links connected (``WorkerEntry.send_topology`` + ``broker_links``; same
  wire sequence as reference tracker.py:80-135, restructured here as
  topology push / brokering rounds / accept-registry bookkeeping).

On TPU the data plane no longer consumes these links (XLA collectives do the
reduction), but the tracker stays wire-compatible so existing Rabit clients
(XGBoost binaries) can rendezvous against it unchanged; our own workers use
only the env contract + ``jax.distributed`` coordination.
"""

from __future__ import annotations

import logging
import socket
import struct
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.telemetry import clock

logger = logging.getLogger("dmlc_core_tpu.tracker")

MAGIC = 0xFF99


class FramedSocket:
    """int32/length-prefixed-string framing (reference ExSocket)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock

    def recvall(self, nbytes: int) -> bytes:
        chunks = []
        nread = 0
        while nread < nbytes:
            chunk = self.sock.recv(min(nbytes - nread, 1024))
            if not chunk:
                raise ConnectionError("peer closed during recvall")
            nread += len(chunk)
            chunks.append(chunk)
        return b"".join(chunks)

    def recvint(self) -> int:
        return struct.unpack("@i", self.recvall(4))[0]

    def sendint(self, n: int) -> None:
        self.sock.sendall(struct.pack("@i", n))

    def sendstr(self, s: str) -> None:
        self.sendint(len(s))
        self.sock.sendall(s.encode())

    def recvstr(self) -> str:
        return self.recvall(self.recvint()).decode()


def _resolve_ip(host: str) -> str:
    return socket.getaddrinfo(host, None)[0][4][0]


class WorkerEntry:
    """One connected worker: the handshake state plus the per-worker half of
    the link-brokering conversation (wire-compatible with Rabit's client
    side; message sequence documented on each method)."""

    def __init__(self, sock: socket.socket, addr):
        connect_start = clock.monotonic()
        self.sock = FramedSocket(sock)
        self.host = _resolve_ip(addr[0])
        magic = self.sock.recvint()
        if magic != MAGIC:
            raise ConnectionError(f"invalid magic {magic:#x} from {self.host}")
        self.sock.sendint(MAGIC)
        self.rank = self.sock.recvint()
        self.world_size = self.sock.recvint()
        self.jobid = self.sock.recvstr()
        self.cmd = self.sock.recvstr()
        # connect-phase bracket, attributed to a rank once one is assigned
        # (assign_rank emits the span) — the per-rank rendezvous timeline is
        # connect -> assign -> barrier in the exported trace
        self.connect_span = (connect_start, clock.monotonic())
        # inbound links this worker still expects peers to dial (it stays in
        # the tracker's accept registry until this reaches zero)
        self.pending_accepts = 0
        # the worker's own listening port, reported at the end of brokering
        self.port: Optional[int] = None

    def resolve_rank(self, jobid_ranks: Dict[str, int]) -> int:
        """Keep a self-reported rank, else restore a restarted worker's old
        rank by job id, else -1 (rank to be assigned in host order)."""
        if self.rank >= 0:
            return self.rank
        return jobid_ranks.get(self.jobid, -1) if self.jobid != "NULL" else -1

    def send_topology(self, rank: int, world: int, tree_links: List[int],
                      parent: int, ring_prev: int, ring_next: int) -> set:
        """Push the assigned rank and its neighborhood down the wire.

        Wire order (fixed by the Rabit client): rank, parent, world size,
        tree-degree, each tree neighbor, ring-prev, ring-next — the ring
        slots carry -1 when absent or self-referential.  Returns the full
        link set (tree + real ring hops) this worker must establish.
        """
        self.rank = rank
        links = set(tree_links)
        self.sock.sendint(rank)
        self.sock.sendint(parent)
        self.sock.sendint(world)
        self.sock.sendint(len(links))
        # iterate the SET, not the list: the neighbor block is a set on the
        # wire, and the reference tracker emits it in set-iteration order —
        # doing the same keeps conversations byte-identical to it
        # (tests/test_tracker_conformance.py)
        for peer in links:
            self.sock.sendint(peer)
        for hop in (ring_prev, ring_next):
            if hop in (-1, rank):
                self.sock.sendint(-1)
            else:
                self.sock.sendint(hop)
                links.add(hop)
        return links

    def broker_links(self, links: set,
                     accept_registry: Dict[int, "WorkerEntry"]) -> List[int]:
        """Run brokering rounds until this worker's dial attempts all
        succeed.

        Each round: the worker reports which peers it already reached; the
        tracker answers with the subset of its missing peers that are
        listening right now (count, then host/port/rank triples) plus how
        many peers are not yet dialable (the worker must accept those
        inbound later).  A round that ends with connect errors repeats;
        a clean round ends with the worker reporting its own listening
        port.  Bookkeeping after a clean round: every peer this worker was
        told to dial has one fewer inbound accept outstanding — peers that
        reach zero are fully linked and leave ``accept_registry``; this
        worker records its own outstanding inbound count.  Returns the
        ranks that became fully linked.
        """
        while True:
            reached = {self.sock.recvint()
                       for _ in range(self.sock.recvint())}
            assert reached <= links, (reached, links)
            missing = links - reached
            dialable = [peer for peer in missing if peer in accept_registry]
            self.sock.sendint(len(dialable))
            self.sock.sendint(len(missing) - len(dialable))
            for peer in dialable:
                listener = accept_registry[peer]
                self.sock.sendstr(listener.host)
                self.sock.sendint(listener.port)
                self.sock.sendint(peer)
            dial_errors = self.sock.recvint()
            if dial_errors != 0:
                continue
            self.port = self.sock.recvint()
            fully_linked = []
            for peer in dialable:
                listener = accept_registry[peer]
                listener.pending_accepts -= 1
                if listener.pending_accepts == 0:
                    fully_linked.append(peer)
            for peer in fully_linked:
                accept_registry.pop(peer, None)
            self.pending_accepts = len(missing) - len(dialable)
            return fully_linked

    def assign_rank(self, rank: int,
                    accept_registry: Dict[int, "WorkerEntry"],
                    tree_map, parent_map, ring_map) -> List[int]:
        telemetry.record_span("rendezvous.connect", *self.connect_span,
                              rank=rank, host=self.host, cmd=self.cmd)
        assign_start = clock.monotonic()
        ring_prev, ring_next = ring_map[rank]
        links = self.send_topology(rank, len(tree_map), tree_map[rank],
                                   parent_map[rank], ring_prev, ring_next)
        out = self.broker_links(links, accept_registry)
        telemetry.record_span("rendezvous.assign", assign_start,
                              clock.monotonic(), rank=rank,
                              links=len(links))
        if telemetry.enabled():
            telemetry.observe("dmlc_rendezvous_assign_seconds",
                              clock.elapsed(assign_start))
            telemetry.count("dmlc_rendezvous_workers_total", cmd=self.cmd)
        return out


def bind_free_port(host: str, port: int = 9091,
                   port_end: int = 9999) -> Tuple[socket.socket, int]:
    """Bind the first free port in [port, port_end) (reference tracker.py:141-152)."""
    family = socket.getaddrinfo(host, None)[0][0]
    sock = socket.socket(family, socket.SOCK_STREAM)
    for p in range(port, port_end):
        try:
            sock.bind((host, p))
            return sock, p
        except socket.error as err:
            if err.errno in (98, 48):  # EADDRINUSE linux/mac
                continue
            raise
    raise OSError(f"no free port in [{port}, {port_end})")


class RabitTracker:
    """The rendezvous server (reference RabitTracker, tracker.py:137-334)."""

    def __init__(self, host_ip: str, num_workers: int, port: int = 9091,
                 port_end: int = 9999):
        self.sock, self.port = bind_free_port(host_ip, port, port_end)
        self.sock.listen(256)
        self.host_ip = host_ip
        self.num_workers = num_workers
        self.thread: Optional[threading.Thread] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        logger.info("start listening on %s:%d", host_ip, self.port)

    # -- topology (tracker.py:165-252) ---------------------------------------
    @staticmethod
    def _tree_neighbors(rank: int, n: int) -> List[int]:
        rank = rank + 1
        out = []
        if rank > 1:
            out.append(rank // 2 - 1)
        if rank * 2 - 1 < n:
            out.append(rank * 2 - 1)
        if rank * 2 < n:
            out.append(rank * 2)
        return out

    @classmethod
    def get_tree(cls, n: int):
        tree_map = {r: cls._tree_neighbors(r, n) for r in range(n)}
        parent_map = {r: (r + 1) // 2 - 1 for r in range(n)}
        return tree_map, parent_map

    @classmethod
    def _share_ring_order(cls, tree_map, parent_map, r: int) -> List[int]:
        """DFS order that keeps ring hops close to tree links (used to recover
        local data, reference tracker.py:193-214)."""
        children = set(tree_map[r]) - {parent_map[r]}
        if not children:
            return [r]
        out = [r]
        for i, v in enumerate(sorted(children)):
            sub = cls._share_ring_order(tree_map, parent_map, v)
            if i == len(children) - 1:
                sub.reverse()
            out += sub
        return out

    @classmethod
    def get_ring(cls, tree_map, parent_map):
        order = cls._share_ring_order(tree_map, parent_map, 0)
        assert len(order) == len(tree_map)
        n = len(tree_map)
        ring_map = {}
        for i in range(n):
            ring_map[order[i]] = (order[(i - 1) % n], order[(i + 1) % n])
        return ring_map

    @classmethod
    def get_link_map(cls, n: int):
        """Relabel ranks so ring order == rank order (tracker.py:227-252)."""
        tree_map, parent_map = cls.get_tree(n)
        ring_map = cls.get_ring(tree_map, parent_map)
        rmap = {0: 0}
        k = 0
        for i in range(n - 1):
            k = ring_map[k][1]
            rmap[k] = i + 1
        ring_out = {rmap[k]: (rmap[v[0]], rmap[v[1]]) for k, v in ring_map.items()}
        tree_out = {rmap[k]: [rmap[x] for x in v] for k, v in tree_map.items()}
        parent_out = {rmap[k]: (rmap[v] if k != 0 else -1)
                      for k, v in parent_map.items()}
        return tree_out, parent_out, ring_out

    # -- env contract ---------------------------------------------------------
    def worker_envs(self) -> Dict[str, str]:
        return {"DMLC_TRACKER_URI": self.host_ip,
                "DMLC_TRACKER_PORT": str(self.port)}

    # -- accept loop (tracker.py:254-320) -------------------------------------
    def _accept_workers(self, n: int) -> None:
        shutdown: Dict[int, WorkerEntry] = {}
        accept_registry: Dict[int, WorkerEntry] = {}
        jobid_ranks: Dict[str, int] = {}
        pending: List[WorkerEntry] = []
        tree_map = None
        todo_nodes: List[int] = []
        barrier_start: Optional[float] = None
        while len(shutdown) != n:
            fd, addr = self.sock.accept()
            try:
                s = WorkerEntry(fd, addr)
            except ConnectionError as err:
                logger.warning("rejected connection: %s", err)
                fd.close()
                continue
            if s.cmd == "print":
                logger.info(s.sock.recvstr().strip())
                continue
            if s.cmd == "shutdown":
                assert s.rank >= 0 and s.rank not in shutdown
                shutdown[s.rank] = s
                logger.debug("shutdown signal from %d", s.rank)
                continue
            assert s.cmd in ("start", "recover"), s.cmd
            if barrier_start is None:
                # barrier = first worker knocking until all n are started
                barrier_start = s.connect_span[0]
            if tree_map is None:
                assert s.cmd == "start"
                if s.world_size > 0:
                    n = s.world_size
                tree_map, parent_map, ring_map = self.get_link_map(n)
                todo_nodes = list(range(n))
            else:
                assert s.world_size in (-1, n)
            if s.cmd == "recover":
                assert s.rank >= 0
            rank = s.resolve_rank(jobid_ranks)
            if rank == -1:
                assert todo_nodes
                pending.append(s)
                if len(pending) == len(todo_nodes):
                    pending.sort(key=lambda x: x.host)
                    for p in pending:
                        rank = todo_nodes.pop(0)
                        if p.jobid != "NULL":
                            jobid_ranks[p.jobid] = rank
                        p.assign_rank(rank, accept_registry, tree_map,
                                      parent_map, ring_map)
                        if p.pending_accepts > 0:
                            accept_registry[rank] = p
                        logger.debug("%s from %s; assigned rank %d",
                                     p.cmd, p.host, p.rank)
                    pending = []
                if not todo_nodes:
                    logger.info("@tracker all of %d nodes started", n)
                    self.start_time = time.time()
                    if barrier_start is not None:
                        telemetry.record_span("rendezvous.barrier",
                                              barrier_start, clock.monotonic(),
                                              world=n)
                        telemetry.observe("dmlc_rendezvous_barrier_seconds",
                                          clock.elapsed(barrier_start))
            else:
                s.assign_rank(rank, accept_registry, tree_map, parent_map,
                              ring_map)
                logger.debug("%s signal from %d", s.cmd, s.rank)
                if s.pending_accepts > 0:
                    accept_registry[rank] = s
        self.end_time = time.time()
        logger.info("@tracker all nodes finished; %.3f secs between start and finish",
                    (self.end_time - (self.start_time or self.end_time)))

    def start(self, num_workers: Optional[int] = None) -> None:
        n = num_workers if num_workers is not None else self.num_workers
        self.thread = threading.Thread(target=self._accept_workers, args=(n,),
                                       daemon=True)
        self.thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.time() + timeout
        while self.thread.is_alive():
            self.thread.join(0.1)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("tracker did not finish in time")

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()


class PSTracker:
    """Parameter-server scheduler bootstrap (reference PSTracker,
    tracker.py:336-386): starts the ps-lite scheduler process locally and
    exports the DMLC_PS_ROOT env contract."""

    def __init__(self, host_ip: str, cmd: Optional[str], port: int = 9091,
                 port_end: int = 9999, envs: Optional[dict] = None):
        self.host_ip = host_ip
        self.cmd = cmd
        self._error: Optional[BaseException] = None
        if cmd:
            sock, self.port = bind_free_port(host_ip, port, port_end)
            sock.close()  # scheduler process rebinds it
            env = dict(__import__("os").environ)
            env.update({k: str(v) for k, v in (envs or {}).items()})
            env["DMLC_ROLE"] = "scheduler"
            env["DMLC_PS_ROOT_URI"] = str(host_ip)
            env["DMLC_PS_ROOT_PORT"] = str(self.port)

            def _run_scheduler() -> None:
                try:
                    subprocess.check_call(cmd, shell=True, env=env)
                except BaseException as exc:  # noqa: BLE001 - ferried to join
                    logger.error("ps scheduler failed: %s", exc)
                    self._error = exc

            self.thread = threading.Thread(target=_run_scheduler, daemon=True)
            self.thread.start()
        else:
            self.port = None
            self.thread = None

    def worker_envs(self) -> Dict[str, str]:
        if self.cmd:
            return {"DMLC_PS_ROOT_URI": self.host_ip,
                    "DMLC_PS_ROOT_PORT": str(self.port)}
        return {}

    def join(self) -> None:
        if self.thread is not None:
            self.thread.join()
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                f"ps-lite scheduler {self.cmd!r} failed") from err
