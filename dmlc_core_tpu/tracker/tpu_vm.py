"""TPU-VM backend: one process per TPU-VM host, jax.distributed wired.

The new backend the north star asks for (BASELINE.json: "the dmlc_tracker /
dmlc-submit launcher gains a tpu-vm backend"): launch the worker command on
every host of a TPU pod slice and let ``dmlc_core_tpu.collective.init`` bring
up ``jax.distributed`` from the env contract.

Two launch paths:
- with ``--host-file``: ssh to each TPU-VM worker (reuses the ssh machinery);
- without: shell out to ``gcloud compute tpus tpu-vm ssh --worker=all`` using
  ``TPU_NAME``/``TPU_ZONE`` env (the standard gcloud flow).

On TPU the per-rank count is *hosts*, not chips: each process drives its local
chips and jax handles the global device view, so ``--num-workers`` should be
the host count of the slice (e.g. 2 for v5e-16).  Rank recovery keeps the
reference's jobid semantics, but note SPMD reality (SURVEY.md §5.3): a lost
host means the whole slice restarts and resumes from the latest checkpoint
(bridge.checkpoint), not per-rank healing.
"""

from __future__ import annotations

import logging
import os
import subprocess
import uuid
from functools import partial
from typing import Dict

from dmlc_core_tpu.tracker.submit import run_ferried, submit_job
from dmlc_core_tpu.tracker.ssh import (FORWARD_ENV, _shquote, _ssh_command,
                                       parse_host_file)

__all__ = ["submit"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def _gcloud_cmd(env: Dict[str, str], command) -> list:
    tpu_name = os.environ.get("TPU_NAME")
    zone = os.environ.get("TPU_ZONE", "")
    assert tpu_name, "tpu-vm backend needs --host-file or TPU_NAME env"
    exports = "; ".join(f"export {k}={_shquote(v)}" for k, v in env.items())
    # the per-host task id MUST expand on the remote host (every host gets
    # the same command line; only TPU_WORKER_ID differs there) — a quoted
    # literal would give every host process id 0 and deadlock rendezvous
    exports += '; export DMLC_TASK_ID="${TPU_WORKER_ID:-0}"'
    remote = f"{exports}; {' '.join(map(_shquote, command))}"
    cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
           "--worker=all", f"--command={remote}"]
    if zone:
        cmd.append(f"--zone={zone}")
    return cmd


def submit(opts) -> None:
    # file shipping (opt-in via --files/--archives): host-file path ships
    # by scp like the ssh backend; the gcloud path exports the
    # DMLC_JOB_FILES/ARCHIVES contract and wraps the command in the
    # launcher, which materializes from host-visible sources (e.g. the
    # GCS-fused paths TPU-VMs mount)
    from dmlc_core_tpu.tracker.filecache import (prepare_scp_shipping,
                                                 wrap_launcher_cmd)
    from dmlc_core_tpu.tracker.ssh import _unpack_prelude, ship_files

    ship_env, command, shipped, archives = prepare_scp_shipping(opts)
    prelude = _unpack_prelude(archives)

    def fun_submit(envs: Dict[str, str]) -> None:
        base_env = dict(envs)
        for key in FORWARD_ENV:
            if key in os.environ:
                base_env.setdefault(key, os.environ[key])
        if opts.host_file:
            hosts = parse_host_file(opts.host_file, opts.ssh_port)
            assert len(hosts) >= opts.num_workers, \
                "host file has fewer hosts than --num-workers"
            workdir = opts.sync_dst_dir or "."
            for host, port in set(hosts[:opts.num_workers]):
                ship_files(shipped, host, port, workdir)
            tasks = []
            for taskid in range(opts.num_workers):
                host, port = hosts[taskid]
                env = dict(base_env)
                env["DMLC_ROLE"] = "worker"
                env["DMLC_TASK_ID"] = str(taskid)
                cmd = _ssh_command(host, port, env, workdir, command,
                                   prelude=prelude)
                tasks.append((f"tpu-vm worker {taskid}",
                              partial(subprocess.check_call, cmd)))
            run_ferried(tasks)
        else:
            # gcloud path: the TPU runtime provides per-host task ids via
            # TPU_WORKER_ID; _gcloud_cmd emits the (unquoted, host-side)
            # DMLC_TASK_ID export itself.
            env = dict(base_env)
            env["DMLC_ROLE"] = "worker"
            gcmd = command
            if ship_env:
                env.update(ship_env)
                # the gcloud ssh session lands in the VM user's persistent
                # home dir — materializing there would serve STALE files on
                # resubmit (skip-if-exists semantics); give every job its
                # own cwd, which the launcher creates and chdirs into
                env["DMLC_JOB_CWD"] = (f"dmlc-jobs/{opts.jobname}-"
                                       f"{uuid.uuid4().hex[:8]}")
                gcmd = wrap_launcher_cmd(command)
            subprocess.check_call(_gcloud_cmd(env, gcmd))

    submit_job(opts, fun_submit, wait=False)
