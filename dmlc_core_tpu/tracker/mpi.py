"""MPI backend: launch worker/server waves under mpirun.

Reference: tracker/dmlc_tracker/mpi.py:12-82 — OpenMPI-vs-MPICH env-flag
detection (23-35) and separate mpirun waves for servers and workers (55-77).
"""

from __future__ import annotations

import logging
import subprocess
from functools import partial
from typing import Dict

from dmlc_core_tpu.tracker.submit import run_ferried, submit_job

__all__ = ["submit"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def _detect_mpi_env_flag() -> str:
    """'-x' for OpenMPI, '-env' for MPICH (reference mpi.py:23-35)."""
    try:
        out = subprocess.run(["mpirun", "--version"], capture_output=True,
                             text=True, timeout=10).stdout.lower()
    except (OSError, subprocess.TimeoutExpired):
        return "-x"
    if "open mpi" in out or "open-rte" in out:
        return "-x"
    return "-env"


def submit(opts) -> None:
    flag = _detect_mpi_env_flag()

    def _mpirun(role: str, n: int, envs: Dict[str, str]) -> None:
        if n == 0:
            return
        cmd = ["mpirun", "-n", str(n)]
        if opts.host_file:
            cmd += ["--hostfile", opts.host_file]
        env = dict(envs)
        env["DMLC_ROLE"] = role
        env["DMLC_JOB_CLUSTER"] = "mpi"
        for k, v in env.items():
            if flag == "-x":
                cmd += ["-x", f"{k}={v}"]
            else:
                cmd += ["-env", k, str(v)]
        cmd += list(opts.command)
        logger.debug("mpirun: %s", " ".join(cmd))
        subprocess.check_call(cmd)

    def fun_submit(envs: Dict[str, str]) -> None:
        run_ferried([(f"mpirun for role {role}",
                      partial(_mpirun, role, n, envs))
                     for role, n in (("server", opts.num_servers),
                                     ("worker", opts.num_workers))])

    submit_job(opts, fun_submit, wait=False)
