"""Sun Grid Engine backend: qsub array job.

Reference: tracker/dmlc_tracker/sge.py — generates a run script exporting
``DMLC_TASK_ID=$SGE_TASK_ID`` and submits it as an array job.
"""

from __future__ import annotations

import logging
import os
import stat
import subprocess
from typing import Dict

from dmlc_core_tpu.tracker.ssh import _shquote
from dmlc_core_tpu.tracker.submit import submit_job

__all__ = ["submit"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def submit(opts) -> None:
    # file shipping (opt-in via --files/--archives: qsub -cwd already runs
    # tasks in the shared-FS submit dir): wrap the task in the launcher,
    # which materializes DMLC_JOB_FILES / unpacks DMLC_JOB_ARCHIVES into
    # the task cwd
    from dmlc_core_tpu.tracker.filecache import prepare_shipping

    ship_env, command, _, _ = prepare_shipping(opts, wrap_launcher=True)

    def fun_submit(envs: Dict[str, str]) -> None:
        envs = {**envs, **ship_env}
        runscript = os.path.join(os.getcwd(), f"{opts.jobname}.sge.sh")
        with open(runscript, "w") as f:
            f.write("#!/bin/bash\n#$ -S /bin/bash\n")
            f.write(f"#$ -q {opts.queue}\n")
            f.write("GLOBAL_ID=$((SGE_TASK_ID - 1))\n")
            for k, v in envs.items():
                f.write(f"export {k}={_shquote(v)}\n")
            # task ids are role-relative (workers 0..nw-1, servers 0..ns-1):
            # DMLC_TASK_ID is the collective's process id, so a server
            # offset would corrupt worker rank identity (ssh.py computes
            # the same split)
            f.write('if [ "$GLOBAL_ID" -lt "%d" ]; then\n'
                    '  export DMLC_ROLE=server\n'
                    '  export DMLC_TASK_ID=$GLOBAL_ID\nelse\n'
                    '  export DMLC_ROLE=worker\n'
                    '  export DMLC_TASK_ID=$((GLOBAL_ID - %d))\nfi\n'
                    % (opts.num_servers, opts.num_servers))
            f.write(" ".join(map(_shquote, command)) + "\n")
        os.chmod(runscript, os.stat(runscript).st_mode | stat.S_IEXEC)
        n = opts.num_workers + opts.num_servers
        cmd = ["qsub", "-cwd", "-t", f"1-{n}",
               "-pe", "smp", str(opts.worker_cores),
               "-N", opts.jobname, runscript]
        logger.info("qsub: %s", " ".join(cmd))
        subprocess.check_call(cmd)

    submit_job(opts, fun_submit, wait=True)
