"""Sun Grid Engine backend: qsub array job.

Reference: tracker/dmlc_tracker/sge.py — generates a run script exporting
``DMLC_TASK_ID=$SGE_TASK_ID`` and submits it as an array job.
"""

from __future__ import annotations

import logging
import os
import stat
import subprocess
from typing import Dict

from dmlc_core_tpu.tracker.submit import submit_job

__all__ = ["submit"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def submit(opts) -> None:
    def fun_submit(envs: Dict[str, str]) -> None:
        runscript = os.path.join(os.getcwd(), f"{opts.jobname}.sge.sh")
        with open(runscript, "w") as f:
            f.write("#!/bin/bash\n#$ -S /bin/bash\n")
            f.write(f"#$ -q {opts.queue}\n")
            f.write("export DMLC_TASK_ID=$((SGE_TASK_ID - 1))\n")
            for k, v in envs.items():
                f.write(f"export {k}={v}\n")
            f.write('if [ "$DMLC_TASK_ID" -lt "%d" ]; then\n'
                    '  export DMLC_ROLE=server\nelse\n'
                    '  export DMLC_ROLE=worker\nfi\n' % opts.num_servers)
            f.write(" ".join(opts.command) + "\n")
        os.chmod(runscript, os.stat(runscript).st_mode | stat.S_IEXEC)
        n = opts.num_workers + opts.num_servers
        cmd = ["qsub", "-cwd", "-t", f"1-{n}",
               "-pe", "smp", str(opts.worker_cores),
               "-N", opts.jobname, runscript]
        logger.info("qsub: %s", " ".join(cmd))
        subprocess.check_call(cmd)

    submit_job(opts, fun_submit, wait=True)
