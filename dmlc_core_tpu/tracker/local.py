"""Local backend: one subprocess per worker/server on this host.

Reference: tracker/dmlc_tracker/local.py:12-72 — thread-per-process launch,
``DMLC_TASK_ID``/``DMLC_ROLE`` env, retry via ``DMLC_NUM_ATTEMPT``.
"""

from __future__ import annotations

import logging
import os
import subprocess
import tempfile
import threading
from typing import Dict, List, Optional

import shutil

from dmlc_core_tpu.tracker.filecache import prepare_shipping, stage_job_dir
from dmlc_core_tpu.tracker.submit import submit_job

__all__ = ["submit", "exec_cmd"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def exec_cmd(cmd: List[str], role: str, taskid: int, pass_env: Dict[str, str],
             num_attempt: int = 1, cwd: Optional[str] = None) -> None:
    """Run one task with retry (reference local.py:25-40).

    ``num_attempt`` is the total attempt budget; like the reference, the
    ``DMLC_NUM_ATTEMPT`` env var is exported once (the configured budget)
    and never mutated across retries.  ``cwd`` is the staged job dir when
    the submit shipped files (the local stand-in for a container sandbox).
    """
    env = os.environ.copy()
    env.update(pass_env)
    env["DMLC_TASK_ID"] = str(taskid)
    env["DMLC_ROLE"] = role
    env["DMLC_NUM_ATTEMPT"] = str(num_attempt)
    num_retry = num_attempt
    while True:
        ret = subprocess.call(cmd, env=env, cwd=cwd)
        if ret == 0:
            logger.debug("task %s:%d finished", role, taskid)
            return
        num_retry -= 1
        if num_retry <= 0:
            raise RuntimeError(f"task {role}:{taskid} failed with exit {ret}")
        logger.warning("task %s:%d failed (exit %d); retrying", role, taskid, ret)


def submit(opts) -> None:
    # file shipping: only when the job names files/archives explicitly —
    # a bare local run keeps its cwd and command untouched (no surprise
    # directory changes for jobs that never opted into shipping)
    ship_env, command, files, archives = prepare_shipping(opts)
    job_dir = None
    if files or archives:
        job_dir = tempfile.mkdtemp(prefix="dmlc-job-")
        try:
            stage_job_dir(files, archives, job_dir)
        except BaseException:
            # staging failed before anything owns the dir: fun_submit's
            # finally (the normal cleanup path) never runs on this edge
            shutil.rmtree(job_dir, ignore_errors=True)
            raise
        ship_env["DMLC_JOB_CWD"] = job_dir
        logger.info("staged %d files / %d archives into %s",
                    len(files), len(archives), job_dir)

    def fun_submit(envs: Dict[str, str]) -> None:
        envs = {**envs, **ship_env}
        threads = []
        errors: List[BaseException] = []

        def run(role: str, taskid: int) -> None:
            try:
                exec_cmd(command, role, taskid, envs,
                         num_attempt=getattr(opts, "num_attempt", 1),
                         cwd=job_dir)
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        for i in range(opts.num_servers):
            t = threading.Thread(target=run, args=("server", i), daemon=True)
            t.start()
            threads.append(t)
        for i in range(opts.num_workers):
            t = threading.Thread(target=run, args=("worker", i), daemon=True)
            t.start()
            threads.append(t)
        try:
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
        finally:
            if job_dir is not None:
                shutil.rmtree(job_dir, ignore_errors=True)

    try:
        submit_job(opts, fun_submit, wait=False)
    except BaseException:
        # tracker bring-up can fail before fun_submit (and its finally)
        # ever runs; fun_submit's own cleanup already ran when it did run,
        # and rmtree(ignore_errors) is safe to repeat
        if job_dir is not None:
            shutil.rmtree(job_dir, ignore_errors=True)
        raise
