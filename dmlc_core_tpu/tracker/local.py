"""Local backend: one subprocess per worker/server on this host.

Reference: tracker/dmlc_tracker/local.py:12-72 — thread-per-process launch,
``DMLC_TASK_ID``/``DMLC_ROLE`` env, retry via ``DMLC_NUM_ATTEMPT``.
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading
from typing import Dict, List

from dmlc_core_tpu.tracker.submit import submit_job

__all__ = ["submit", "exec_cmd"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def exec_cmd(cmd: List[str], role: str, taskid: int, pass_env: Dict[str, str],
             num_attempt: int = 1) -> None:
    """Run one task with retry (reference local.py:25-40).

    ``num_attempt`` is the total attempt budget; like the reference, the
    ``DMLC_NUM_ATTEMPT`` env var is exported once (the configured budget)
    and never mutated across retries.
    """
    env = os.environ.copy()
    env.update(pass_env)
    env["DMLC_TASK_ID"] = str(taskid)
    env["DMLC_ROLE"] = role
    env["DMLC_NUM_ATTEMPT"] = str(num_attempt)
    num_retry = num_attempt
    while True:
        ret = subprocess.call(cmd, env=env)
        if ret == 0:
            logger.debug("task %s:%d finished", role, taskid)
            return
        num_retry -= 1
        if num_retry <= 0:
            raise RuntimeError(f"task {role}:{taskid} failed with exit {ret}")
        logger.warning("task %s:%d failed (exit %d); retrying", role, taskid, ret)


def submit(opts) -> None:
    def fun_submit(envs: Dict[str, str]) -> None:
        threads = []
        errors: List[BaseException] = []

        def run(role: str, taskid: int) -> None:
            try:
                exec_cmd(opts.command, role, taskid, envs,
                         num_attempt=getattr(opts, "num_attempt", 1))
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        for i in range(opts.num_servers):
            t = threading.Thread(target=run, args=("server", i), daemon=True)
            t.start()
            threads.append(t)
        for i in range(opts.num_workers):
            t = threading.Thread(target=run, args=("worker", i), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    submit_job(opts, fun_submit, wait=False)
