"""Container supervision: the YARN ApplicationMaster's retry/blacklist brain.

The reference supervises its own containers from a 687-LoC Java
ApplicationMaster: each task retries up to ``maxNumAttempt`` (default 3,
``DMLC_MAX_ATTEMPT``; ApplicationMaster.java:74,210), a failing container's
node goes onto a blacklist (ApplicationMaster.java:112,554) so later
allocations on that node are burned with a dummy task instead of a real one
(ApplicationMaster.java:486-488), memory-limit kills abort the whole job
(ApplicationMaster.java:585-600), and exhausting attempts aborts with the
task named (ApplicationMaster.java:558-561).

This module is that state machine, extracted from the YARN callback plumbing
so it is (a) unit-testable against a fake cluster and (b) reusable by any
launcher that can report "container started on node N" / "container finished
with status S" — the TPU-VM and local backends see the same failure shapes.
The YARN REST wiring lives in :mod:`.yarn`.

Event protocol (mirrors the AMRMClientAsync callbacks):

- :meth:`ContainerSupervisor.start` queues every task as pending and asks the
  cluster for containers (submitTasks, ApplicationMaster.java:308-324).
- :meth:`on_containers_allocated` — for each offered container: blacklisted
  node -> ``cluster.burn`` (the dummy-task move), no pending work ->
  ``cluster.release``, else ``cluster.launch`` (onContainersAllocated,
  ApplicationMaster.java:478-500).
- :meth:`on_container_completed` — SUCCESS finishes the task; memory-kill
  statuses abort the job; any other failure bumps the attempt counter,
  blacklists the node, and resubmits (onContainersCompleted + handleFailure,
  ApplicationMaster.java:535-613).
- :meth:`on_container_error` — NM-side launch error: same failure path
  (onStartContainerError, ApplicationMaster.java:655-673).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from dmlc_core_tpu.param import get_env

__all__ = ["Container", "TaskRecord", "ClusterBackend", "JobAbort",
           "ContainerSupervisor", "EXIT_SUCCESS", "EXIT_KILLED_PMEM",
           "EXIT_KILLED_VMEM"]

logger = logging.getLogger("dmlc_core_tpu.tracker")

# YARN ContainerExitStatus values the AM special-cases
EXIT_SUCCESS = 0
EXIT_KILLED_PMEM = -104   # KILLED_EXCEEDED_PMEM
EXIT_KILLED_VMEM = -103   # KILLED_EXCEEDED_VMEM


@dataclass(frozen=True)
class Container:
    """An allocated container: identity + the node it landed on.

    ``task_id`` is set by backends whose containers are pre-bound to a task
    (the REST adapter bakes DMLC_TASK_ID into each app's command at submit
    time); the supervisor then matches the exact task instead of FIFO-popping
    pending work — out-of-order RUNNING reports must not misattribute tasks.
    YARN-AM-style backends where any container serves any task leave it None.
    """

    container_id: str
    node: str
    task_id: Optional[int] = None


@dataclass
class TaskRecord:
    """Reference TaskRecord.java: task identity + attempt bookkeeping."""

    task_id: int
    role: str = "worker"
    attempts: int = 0
    container: Optional[Container] = None


class ClusterBackend:
    """What the supervisor needs from a cluster (the RM/NM client surface).

    Implementations: the REST adapter in :mod:`.yarn`, fakes in tests.
    """

    def request_containers(self, tasks: List[TaskRecord]) -> None:
        """Ask for one container per task (rmClient.addContainerRequest)."""
        raise NotImplementedError

    def launch(self, container: Container, task: TaskRecord) -> None:
        """Start the task's command in the container (nmClient.startContainerAsync)."""
        raise NotImplementedError

    def burn(self, container: Container) -> None:
        """Launch a no-op in a container on a blacklisted node.

        The reference cannot return a tainted container without the RM
        re-offering it, so it runs ``./launcher.py`` with no command — a
        dummy task (launchDummyTask, ApplicationMaster.java:329-345).
        """
        raise NotImplementedError

    def release(self, container: Container) -> None:
        """Free a surplus container (freeUnusedContainers)."""
        raise NotImplementedError

    def stop(self, container: Container) -> None:
        """Stop a failed container (nmClient.stopContainerAsync)."""
        raise NotImplementedError

    def cancel_requests(self, tasks: List[TaskRecord]) -> None:
        """Withdraw outstanding container requests on abort.

        REST-model backends have a live application per pending task; leaving
        them running after a JobAbort would leak cluster resources.  Default
        no-op matches the reference AM (the RM reclaims open requests when
        the AM unregisters).
        """


class JobAbort(RuntimeError):
    """Raised when the job must die (abortJob, ApplicationMaster.java:616)."""


class ContainerSupervisor:
    """Per-task retry + node blacklist over a :class:`ClusterBackend`.

    Single-threaded by design: callers serialize events into it (the
    reference reaches the same effect by making every callback
    ``synchronized``).
    """

    def __init__(self, cluster: ClusterBackend, num_workers: int,
                 num_servers: int = 0, max_attempts: Optional[int] = None):
        if max_attempts is None:
            # reference: DMLC_MAX_ATTEMPT env, default 3
            max_attempts = get_env("DMLC_MAX_ATTEMPT", int, 3)
        self.cluster = cluster
        self.max_attempts = max_attempts
        self.tasks = ([TaskRecord(i, "worker") for i in range(num_workers)]
                      + [TaskRecord(num_workers + i, "server")
                         for i in range(num_servers)])
        self.pending: List[TaskRecord] = []
        self.running: Dict[str, TaskRecord] = {}
        self.finished: List[TaskRecord] = []
        self.killed: List[TaskRecord] = []
        self.blacklist: Set[str] = set()
        self.aborted: Optional[str] = None   # diagnosis once aborting

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._submit(list(self.tasks))

    @property
    def done(self) -> bool:
        return (self.aborted is None and not self.pending and not self.running
                and len(self.finished) == len(self.tasks))

    # -- event handlers ------------------------------------------------------
    def on_containers_allocated(self, containers: List[Container]) -> None:
        if self.aborted is not None:
            for c in containers:
                self.cluster.release(c)
            return
        for c in containers:
            if c.node in self.blacklist:
                logger.info("container %s on blacklisted node %s: burning",
                            c.container_id, c.node)
                self.cluster.burn(c)
                continue
            task = self._match_pending(c)
            if task is None:
                self.cluster.release(c)
                continue
            task.container = c
            self.running[c.container_id] = task
            self.cluster.launch(c, task)

    def on_container_completed(self, container_id: str, exit_status: int,
                               diagnostics: str = "") -> None:
        task = self.running.get(container_id)
        if task is None:
            return
        if exit_status == EXIT_SUCCESS:
            del self.running[container_id]
            task.container = None
            self.finished.append(task)
            return
        if exit_status in (EXIT_KILLED_PMEM, EXIT_KILLED_VMEM):
            kind = "physical" if exit_status == EXIT_KILLED_PMEM else "virtual"
            self._abort(f"[DMLC] Task {task.task_id} killed because of "
                        f"exceeding allocated {kind} memory")
            return
        logger.info("[DMLC] Task %d exited with status %d Diagnostics: %s",
                    task.task_id, exit_status, diagnostics)
        self._handle_failure(container_id)

    def on_container_error(self, container_id: str, error: str) -> None:
        """NM could not start / lost the container: treated as a failure."""
        logger.warning("container %s error: %s", container_id, error)
        self._handle_failure(container_id)

    def on_unreported_completion(self, c: Container, exit_status: int,
                                 diagnostics: str = "") -> None:
        """Terminal event for a container that never reported a placement.

        REST-model backends can see an app jump straight to FAILED/FINISHED
        between polls (fast-failing command, queue rejection).  Routing that
        through the allocation path would be wrong — a blacklisted node would
        burn the already-dead container and swallow the completion — so the
        task is matched and completed directly: successes count, failures
        bump the attempt counter like any other.
        """
        task = self._match_pending(c)
        if task is None:
            return
        task.container = c
        self.running[c.container_id] = task
        self.on_container_completed(c.container_id, exit_status, diagnostics)

    # -- internals -----------------------------------------------------------
    def _match_pending(self, c: Container) -> Optional[TaskRecord]:
        """The pending task this container serves: the pre-bound one when the
        container names a task, else the head of the queue."""
        if c.task_id is None:
            return self.pending.pop(0) if self.pending else None
        for i, task in enumerate(self.pending):
            if task.task_id == c.task_id:
                return self.pending.pop(i)
        return None

    def _submit(self, tasks: List[TaskRecord]) -> None:
        self.pending.extend(tasks)
        self.cluster.request_containers(tasks)

    def _handle_failure(self, container_id: str) -> None:
        task = self.running.pop(container_id, None)
        if task is None:
            return
        container = task.container
        task.attempts += 1
        task.container = None
        if container is not None:
            # stop the failed container and blacklist its node (containers
            # that died before ever reporting a placement have no node)
            self.cluster.stop(container)
            if container.node:
                self.blacklist.add(container.node)
            logger.info("task %d failed on %s (attempt %d/%d); node "
                        "blacklisted", task.task_id, container.node,
                        task.attempts, self.max_attempts)
        if task.attempts >= self.max_attempts:
            self.killed.append(task)
            self._abort(f"[DMLC] Task {task.task_id} failed more than "
                        f"{task.attempts} times")
            return
        if self.aborted is not None:
            self.killed.append(task)
            return
        self._submit([task])

    def _abort(self, diagnosis: str) -> None:
        if self.aborted is None:
            self.aborted = diagnosis
            logger.error("%s", diagnosis)
        # running containers are stopped; pending work (and any outstanding
        # container requests backing it) is withdrawn
        for cid, task in list(self.running.items()):
            if task.container is not None:
                self.cluster.stop(task.container)
            self.killed.append(task)
        self.running.clear()
        if self.pending:
            self.cluster.cancel_requests(list(self.pending))
            self.killed.extend(self.pending)
            self.pending.clear()
        raise JobAbort(diagnosis)
