"""SSH backend: launch workers over ssh with env forwarding + workdir rsync.

Reference: tracker/dmlc_tracker/ssh.py:13-85 — host-file parsing with optional
ports (43-53), rsync of the working dir (13-21), env-forward whitelist
including cloud credentials (26-27).
"""

from __future__ import annotations

import inspect
import logging
import os
import subprocess
from functools import partial
from typing import Dict, List, Tuple

from dmlc_core_tpu.tracker.filecache import extract_archive_atomic
from dmlc_core_tpu.tracker.submit import run_ferried, submit_job

__all__ = ["submit", "parse_host_file"]

logger = logging.getLogger("dmlc_core_tpu.tracker")

# env vars forwarded to remote workers (reference ssh.py:26-27 + TPU additions)
FORWARD_ENV = [
    "LD_LIBRARY_PATH", "PYTHONPATH", "DMLC_INTERFACE",
    "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY", "AWS_SESSION_TOKEN",
    "AWS_REGION", "GOOGLE_APPLICATION_CREDENTIALS",
    "TPU_NAME", "JAX_PLATFORMS",
]


def parse_host_file(path: str, default_port: int = 22) -> List[Tuple[str, int]]:
    """Lines of ``host`` or ``host:port`` (reference ssh.py:43-53)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            if ":" in line:
                host, port = line.rsplit(":", 1)
                hosts.append((host, int(port)))
            else:
                hosts.append((line, default_port))
    return hosts


def sync_dir(local_dir: str, host: str, port: int, remote_dir: str) -> None:
    """rsync the working directory to the remote host (reference ssh.py:13-21)."""
    cmd = ["rsync", "-az", "--rsh", f"ssh -o StrictHostKeyChecking=no -p {port}",
           local_dir + "/", f"{host}:{remote_dir}/"]
    logger.debug("rsync: %s", " ".join(cmd))
    subprocess.check_call(cmd)


def ship_files(specs: List[str], host: str, port: int,
               remote_dir: str) -> None:
    """scp the job's cached ``src#dest`` entries into the remote workdir
    under their dest names (the ssh-backend leg of the --files/--archives
    contract)."""
    from dmlc_core_tpu.tracker.filecache import split_spec_item

    for item in specs:
        src, dest = split_spec_item(item)
        cmd = ["scp", "-o", "StrictHostKeyChecking=no", "-P", str(port),
               src, f"{host}:{remote_dir}/{dest}"]
        logger.debug("scp: %s", " ".join(cmd))
        subprocess.check_call(cmd)


# remote unpack program: the REAL filecache.extract_archive_atomic source
# (stdlib-only by construction), not a hand-maintained string twin — the
# twins drifted once already (the BadZipFile temp-dir leak was fixed in
# the function but originally shipped in both copies)
_REMOTE_UNZIP = (
    "import os, shutil, sys, tempfile, zipfile\n"
    + inspect.getsource(extract_archive_atomic)
    + "extract_archive_atomic(sys.argv[1], sys.argv[2])\n")


def _unpack_prelude(archives: List[str]) -> str:
    """Remote shell prelude unpacking shipped archives with a stdlib-only
    python one-liner (no framework install needed on the remote side);
    dest naming matches the launcher's src#dest rule."""
    from dmlc_core_tpu.tracker.filecache import remote_python, split_spec_item

    steps = []
    for item in archives:
        src, dest = split_spec_item(item, archive=True)
        # the zip was shipped under its basename into the workdir
        steps.append(f"{remote_python()} -c {_shquote(_REMOTE_UNZIP)} "
                     f"{_shquote(os.path.basename(src))} {_shquote(dest)}")
    return "; ".join(steps)


def _ssh_command(host: str, port: int, env: Dict[str, str], workdir: str,
                 cmd: List[str], prelude: str = "") -> List[str]:
    exports = "; ".join(f"export {k}={_shquote(v)}" for k, v in env.items())
    steps = [exports, f"cd {_shquote(workdir)}"]
    if prelude:
        steps.append(prelude)
    steps.append(f"exec {' '.join(map(_shquote, cmd))}")
    return ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(port), host,
            "; ".join(steps)]


def _shquote(s: str) -> str:
    import shlex

    return shlex.quote(str(s))


def submit(opts) -> None:
    assert opts.host_file, "--host-file is required for the ssh backend"
    hosts = parse_host_file(opts.host_file, opts.ssh_port)

    # file shipping: cached files + archives ride next to the rsync; the
    # command is rewritten to ./basename only when shipping is active
    from dmlc_core_tpu.tracker.filecache import prepare_scp_shipping

    _, command, shipped, archives = prepare_scp_shipping(opts)
    prelude = _unpack_prelude(archives)

    def fun_submit(envs: Dict[str, str]) -> None:
        workdir = opts.sync_dst_dir or os.getcwd()
        if opts.sync_dst_dir:
            for host, port in set(hosts):
                sync_dir(os.getcwd(), host, port, opts.sync_dst_dir)
        for host, port in set(hosts):
            ship_files(shipped, host, port, workdir)
        tasks = []
        for i in range(opts.num_workers + opts.num_servers):
            role = "server" if i < opts.num_servers else "worker"
            taskid = i if role == "server" else i - opts.num_servers
            host, port = hosts[i % len(hosts)]
            env = dict(envs)
            env["DMLC_ROLE"] = role
            env["DMLC_TASK_ID"] = str(taskid)
            for key in FORWARD_ENV:
                if key in os.environ:
                    env.setdefault(key, os.environ[key])
            cmd = _ssh_command(host, port, env, workdir, command,
                               prelude=prelude)
            tasks.append((f"ssh task {role}:{taskid}",
                          partial(subprocess.check_call, cmd)))
        run_ferried(tasks)

    submit_job(opts, fun_submit, wait=False)
