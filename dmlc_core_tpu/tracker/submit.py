"""dmlc-submit: start tracker + coordinator, dispatch to a cluster backend.

Reference: tracker/dmlc_tracker/submit.py:37-53 (dispatch) and
tracker.py:410-433 (``submit()``: tracker startup + env assembly).

Env contract handed to every worker (SURVEY.md §5.6):
- ``DMLC_TRACKER_URI`` / ``DMLC_TRACKER_PORT``   — Rabit rendezvous (for
  wire-compatible Rabit clients);
- ``DMLC_NUM_WORKER`` / ``DMLC_NUM_SERVER``      — world shape;
- ``DMLC_COORDINATOR_URI`` / ``DMLC_COORDINATOR_PORT`` — jax.distributed
  coordinator (rank 0 hosts it; dmlc_core_tpu.collective.init consumes it);
- per-task: ``DMLC_TASK_ID``, ``DMLC_ROLE``, ``DMLC_NUM_ATTEMPT``.
"""

from __future__ import annotations

import logging
import socket
import sys
import threading
from typing import Callable, Dict, List, Sequence, Tuple

from dmlc_core_tpu.tracker.rendezvous import PSTracker, RabitTracker, bind_free_port

__all__ = ["submit_job", "run_ferried", "main"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def run_ferried(tasks: Sequence[Tuple[str, Callable[[], None]]]) -> None:
    """Run ``(label, thunk)`` tasks on daemon threads, join them all, and
    re-raise the first failure.

    The one ferrying stanza shared by the ssh/mpi/tpu-vm backends: a thread
    target that raises dies silently in ``Thread.run`` and ``join()``
    reports success over a dead task (the dmlclint lockset-thread-leak
    rule), so every task's exception is logged under its label and the
    first one propagates to the caller after all tasks finish."""
    errors: List[BaseException] = []

    def run(label: str, thunk: Callable[[], None]) -> None:
        try:
            thunk()
        except BaseException as exc:  # noqa: BLE001 - ferried to caller
            logger.error("%s failed: %s", label, exc)
            errors.append(exc)

    threads = [threading.Thread(target=run, args=(label, thunk), daemon=True)
               for label, thunk in tasks]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]


def _default_host_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    except OSError:
        return "127.0.0.1"
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        # the old shape leaked the probe socket here: connect() failing
        # (no route) jumped past s.close() straight to the handler
        return "127.0.0.1"
    finally:
        s.close()


def submit_job(opts, fun_submit: Callable[[Dict[str, str]], None],
               wait: bool = True) -> RabitTracker:
    """Start the tracker, build worker envs, and hand off to the backend's
    ``fun_submit(envs)`` (reference tracker.py:410-433)."""
    host_ip = opts.host_ip or _default_host_ip()
    tracker = RabitTracker(host_ip, opts.num_workers)
    tracker.start(opts.num_workers)

    envs = {
        "DMLC_NUM_WORKER": str(opts.num_workers),
        "DMLC_NUM_SERVER": str(opts.num_servers),
        "DMLC_JOB_CLUSTER": opts.cluster,
    }
    envs.update(tracker.worker_envs())
    # allocate a coordinator port for jax.distributed (rank 0 binds it)
    coord_sock, coord_port = bind_free_port(host_ip, 12321, 12999)
    coord_sock.close()
    envs["DMLC_COORDINATOR_URI"] = host_ip
    envs["DMLC_COORDINATOR_PORT"] = str(coord_port)
    if opts.num_servers > 0:
        ps = PSTracker(host_ip, cmd=None)
        envs.update(ps.worker_envs())
    for kv in getattr(opts, "env", []):
        key, _, value = kv.partition("=")
        envs[key] = value

    fun_submit(envs)
    if wait:
        tracker.join()
    return tracker


def main(argv=None) -> int:
    from dmlc_core_tpu.tracker.opts import get_opts

    opts = get_opts(argv)
    logging.basicConfig(
        level=getattr(logging, opts.log_level),
        filename=opts.log_file,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    if not opts.command:
        print("error: no worker command given", file=sys.stderr)
        return 2
    if opts.cluster == "local":
        from dmlc_core_tpu.tracker import local as backend
    elif opts.cluster == "ssh":
        from dmlc_core_tpu.tracker import ssh as backend
    elif opts.cluster == "mpi":
        from dmlc_core_tpu.tracker import mpi as backend
    elif opts.cluster == "sge":
        from dmlc_core_tpu.tracker import sge as backend
    elif opts.cluster == "tpu-vm":
        from dmlc_core_tpu.tracker import tpu_vm as backend
    elif opts.cluster == "yarn":
        from dmlc_core_tpu.tracker import yarn as backend
    elif opts.cluster == "mesos":
        from dmlc_core_tpu.tracker import mesos as backend
    else:
        print(f"error: unknown cluster backend {opts.cluster!r}",
              file=sys.stderr)
        return 2
    backend.submit(opts)
    return 0


if __name__ == "__main__":
    sys.exit(main())
