"""dmlc-submit option schema (reference tracker/dmlc_tracker/opts.py:60-157)."""

from __future__ import annotations

import argparse
import os

__all__ = ["get_opts", "parse_memory_mb"]

CLUSTERS = ["local", "ssh", "mpi", "sge", "tpu-vm", "yarn", "mesos"]


def parse_memory_mb(text: str) -> int:
    """'4g'/'512m'/'1024' -> MB (reference opts.py:39-57)."""
    text = str(text).strip().lower()
    if text.endswith("g"):
        return int(float(text[:-1]) * 1024)
    if text.endswith("m"):
        return int(float(text[:-1]))
    return int(text)


def get_opts(args=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="dmlc-submit",
        description="Submit a distributed dmlc_core_tpu job to a cluster.")
    parser.add_argument("--cluster", default=os.environ.get(
        "DMLC_SUBMIT_CLUSTER", "local"), choices=CLUSTERS,
        help="cluster backend (env default: DMLC_SUBMIT_CLUSTER)")
    parser.add_argument("--num-workers", type=int, required=True,
                        help="number of worker processes")
    parser.add_argument("--num-servers", type=int, default=0,
                        help="number of parameter-server processes")
    parser.add_argument("--worker-cores", type=int, default=1)
    parser.add_argument("--worker-memory", default="1g",
                        help="per-worker memory, e.g. 1g, 512m")
    parser.add_argument("--server-cores", type=int, default=1)
    parser.add_argument("--server-memory", default="1g")
    parser.add_argument("--jobname", default="dmlc-job")
    parser.add_argument("--queue", default="default")
    parser.add_argument("--log-level", default="INFO",
                        choices=["DEBUG", "INFO", "WARNING", "ERROR"])
    parser.add_argument("--log-file", default=None)
    parser.add_argument("--host-file", default=None,
                        help="(ssh/mpi/tpu-vm) newline-separated worker hosts, "
                             "optionally host:port")
    parser.add_argument("--ssh-port", type=int, default=22)
    parser.add_argument("--sync-dst-dir", default=None,
                        help="(ssh/tpu-vm) rsync the working dir to this remote path")
    parser.add_argument("--host-ip", default=None,
                        help="tracker bind IP (default: auto-detect)")
    parser.add_argument("--env", action="append", default=[],
                        help="extra KEY=VALUE env to forward (repeatable)")
    parser.add_argument("--mesos-master", default=None,
                        help="(mesos) master host[:port]; default $MESOS_MASTER")
    parser.add_argument("--num-attempt", type=int,
                        default=int(os.environ.get("DMLC_NUM_ATTEMPT", "1")),
                        help="per-worker retry attempts (local backend)")
    parser.add_argument("--files", action="append", default=[],
                        help="file (src or src#dest) copied to the task "
                             "execution dir; repeatable (reference "
                             "opts.py:108-113)")
    parser.add_argument("--archives", action="append", default=[],
                        help="zip archive (src or src#dest) unpacked in the "
                             "task execution dir; repeatable — ship python "
                             "libs this way (reference opts.py:114-120)")
    parser.add_argument("--auto-file-cache",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="cache command-line tokens that name existing "
                             "files and rewrite them to ./basename "
                             "(reference opts.py:6-36); applies when the "
                             "backend stages a job dir (--files/--archives "
                             "given, or yarn/mesos)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="worker command to run")
    opts = parser.parse_args(args)
    if opts.command and opts.command[0] == "--":
        opts.command = opts.command[1:]
    opts.worker_memory_mb = parse_memory_mb(opts.worker_memory)
    opts.server_memory_mb = parse_memory_mb(opts.server_memory)
    return opts
