"""YARN backend: submit via the ResourceManager REST API.

The reference ships a 1k-LoC Java Client/ApplicationMaster pair
(tracker/yarn/, reference yarn.py:16-129) that requests containers, retries
failed tasks up to 3 attempts, and blacklists bad nodes.  The rebuild talks
to the RM's REST API (``/ws/v1/cluster/apps``) directly — no Java build — and
launches each task with the standard env contract through
``dmlc_core_tpu.tracker.launcher``; per-task retry is delegated to YARN's
``maxAppAttempts`` (the AM-level retry of the reference) plus
``DMLC_NUM_ATTEMPT`` inside the container.

Config: ``YARN_RM_URI`` (e.g. http://rm-host:8088) or --env YARN_RM_URI=...;
resources from --worker-cores/--worker-memory (the reference's
DMLC_WORKER_CORES/MEMORY_MB contract, yarn.py:89-96).
"""

from __future__ import annotations

import json
import logging
import os
import urllib.request
from typing import Dict

from dmlc_core_tpu.tracker.submit import submit_job
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["submit"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def _rest(rm_uri: str, path: str, payload: Dict = None, method: str = "GET"):
    url = rm_uri.rstrip("/") + path
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = resp.read()
        return resp.status, json.loads(body) if body else {}


def _launch_command(opts, envs: Dict[str, str], role: str) -> str:
    exports = " && ".join(
        f"export {k}='{v}'" for k, v in {**envs, "DMLC_ROLE": role,
                                         "DMLC_TASK_ID": "$CONTAINER_ID_IDX",
                                         "DMLC_JOB_CLUSTER": "yarn"}.items())
    cmd = " ".join(opts.command)
    return (f"{exports} && python -m dmlc_core_tpu.tracker.launcher {cmd} "
            f"1><LOG_DIR>/stdout 2><LOG_DIR>/stderr")


def submit(opts) -> None:
    rm_uri = os.environ.get("YARN_RM_URI", "")
    for kv in getattr(opts, "env", []):
        if kv.startswith("YARN_RM_URI="):
            rm_uri = kv.split("=", 1)[1]
    CHECK(rm_uri, "yarn backend needs YARN_RM_URI (ResourceManager REST "
                  "endpoint, e.g. http://rm:8088)")

    def fun_submit(envs: Dict[str, str]) -> None:
        status, new_app = _rest(rm_uri, "/ws/v1/cluster/apps/new-application",
                                payload={}, method="POST")
        CHECK(status in (200, 201), f"new-application failed: {status}")
        app_id = new_app["application-id"]
        payload = {
            "application-id": app_id,
            "application-name": opts.jobname,
            "application-type": "DMLC",
            "queue": opts.queue,
            "max-app-attempts": 3,  # reference ApplicationMaster.java:74
            "am-container-spec": {
                "commands": {"command": _launch_command(opts, envs, "worker")},
                "environment": {"entry": [
                    {"key": k, "value": str(v)} for k, v in envs.items()]},
            },
            "resource": {
                "memory": opts.worker_memory_mb,
                "vCores": opts.worker_cores,
            },
        }
        status, _ = _rest(rm_uri, "/ws/v1/cluster/apps", payload=payload,
                          method="POST")
        CHECK(status in (200, 202), f"application submit failed: {status}")
        logger.info("submitted %s to YARN as %s (%d workers, %d servers)",
                    opts.jobname, app_id, opts.num_workers, opts.num_servers)

    submit_job(opts, fun_submit, wait=True)
