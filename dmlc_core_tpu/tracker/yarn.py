"""YARN backend: per-task apps via the ResourceManager REST API, supervised.

The reference ships a 1k-LoC Java Client/ApplicationMaster pair
(tracker/yarn/, reference yarn.py:16-129) whose AM requests one container per
task, retries each task up to ``DMLC_MAX_ATTEMPT`` times, and blacklists
nodes that fail a container (ApplicationMaster.java:74,112,535-566).  The
rebuild keeps that *supervision capability* without the Java build:

- each task (worker/server) is submitted as its own YARN application whose
  AM container runs the task command through
  ``dmlc_core_tpu.tracker.launcher`` — the REST API's unit of placement and
  monitoring is the application attempt, so "task container" maps to "the
  app's AM container";
- :class:`~.yarn_supervisor.ContainerSupervisor` (the extracted AM state
  machine) drives retry + blacklist decisions; this module is only the REST
  adapter: submit app = request container, app RUNNING on node N = container
  allocated on N, app FAILED = container failed, kill+resubmit = the
  dummy-task burn for placements on blacklisted nodes.

Config: ``YARN_RM_URI`` (e.g. http://rm-host:8088) or --env YARN_RM_URI=...;
resources from --worker-cores/--worker-memory (the reference's
DMLC_WORKER_CORES/MEMORY_MB contract, yarn.py:89-96).
"""

from __future__ import annotations

import json
import logging
import os
import time
import urllib.error
import urllib.request
from typing import Dict, List

from dmlc_core_tpu.tracker.submit import submit_job
from dmlc_core_tpu.tracker.yarn_supervisor import (EXIT_KILLED_PMEM,
                                                   EXIT_KILLED_VMEM,
                                                   ClusterBackend, Container,
                                                   ContainerSupervisor,
                                                   JobAbort, TaskRecord)
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["submit", "RestYarnCluster"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def _rest(rm_uri: str, path: str, payload: Dict = None, method: str = "GET"):
    url = rm_uri.rstrip("/") + path
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = resp.read()
        return resp.status, json.loads(body) if body else {}


def _exit_status_from_diag(diagnostics: str) -> int:
    """Map YARN diagnostics text to the AM's special-cased exit statuses.

    The REST app report carries no container exit code, but the NM's
    memory-kill diagnostics are stable strings ("... is running beyond
    physical/virtual memory limits ..."); the reference AM aborts the whole
    job on those (ApplicationMaster.java:585-600) instead of retrying a task
    that will just be killed again.
    """
    d = diagnostics.lower()
    if "beyond physical memory" in d:
        return EXIT_KILLED_PMEM
    if "beyond virtual memory" in d:
        return EXIT_KILLED_VMEM
    return -1


def _launch_command(opts, envs: Dict[str, str], task: TaskRecord) -> str:
    exports = " && ".join(
        f"export {k}='{v}'" for k, v in {**envs, "DMLC_ROLE": task.role,
                                         "DMLC_TASK_ID": str(task.task_id),
                                         "DMLC_NUM_ATTEMPT":
                                             str(task.attempts),
                                         "DMLC_JOB_CLUSTER": "yarn"}.items())
    from dmlc_core_tpu.tracker.filecache import remote_python

    cmd = " ".join(opts.command)
    return (f"{exports} && {remote_python()} -m dmlc_core_tpu.tracker.launcher "
            f"{cmd} 1><LOG_DIR>/stdout 2><LOG_DIR>/stderr")


class RestYarnCluster(ClusterBackend):
    """ClusterBackend over the RM REST API: one application per task."""

    def __init__(self, rm_uri: str, opts, envs: Dict[str, str]):
        self.rm_uri = rm_uri
        self.opts = opts
        self.envs = envs
        self.app_task: Dict[str, TaskRecord] = {}   # app_id -> task
        self.reported: Dict[str, str] = {}          # app_id -> node reported
        self.live: List[str] = []                   # app ids worth polling
        self.poll_errors: Dict[str, int] = {}       # app_id -> consecutive
        self.submit_backlog: List[TaskRecord] = []  # deferred (RM was down)

    # -- ClusterBackend ------------------------------------------------------
    def request_containers(self, tasks: List[TaskRecord]) -> None:
        for task in tasks:
            self._try_submit_app(task)

    def launch(self, container: Container, task: TaskRecord) -> None:
        # the app's AM container already runs the task command; allocation
        # and launch coincide in the REST model
        pass

    def burn(self, container: Container) -> None:
        # a placement on a blacklisted node cannot be re-targeted over REST:
        # kill the app and submit a replacement (the reference burns the
        # container with a dummy task instead, ApplicationMaster.java:486)
        task = self.app_task.get(container.container_id)
        self._kill_app(container.container_id)
        if task is not None:
            self._try_submit_app(task)

    def release(self, container: Container) -> None:
        self._kill_app(container.container_id)

    def stop(self, container: Container) -> None:
        self._kill_app(container.container_id)

    def cancel_requests(self, tasks: List[TaskRecord]) -> None:
        # every pending task is backed by a live application; kill them so an
        # aborted job does not leak cluster resources
        ids = {t.task_id for t in tasks}
        for app_id, task in list(self.app_task.items()):
            if task.task_id in ids and app_id in self.live:
                self._kill_app(app_id)

    # -- REST plumbing -------------------------------------------------------
    def _try_submit_app(self, task: TaskRecord) -> None:
        """Submit, deferring to the next poll sweep when the RM is down.

        A (re)submission raced against an RM outage must not crash the
        supervision loop — the task stays pending in the supervisor, and the
        backlog retries once per sweep until the RM answers.
        """
        try:
            self._submit_app(task)
        except (OSError, ValueError) as exc:
            # URLError/HTTPError are OSError subclasses; ValueError covers a
            # proxy/LB answering 200 with a non-JSON body mid-outage
            logger.warning("submit of task %d failed (%s); will retry",
                           task.task_id, exc)
            self.submit_backlog.append(task)

    def _submit_app(self, task: TaskRecord) -> None:
        status, new_app = _rest(self.rm_uri,
                                "/ws/v1/cluster/apps/new-application",
                                payload={}, method="POST")
        CHECK(status in (200, 201), f"new-application failed: {status}")
        app_id = new_app["application-id"]
        mem = (self.opts.server_memory_mb if task.role == "server"
               else self.opts.worker_memory_mb)
        cores = (self.opts.server_cores if task.role == "server"
                 else self.opts.worker_cores)
        payload = {
            "application-id": app_id,
            "application-name":
                f"{self.opts.jobname}[{task.task_id}]:{task.role}",
            "application-type": "DMLC",
            "queue": self.opts.queue,
            # per-task retry belongs to the supervisor; the RM must not also
            # retry behind its back
            "max-app-attempts": 1,
            "am-container-spec": {
                "commands": {"command":
                             _launch_command(self.opts, self.envs, task)},
                "environment": {"entry": [
                    {"key": k, "value": str(v)}
                    for k, v in self.envs.items()]},
            },
            "resource": {"memory": mem, "vCores": cores},
        }
        status, _ = _rest(self.rm_uri, "/ws/v1/cluster/apps", payload=payload,
                          method="POST")
        CHECK(status in (200, 202), f"application submit failed: {status}")
        self.app_task[app_id] = task
        self.live.append(app_id)
        logger.info("submitted task %d (%s) as %s", task.task_id, task.role,
                    app_id)

    def _kill_app(self, app_id: str) -> None:
        try:
            _rest(self.rm_uri, f"/ws/v1/cluster/apps/{app_id}/state",
                  payload={"state": "KILLED"}, method="PUT")
        except OSError as exc:      # already gone is fine
            logger.warning("kill %s failed: %s", app_id, exc)
        if app_id in self.live:
            self.live.remove(app_id)
        self.reported.pop(app_id, None)

    # -- polling -> supervisor events ---------------------------------------
    # consecutive poll errors before an app is declared lost (RM restarted
    # and forgot it, network partition to the RM, ...)
    MAX_POLL_ERRORS = 5

    def poll(self, sup: ContainerSupervisor) -> None:
        """One monitoring sweep: translate app states to supervisor events."""
        backlog, self.submit_backlog = self.submit_backlog, []
        for task in backlog:
            self._try_submit_app(task)
        for app_id in list(self.live):
            try:
                _, body = _rest(self.rm_uri, f"/ws/v1/cluster/apps/{app_id}")
            except (urllib.error.URLError, OSError, ValueError) as exc:
                # transient RM errors must not crash a long-lived supervision
                # loop; persistent ones mean the container is lost
                n = self.poll_errors.get(app_id, 0) + 1
                self.poll_errors[app_id] = n
                logger.warning("poll %s failed (%d/%d): %s", app_id, n,
                               self.MAX_POLL_ERRORS, exc)
                if n >= self.MAX_POLL_ERRORS:
                    self.live.remove(app_id)
                    msg = f"unpollable: {exc}"
                    if app_id in self.reported:
                        sup.on_container_error(app_id, msg)
                    else:
                        self.reported[app_id] = ""
                        sup.on_unreported_completion(
                            self._container(app_id, ""), -1, msg)
                continue
            self.poll_errors.pop(app_id, None)
            app = body.get("app", body)
            state = app.get("state", "")
            node = (app.get("amHostHttpAddress") or "").split(":")[0]
            terminal = state in ("FINISHED", "FAILED", "KILLED")
            if app_id not in self.reported and node and not terminal:
                # first placement report = the allocation event; the
                # supervisor may respond by burning (blacklisted node)
                self.reported[app_id] = node
                sup.on_containers_allocated(
                    [self._container(app_id, node)])
                continue
            if terminal:
                self.live.remove(app_id)
                final = app.get("finalStatus", "")
                ok = state == "FINISHED" and final == "SUCCEEDED"
                diag = app.get("diagnostics", "")
                status = 0 if ok else _exit_status_from_diag(diag)
                if app_id in self.reported:
                    sup.on_container_completed(app_id, status,
                                               diagnostics=diag)
                else:
                    # died (or finished) before ever reporting a node: no
                    # allocation happened, so route around the blacklist/burn
                    # logic and complete the task directly
                    self.reported[app_id] = node
                    sup.on_unreported_completion(
                        self._container(app_id, node), status, diag)

    def _container(self, app_id: str, node: str) -> Container:
        task = self.app_task[app_id]
        return Container(app_id, node, task_id=task.task_id)


def supervise(cluster: RestYarnCluster, num_workers: int, num_servers: int,
              poll_interval: float = 2.0, max_polls: int = 0) -> ContainerSupervisor:
    """Run the AM-equivalent supervision loop until the job finishes.

    Raises :class:`JobAbort` when a task exhausts its attempts or dies of a
    memory kill (the reference AM's unregister-with-FAILED path).
    """
    sup = ContainerSupervisor(cluster, num_workers, num_servers)
    sup.start()
    polls = 0
    while not sup.done:
        cluster.poll(sup)
        polls += 1
        if max_polls and polls >= max_polls:
            break
        if not sup.done:
            time.sleep(poll_interval)
    return sup


def submit(opts) -> None:
    rm_uri = os.environ.get("YARN_RM_URI", "")
    for kv in getattr(opts, "env", []):
        if kv.startswith("YARN_RM_URI="):
            rm_uri = kv.split("=", 1)[1]
    CHECK(rm_uri, "yarn backend needs YARN_RM_URI (ResourceManager REST "
                  "endpoint, e.g. http://rm:8088)")

    # file shipping: every task command already routes through the
    # container-side launcher, which materializes DMLC_JOB_FILES and
    # unpacks DMLC_JOB_ARCHIVES into the task cwd (sources must be
    # container-visible, e.g. shared FS — the REST adapter has no
    # HDFS-localized-resource path).  always=True: like the reference's
    # YARN backend, auto-file-cache applies without explicit --files.
    from dmlc_core_tpu.tracker.filecache import prepare_shipping

    ship_env, opts.command, _, _ = prepare_shipping(opts, always=True)

    def fun_submit(envs: Dict[str, str]) -> None:
        cluster = RestYarnCluster(rm_uri, opts, {**envs, **ship_env})
        try:
            sup = supervise(cluster, opts.num_workers, opts.num_servers)
            logger.info("yarn job %s finished: %d tasks ok", opts.jobname,
                        len(sup.finished))
        except JobAbort as exc:
            logger.error("yarn job %s aborted: %s", opts.jobname, exc)
            raise

    submit_job(opts, fun_submit, wait=True)
