"""Mesos backend: per-task launch on an Apache Mesos cluster.

Reference: tracker/dmlc_tracker/mesos.py — one Mesos task per worker/server
with ``cpus``/``mem`` resources, launched either through pymesos (when
importable) or by shelling out to ``mesos-execute`` against
``MESOS_MASTER``.  Env forwarded per task: the tracker contract plus
``DMLC_TASK_ID``/``DMLC_ROLE``, ``DMLC_SERVER_ID``/``DMLC_WORKER_ID`` and a
small passthrough whitelist (OMP_NUM_THREADS, KMP_AFFINITY,
LD_LIBRARY_PATH).
"""

from __future__ import annotations

import json
import logging
import os
import shlex
import subprocess
import threading
import uuid
from typing import Dict, List

from dmlc_core_tpu.tracker.submit import submit_job

__all__ = ["submit"]

logger = logging.getLogger("dmlc_core_tpu.tracker")

# env vars forwarded from the submitting shell into every task
_FORWARD_ENV = ("OMP_NUM_THREADS", "KMP_AFFINITY", "LD_LIBRARY_PATH")


def _forwarded_env() -> Dict[str, str]:
    return {k: os.environ[k] for k in _FORWARD_ENV if k in os.environ}


def _resolve_master(opts) -> str:
    master = getattr(opts, "mesos_master", None) or os.environ.get("MESOS_MASTER")
    if not master:
        raise RuntimeError(
            "no Mesos master configured: set MESOS_MASTER or --mesos-master")
    if ":" not in master:
        master += ":5050"
    return master


def _try_pymesos_run(master: str, prog: str, env: Dict[str, str],
                     resources: Dict[str, float]) -> bool:
    """Run through pymesos when available; returns False to fall back."""
    try:
        import pymesos.subprocess  # type: ignore
    except ImportError:
        return False
    logging.getLogger("pymesos").setLevel(logging.WARNING)
    # pymesos reads the master from the env; hand it the resolved address so
    # --mesos-master and the :5050 default take effect on this path too
    os.environ["MESOS_MASTER"] = master
    pymesos.subprocess.check_call(
        prog, shell=True, env=env, cwd=os.getcwd(),
        cpus=resources["cpus"], mem=resources["mem"])
    return True


def _mesos_execute_argv(master: str, prog: str, env: Dict[str, str],
                        resources: Dict[str, float]) -> List[str]:
    """Build the ``mesos-execute`` command line for one task."""
    res = ";".join(f"{k}:{v}" for k, v in sorted(resources.items()))
    return [
        "mesos-execute",
        f"--master={master}",
        f"--name=dmlc-{uuid.uuid4()}",
        f"--command=cd {shlex.quote(os.getcwd())} && {prog}",
        f"--env={json.dumps(env)}",
        f"--resources={res}",
    ]


def _run_task(master: str, prog: str, env: Dict[str, str],
              resources: Dict[str, float]) -> None:
    if _try_pymesos_run(master, prog, env, resources):
        return
    argv = _mesos_execute_argv(master, prog, env, resources)
    proc = subprocess.run(argv, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    if proc.returncode != 0:
        logger.error("mesos-execute failed (exit %d) for task %s:\n%s",
                     proc.returncode, env.get("DMLC_TASK_ID", "?"),
                     proc.stdout)
        raise RuntimeError(
            f"mesos-execute exited {proc.returncode} for task "
            f"{env.get('DMLC_TASK_ID', '?')}")


def submit(opts) -> None:
    master = _resolve_master(opts)

    # file shipping: wrap the task in the launcher, which materializes
    # DMLC_JOB_FILES / unpacks DMLC_JOB_ARCHIVES into the task cwd
    # (sources must be agent-visible, e.g. shared FS).  always=True:
    # containers get a fresh sandbox, so auto-file-cache applies without
    # explicit --files, like the reference's YARN semantics.
    from dmlc_core_tpu.tracker.filecache import prepare_shipping

    ship_env, command, _, _ = prepare_shipping(opts, wrap_launcher=True,
                                               always=True)

    def fun_submit(envs: Dict[str, str]) -> None:
        envs = {**envs, **ship_env}
        prog = " ".join(command)
        threads = []
        errors: List[BaseException] = []

        def run(env: Dict[str, str], resources: Dict[str, float]) -> None:
            try:
                _run_task(master, prog, env, resources)
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        for i in range(opts.num_servers + opts.num_workers):
            env = dict(envs)
            # task ids are role-relative (workers 0..nw-1): DMLC_TASK_ID is
            # the collective's process id, same split as ssh.py/sge.py
            if i < opts.num_servers:
                env["DMLC_ROLE"] = "server"
                env["DMLC_TASK_ID"] = str(i)
                env["DMLC_SERVER_ID"] = str(i)
                resources = {"cpus": float(opts.server_cores),
                             "mem": float(opts.server_memory_mb)}
            else:
                env["DMLC_ROLE"] = "worker"
                env["DMLC_TASK_ID"] = str(i - opts.num_servers)
                env["DMLC_WORKER_ID"] = str(i - opts.num_servers)
                resources = {"cpus": float(opts.worker_cores),
                             "mem": float(opts.worker_memory_mb)}
            for k, v in _forwarded_env().items():
                env.setdefault(k, v)
            env = {str(k): str(v) for k, v in env.items()}
            t = threading.Thread(target=run, args=(env, resources),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    submit_job(opts, fun_submit, wait=False)
