"""Job file cache: ship files/archives named at submit time to the
execution environment.

Reference semantics (tracker/dmlc_tracker/opts.py:6-36 auto cache-file set;
opts.py:108-126 the ``--files``/``--archives`` options; consumed via
``DMLC_JOB_ARCHIVES``, yarn.py:96): with auto-file-cache on, every command
token that names an existing file is shipped to the executor and the token
rewritten to ``./basename``; ``--files`` adds explicit extras; ``--archives``
lists zip files unpacked in the execution dir.  The reference wires this
only for YARN; here one module serves every backend:

- **local** stages into a per-job temp dir and runs workers there;
- **ssh** copies the staged set into the remote workdir next to the rsync;
- **yarn / mesos / sge** export the ``DMLC_JOB_FILES`` /
  ``DMLC_JOB_ARCHIVES`` env contract (``:``-separated ``src#dest`` items)
  and the container-side launcher materializes them into the task cwd.

Entries use the reference's ``src#dest`` spelling throughout — the ``dest``
rename survives into staging/shipping.  ``dest`` defaults to the source
basename (for archives: basename without the zip extension).
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import zipfile
from typing import Dict, List, Tuple

__all__ = ["collect_job_files", "stage_job_dir", "files_env",
           "prepare_shipping", "prepare_scp_shipping", "wrap_launcher_cmd",
           "split_spec_item", "extract_archive_atomic"]

logger = logging.getLogger("dmlc_core_tpu.tracker")


def split_spec_item(item: str, archive: bool = False) -> Tuple[str, str]:
    """``src#dest`` -> (src, dest); dest defaults to basename (archives:
    basename without the zip extension, the reference launcher rule)."""
    src, _, dest = item.partition("#")
    if not dest:
        base = os.path.basename(src)
        dest = os.path.splitext(base)[0] if archive else base
    return src, dest


def collect_job_files(opts) -> Tuple[List[str], List[str], List[str]]:
    """Resolve the job's file-cache set from the submit options.

    Returns ``(files, archives, command)``: both lists hold normalized
    ``src#dest`` specs (absolute sources, deduped by source, command order
    first), and the command has every auto-cached token rewritten to
    ``./basename`` — e.g. ``../../kmeans ../kmeans.conf`` becomes
    ``./kmeans ./kmeans.conf`` running in the staged dir.
    """
    files: List[str] = []
    seen = set()

    def _add(src: str, dest: str) -> bool:
        src = os.path.abspath(src)
        if not os.path.isfile(src):
            return False
        if src not in seen:
            seen.add(src)
            files.append(f"{src}#{dest}")
        return True

    command = []
    auto = getattr(opts, "auto_file_cache", True)
    for tok in getattr(opts, "command", []):
        if auto and os.path.isfile(tok):
            _add(tok, os.path.basename(tok))
            command.append("./" + os.path.basename(tok))
        else:
            command.append(tok)
    for item in getattr(opts, "files", []) or []:
        src, dest = split_spec_item(item)
        if not _add(src, dest):
            logger.warning("--files entry %r does not exist; skipped", item)
    archives = []
    for item in getattr(opts, "archives", []) or []:
        src, dest = split_spec_item(item, archive=True)
        src = os.path.abspath(src)
        if not os.path.isfile(src):
            logger.warning("--archives entry %r does not exist; skipped",
                           item)
            continue
        archives.append(f"{src}#{dest}")
    return files, archives, command


def extract_archive_atomic(src: str, dest: str) -> None:
    """Unpack ``src`` so ``dest`` only ever appears fully extracted:
    extract into a sibling temp dir, then rename into place.  Concurrent
    extractors (SGE array tasks in one qsub -cwd, several ssh workers per
    host) race safely — the rename loser discards its copy and uses the
    winner's, which is complete by rename-atomicity."""
    if os.path.exists(dest):
        return
    parent = os.path.dirname(os.path.abspath(dest)) or "."
    tmp = tempfile.mkdtemp(prefix=".dmlc-unpack-", dir=parent)
    try:
        with zipfile.ZipFile(src) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            # rename-race loser: the winner's fully-extracted copy serves
            if not os.path.exists(dest):
                raise
    finally:
        # after a successful rename tmp no longer exists and this no-ops;
        # on ANY failure (BadZipFile included, which the old except OSError
        # arm leaked) the temp dir is removed
        shutil.rmtree(tmp, ignore_errors=True)


def stage_job_dir(files: List[str], archives: List[str],
                  dest_dir: str) -> None:
    """Materialize the cache set into ``dest_dir`` (the local-backend
    execution dir): copy files under their dest names (permissions
    preserved, so shipped binaries stay executable) and unpack archives —
    ``dest_dir`` plays the role of the container sandbox, where the
    launcher would unpack."""
    os.makedirs(dest_dir, exist_ok=True)
    for item in files:
        src, dest = split_spec_item(item)
        shutil.copy2(src, os.path.join(dest_dir, dest))
    for item in archives:
        src, dest = split_spec_item(item, archive=True)
        extract_archive_atomic(src, os.path.join(dest_dir, dest))


def files_env(files: List[str], archives: List[str]) -> Dict[str, str]:
    """The env contract consumed by the container-side launcher:
    ``DMLC_JOB_FILES`` / ``DMLC_JOB_ARCHIVES`` as ``:``-separated
    ``src#dest`` lists (sources must be visible from the container — a
    shared filesystem or resources the cluster itself localizes)."""
    env: Dict[str, str] = {}
    if files:
        env["DMLC_JOB_FILES"] = ":".join(files)
    if archives:
        env["DMLC_JOB_ARCHIVES"] = ":".join(archives)
    return env


def prepare_shipping(opts, wrap_launcher: bool = False,
                     always: bool = False):
    """The one ship-prep stanza shared by every backend.

    Returns ``(ship_env, command, files, archives)``.  Shipping activates
    when ``--files``/``--archives`` were given, or — for backends whose
    execution dir is always a fresh container sandbox (``always=True``,
    yarn/mesos, matching the reference's always-on YARN auto-cache) — when
    auto-file-cache is enabled.  ``wrap_launcher`` prefixes the command
    with the container-side launcher for backends that don't already
    route through it.
    """
    explicit = bool(getattr(opts, "files", None)
                    or getattr(opts, "archives", None))
    auto = getattr(opts, "auto_file_cache", True)
    if not explicit and not (always and auto):
        return {}, list(getattr(opts, "command", [])), [], []
    files, archives, command = collect_job_files(opts)
    env = files_env(files, archives)
    if wrap_launcher and (files or archives):
        command = wrap_launcher_cmd(command)
    return env, command, files, archives


def remote_python() -> str:
    """Interpreter name to use in remote/container command lines.  Default
    ``python3``: a bare ``python`` does not exist on python3-only hosts
    (default Debian/Ubuntu and most cluster images).  Overridable for
    clusters whose interpreter lives elsewhere."""
    return os.environ.get("DMLC_REMOTE_PYTHON", "python3")


def wrap_launcher_cmd(command: List[str]) -> List[str]:
    """Route a task command through the container-side launcher (which
    materializes DMLC_JOB_FILES / unpacks DMLC_JOB_ARCHIVES)."""
    return [remote_python(), "-m", "dmlc_core_tpu.tracker.launcher"] \
        + list(command)


def prepare_scp_shipping(opts):
    """The ssh-style backends' ship-prep (ssh + tpu-vm host-file path):
    returns ``(ship_env, command, scp_specs, archives)`` where
    ``scp_specs`` is every file spec plus each archive zip under its
    basename (the form the remote unpack prelude expects)."""
    ship_env, command, files, archives = prepare_shipping(opts)
    scp_specs = list(files)
    for item in archives:
        src, _ = split_spec_item(item, archive=True)
        scp_specs.append(f"{src}#{os.path.basename(src)}")
    return ship_env, command, scp_specs, archives
