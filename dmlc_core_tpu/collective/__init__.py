"""Rabit-shaped collectives implemented as XLA collectives over ICI/DCN.

The reference provides *no* data-plane collectives in-repo — its tracker
brokers TCP links for the external Rabit allreduce library (SURVEY.md §5.8).
Here the data plane is ``jax.lax`` collectives compiled by XLA:

- :mod:`dmlc_core_tpu.collective.api` — the process-level, Rabit-shaped API
  (init/finalize/get_rank/get_world_size/allreduce/broadcast/tracker_print)
  that downstream launchers and scripts use;
- :mod:`dmlc_core_tpu.collective.mesh_collectives` — in-program, jit-compiled
  collectives over a named mesh axis (allreduce/allgather/reducescatter/
  broadcast/ppermute ring), for use inside shard_map'd training steps.
"""

from dmlc_core_tpu.collective.api import (  # noqa: F401
    init,
    finalize,
    is_initialized,
    get_rank,
    get_world_size,
    get_processor_name,
    allreduce,
    broadcast,
    allgather,
    tracker_print,
    version_number,
    checkpoint,
    load_checkpoint,
)
from dmlc_core_tpu.collective.mesh_collectives import (  # noqa: F401
    MeshCollective,
    ring_allreduce,
)
