"""In-program collectives over a named mesh axis (the ICI data plane).

These are the building blocks a training step uses *inside* jit/shard_map —
replacing Rabit's tree allreduce with XLA collectives that ride ICI within a
slice and DCN across slices (the design center of SURVEY.md §5.8).

:class:`MeshCollective` compiles allreduce/allgather/reducescatter/broadcast
for a given mesh axis once and reuses the executable (jit caching), plus a
benchmark helper reporting effective allreduce GB/s — the BASELINE.json
"Rabit→ICI allreduce GB/s" metric.

:func:`ring_allreduce` is an explicit ``lax.ppermute`` ring
(reduce-scatter + all-gather), provided both as a reference for custom
overlap patterns (the scaling-book recipe) and as a cross-check that XLA's
built-in ``psum`` beats a hand-rolled ring — it should, and bench.py verifies.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dmlc_core_tpu.utils.logging import CHECK
from dmlc_core_tpu.utils.timer import get_time

__all__ = ["MeshCollective", "ring_allreduce", "allreduce_bandwidth_gbps"]


class MeshCollective:
    """Compiled collectives over one axis of a Mesh."""

    def __init__(self, mesh, axis: str = "data"):
        CHECK(axis in mesh.axis_names, f"axis {axis!r} not in mesh {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        self.axis_size = mesh.shape[axis]
        # compiled-fn cache lives on the instance (NOT functools.lru_cache on
        # bound methods, which pins self/mesh in a global cache forever — a
        # leak in long-lived jobs that build many meshes)
        self._fn_cache: dict = {}

    def _cached(self, key, builder):
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = self._fn_cache[key] = builder()
        return fn

    def _shard_map(self, fn, in_spec, out_spec):
        import jax

        from dmlc_core_tpu.parallel.compat import get_shard_map

        shard_map = get_shard_map()
        return jax.jit(shard_map(
            fn, mesh=self.mesh, in_specs=in_spec, out_specs=out_spec))

    def _allreduce_fn(self, op: str):
        return self._cached(("allreduce", op),
                            lambda: self._build_allreduce(op))

    def _build_allreduce(self, op: str):
        import jax
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        reducers = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin}
        CHECK(op in reducers, f"unknown op {op!r}")
        red = reducers[op]
        axis = self.axis

        def kernel(x):
            return red(x, axis)

        # input sharded over the axis on dim 0, output likewise (allreduce of
        # per-shard partials -> every shard holds the same reduced value, so
        # the logical output is the reduction replicated along the axis)
        return self._shard_map(kernel, P(axis), P(axis))

    def allreduce(self, x, op: str = "sum"):
        """Reduce per-shard partials along the axis; every shard of the output
        holds the reduced value.  Input dim 0 must equal the axis size."""
        return self._allreduce_fn(op)(x)

    def _psum_scalar_fn(self):
        return self._cached("psum", self._build_psum)

    def _build_psum(self):
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        axis = self.axis

        def kernel(x):
            # caller contract: x.shape[0] == axis_size, so the local shard's
            # dim 0 is 1; drop it so the logical result is x.shape[1:]
            return lax.psum(x[0], axis)

        return self._shard_map(kernel, P(axis), P())

    def psum(self, x):
        """Sum shards along the axis, returning the unreplicated result
        (shape = x.shape[1:])."""
        import jax.numpy as jnp  # noqa: F401

        return self._psum_scalar_fn()(x)

    def _allgather_fn(self):
        return self._cached("allgather", self._build_allgather)

    def _build_allgather(self):
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        axis = self.axis

        def kernel(x):
            return lax.all_gather(x, axis, tiled=True)

        return self._shard_map(kernel, P(axis), P(axis))

    def allgather(self, x):
        """All-gather shards: output dim0 = axis_size * x.dim0 per shard."""
        return self._allgather_fn()(x)

    def _reduce_scatter_fn(self):
        return self._cached("reduce_scatter", self._build_reduce_scatter)

    def _build_reduce_scatter(self):
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        axis = self.axis

        def kernel(x):
            # caller contract: x is [axis_size, elems]; each shard contributes
            # its partial vector x[0] and receives its 1/axis_size slice of
            # the sum.
            return lax.psum_scatter(x[0], axis, scatter_dimension=0, tiled=True)

        return self._shard_map(kernel, P(axis), P(axis))

    def reduce_scatter(self, x):
        """Reduce [axis_size, elems] partials; shard i of the [elems] output
        holds slice i of the sum (elems must divide by axis_size)."""
        return self._reduce_scatter_fn()(x)

    def _broadcast_fn(self, root: int):
        return self._cached(("broadcast", root),
                            lambda: self._build_broadcast(root))

    def _build_broadcast(self, root: int):
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        size = self.axis_size

        def kernel(x):
            # select the root shard everywhere via a masked psum
            idx = lax.axis_index(axis)
            mask = (idx == root).astype(x.dtype)
            return lax.psum(x * mask, axis)

        return self._shard_map(kernel, P(axis), P(axis))

    def broadcast(self, x, root: int = 0):
        """Every output shard holds the root shard's value."""
        return self._broadcast_fn(root)(x)


# compiled ring kernels keyed by (mesh, axis): rebuilding the jit wrapper
# per ring_allreduce call emptied its compile cache every time, so every
# call paid a full retrace (jax.Mesh is hashable and meshes are few and
# long-lived, so a plain dict is the right cache)
_RING_FNS: dict = {}


def ring_allreduce(mesh, axis: str, x):
    """Explicit bidirectional-free ppermute ring allreduce
    (reduce-scatter phase + all-gather phase), shard_map'd over ``axis``.

    The per-shard input must be divisible into ``axis_size`` equal segments on
    dim 0."""
    fn = _RING_FNS.get((mesh, axis))
    if fn is None:
        fn = _RING_FNS[(mesh, axis)] = _build_ring_allreduce(mesh, axis)
    return fn(x)


def _build_ring_allreduce(mesh, axis: str):
    import jax
    import jax.lax as lax
    import jax.numpy as jnp  # noqa: F401
    from jax.sharding import PartitionSpec as P

    from dmlc_core_tpu.parallel.compat import get_shard_map

    shard_map = get_shard_map()
    n = mesh.shape[axis]
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]

    def kernel(x):
        segs = x.reshape((n, -1) + x.shape[1:])
        my = lax.axis_index(axis)

        # reduce-scatter: after n-1 steps, shard i holds the full sum of
        # segment (i+1) mod n
        def rs_step(k, acc_segs):
            send_idx = (my - k) % n
            chunk = acc_segs[send_idx]
            received = lax.ppermute(chunk, axis, perm_fwd)
            recv_idx = (my - k - 1) % n
            return acc_segs.at[recv_idx].add(received)

        segs = lax.fori_loop(0, n - 1, rs_step, segs)

        # all-gather: circulate each completed segment around the ring
        def ag_step(k, acc_segs):
            send_idx = (my - k + 1) % n
            chunk = acc_segs[send_idx]
            received = lax.ppermute(chunk, axis, perm_fwd)
            recv_idx = (my - k) % n
            return acc_segs.at[recv_idx].set(received)

        segs = lax.fori_loop(0, n - 1, ag_step, segs)
        return segs.reshape((-1,) + x.shape[1:])

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis)))


def allreduce_bandwidth_gbps(mesh, axis: str, nbytes: int = 64 << 20,
                             iters: int = 10, dtype=np.float32) -> float:
    """Measure effective allreduce bandwidth over the axis (the BASELINE.json
    'Rabit→ICI allreduce GB/s' metric): algbw = 2*(n-1)/n * bytes / time."""
    import jax
    import jax.numpy as jnp

    n = mesh.shape[axis]
    coll = MeshCollective(mesh, axis)
    elems_per_shard = max(1, nbytes // np.dtype(dtype).itemsize // max(n, 1))
    x = jnp.ones((n, elems_per_shard), dtype=dtype)
    fn = coll._psum_scalar_fn()
    jax.block_until_ready(fn(x))  # compile
    start = get_time()
    for _ in range(iters):
        out = fn(x)
    jax.block_until_ready(out)
    elapsed = (get_time() - start) / iters
    payload = elems_per_shard * np.dtype(dtype).itemsize * n
    algbw = 2 * (n - 1) / max(n, 1) * payload / max(elapsed, 1e-12)
    return algbw / 1e9
