"""Process-level Rabit-shaped collective API.

Mirrors the client contract the reference tracker serves (rabit's
init/finalize/get_rank/get_world_size/allreduce/broadcast/version_number/
checkpoint — the env-var protocol in SURVEY.md §5.6): each *process* is a
rank; arrays are host numpy arrays; reduction happens across processes.

Implementation: ``jax.distributed`` global runtime + one global 1-D mesh over
every device of every process.  An allreduce builds a global array whose
process-local shard is this rank's contribution, then runs a jit-compiled
cross-device reduction (XLA lowers it to ICI/DCN collectives); the result is
fetched fully-replicated.  Single-process runs degrade to local identity, so
the same script works from a laptop to a pod (the reference's local-vs-cluster
symmetry).

Env contract (set by dmlc_core_tpu.tracker launchers, reference tracker.py):
``DMLC_TASK_ID`` → process id (falling back to the launcher rank vars
``OMPI_COMM_WORLD_RANK``/``PMIX_RANK``/``PMI_RANK``/``SLURM_PROCID`` — the
mpi backend cannot bake per-rank ids into mpirun's shared environment),
``DMLC_NUM_WORKER`` → world size, ``DMLC_COORDINATOR_URI``/
``DMLC_COORDINATOR_PORT`` → jax.distributed coordinator address.
"""

from __future__ import annotations

import atexit
import os
import socket
import sys
from typing import Any, Optional

import numpy as np

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.param import get_env
from dmlc_core_tpu.telemetry import clock
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ, log_info

__all__ = [
    "init",
    "finalize",
    "is_initialized",
    "get_rank",
    "get_world_size",
    "get_processor_name",
    "allreduce",
    "broadcast",
    "allgather",
    "tracker_print",
    "version_number",
    "checkpoint",
    "load_checkpoint",
]

_state: dict = {
    "initialized": False,
    "distributed": False,
    "mesh": None,
    "version": 0,
    "fn_cache": {},
}

_OPS = {"sum": np.add, "max": np.maximum, "min": np.minimum, "prod": np.multiply}


def _task_id_from_env(env) -> int:
    """Process id for jax.distributed: DMLC_TASK_ID when the launcher set it
    (local/ssh/sge/yarn backends), else the MPI/SLURM launcher's rank var —
    mpirun assigns ranks itself, so the mpi backend cannot bake per-process
    task ids into the (shared) environment (reference rabit got its rank
    from tracker rendezvous instead; jax.distributed needs it up front)."""
    for key in ("DMLC_TASK_ID", "OMPI_COMM_WORLD_RANK", "PMIX_RANK",
                "PMI_RANK", "SLURM_PROCID"):
        value = env.get(key, "").strip()
        if value:
            try:
                return int(value)
            except ValueError:
                # stale/garbage launcher vars inherited by an unrelated run
                # must not break standalone init
                log_info(f"ignoring non-integer {key}={value!r}")
    return 0


def init(args: Optional[dict] = None) -> None:
    """Initialize the collective runtime (rabit::Init equivalent).

    In a tracker-launched job (DMLC_NUM_WORKER > 1 in the environment) this
    calls ``jax.distributed.initialize`` against the coordinator the launcher
    advertised; standalone it is a no-op beyond building the local mesh.
    """
    if _state["initialized"]:
        return
    import jax

    env = dict(os.environ)
    if args:
        env.update({k: str(v) for k, v in args.items()})
    num_worker = int(env.get("DMLC_NUM_WORKER", "1"))
    task_id = _task_id_from_env(env)
    coord_uri = env.get("DMLC_COORDINATOR_URI", "")
    coord_port = env.get("DMLC_COORDINATOR_PORT", "")
    if num_worker > 1 and coord_uri:
        jax.distributed.initialize(
            coordinator_address=f"{coord_uri}:{coord_port}",
            num_processes=num_worker,
            process_id=task_id,
        )
        _state["distributed"] = True
    from dmlc_core_tpu.parallel.mesh import make_mesh

    _state["mesh"] = make_mesh({"world": len(jax.devices())})
    _state["initialized"] = True
    atexit.register(finalize)


def finalize() -> None:
    """rabit::Finalize equivalent."""
    if not _state["initialized"]:
        return
    if _state["distributed"]:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    # version resets with the session: a re-init is a fresh job whose
    # restart recovery (load_checkpoint's version discovery) must not
    # inherit a dead session's counter
    _state.update(initialized=False, distributed=False, mesh=None,
                  fn_cache={}, version=0)


def is_initialized() -> bool:
    return _state["initialized"]


def _require_init() -> None:
    CHECK(_state["initialized"], "collective.init() must be called first")


def get_rank() -> int:
    _require_init()
    import jax

    return jax.process_index()


def get_world_size() -> int:
    _require_init()
    import jax

    return jax.process_count()


def get_processor_name() -> str:
    return socket.gethostname()


def _proc_slots(devices, nproc: int) -> np.ndarray:
    """One representative device slot per process rank, in rank order.

    ``devices`` is the mesh's world-axis device sequence.  Device enumeration
    is NOT guaranteed process-major (or process-uniform) on real multi-host
    topologies, so the slot of rank p is derived from each device's actual
    ``process_index`` — never from stride arithmetic.
    """
    slots = np.full(nproc, -1, dtype=np.int64)
    for i, d in enumerate(devices):
        p = d.process_index
        if 0 <= p < nproc and slots[p] < 0:
            slots[p] = i
    CHECK(bool((slots >= 0).all()),
          f"mesh devices cover only {int((slots >= 0).sum())}/{nproc} "
          "processes; every rank must own at least one device")
    return slots


def _global_op(value: np.ndarray, op: str, root: Optional[int] = None,
               gather: bool = False) -> np.ndarray:
    """Telemetry wrapper over :func:`_global_op_impl`: per-op latency
    histogram, payload-byte counter, and a trace span — the labels collapse
    root-moves to ``broadcast`` so the metric families stay small."""
    if not telemetry.enabled():
        return _global_op_impl(value, op, root, gather)
    opname = "gather" if gather else ("broadcast" if root is not None else op)
    value = np.asarray(value)
    nbytes = int(value.nbytes)
    start = clock.monotonic()
    with telemetry.span(f"collective.{opname}", payload_bytes=nbytes):
        out = _global_op_impl(value, op, root, gather)
    telemetry.observe("dmlc_collective_op_seconds", clock.elapsed(start),
                      op=opname)
    telemetry.count("dmlc_collective_ops_total", op=opname)
    telemetry.count("dmlc_collective_payload_bytes_total", nbytes, op=opname)
    return out


def _global_op_impl(value: np.ndarray, op: str, root: Optional[int] = None,
                    gather: bool = False) -> np.ndarray:
    """Shared engine: stack per-process contributions on a leading axis,
    reduce (or gather) on device, return replicated result."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    _require_init()
    value = np.asarray(value)
    nproc = jax.process_count()
    if root is not None:
        CHECK(0 <= root < nproc, f"root {root} out of range for {nproc} ranks")
    if nproc == 1:
        if gather:
            return value[None]
        return value
    mesh = _state["mesh"]
    devs = list(mesh.devices.reshape(-1))
    ndev = len(devs)
    # leading axis = device slots; each process replicates its value into
    # every slot it owns, so the global array's shard on any of process p's
    # devices holds value_p.  Local slot count comes from the actual device->
    # process mapping (processes need not own equal device counts).
    n_local = sum(1 for d in devs if d.process_index == jax.process_index())
    local = np.broadcast_to(value[None], (n_local,) + value.shape)
    sharding = NamedSharding(mesh, P("world"))
    garr = jax.make_array_from_process_local_data(sharding, local,
                                                  (ndev,) + value.shape)
    out_sharding = NamedSharding(mesh, P())
    slots = _proc_slots(devs, nproc)   # one slot per rank, rank order
    # compiled-dispatch cache: a fresh lambda per call would defeat jit's
    # function-identity cache and retrace two collectives per broadcast
    mode = ("gather" if gather else
            ("root", root) if root is not None else ("red", op))
    key = (mode, ndev, tuple(slots.tolist()), value.shape, str(value.dtype))
    fn = _state["fn_cache"].get(key)
    if fn is None:
        if gather:
            fn = jax.jit(lambda x: x[slots], out_shardings=out_sharding)
        elif root is not None:
            r = int(slots[root])
            fn = jax.jit(lambda x: x[r], out_shardings=out_sharding)
        else:
            reducers = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
                        "prod": jnp.prod}
            CHECK(op in reducers, f"unknown reduce op {op!r}")
            red = reducers[op]
            # reduce over exactly one slot per process (duplicates dropped
            # uniformly for every op)
            fn = jax.jit(lambda x: red(x[slots], axis=0),
                         out_shardings=out_sharding)
        _state["fn_cache"][key] = fn
    return np.asarray(fn(garr))


def allreduce(value: Any, op: str = "sum") -> np.ndarray:
    """Elementwise reduce across all ranks; result identical on every rank
    (rabit::Allreduce).  ``op`` in {sum, max, min, prod}."""
    return _global_op(np.asarray(value), op)


# dtype codes for the broadcast shape/dtype header (fixed order — part of the
# cross-rank wire contract; append only).  The payload itself travels as raw
# uint8 bytes, so 64-bit dtypes survive even though the device path
# canonicalizes to 32 bits when jax_enable_x64 is off (ranks are assumed
# same-endian, as on any homogeneous TPU/CPU fleet).
_BCAST_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "bool",
                 "float16", "uint32", "uint64", "int8", "int16", "uint16",
                 "complex64", "complex128"]
_BCAST_MAX_NDIM = 8
_BCAST_ERR = -1   # header[0] sentinel: root-side validation failed


def broadcast(value: Any = None, root: int = 0) -> np.ndarray:
    """Broadcast ``value`` from ``root`` to all ranks (rabit::Broadcast).

    Only ``root`` needs to supply data — matching rabit's semantics; other
    ranks may pass ``None`` (the shape/dtype travel in a fixed-size header
    round first).  A non-None value on a non-root rank is ignored.
    """
    _require_init()
    rank = get_rank()
    if get_world_size() == 1:
        CHECK(value is not None, "broadcast root must supply a value")
        return np.asarray(value)
    # header round: root validates FIRST but always participates — a
    # root-side error is shipped as a sentinel so the other ranks raise too
    # instead of hanging in the collective
    header = np.zeros(2 + _BCAST_MAX_NDIM, np.int32)
    root_err: Optional[str] = None
    if rank == root:
        if value is None:
            root_err = "broadcast root must supply a value"
        else:
            value = np.asarray(value)
            if value.ndim > _BCAST_MAX_NDIM:
                root_err = f"broadcast supports ndim <= {_BCAST_MAX_NDIM}"
            elif str(value.dtype) not in _BCAST_DTYPES:
                root_err = f"unsupported broadcast dtype {value.dtype}"
        if root_err is None:
            header[0] = _BCAST_DTYPES.index(str(value.dtype))
            header[1] = value.ndim
            header[2:2 + value.ndim] = value.shape
        else:
            header[0] = _BCAST_ERR
    header = _global_op(header, "sum", root=root)
    if int(header[0]) == _BCAST_ERR:
        CHECK(False, root_err or
              f"broadcast root {root} failed validation; see its log")
    dtype = np.dtype(_BCAST_DTYPES[int(header[0])])
    shape = tuple(int(s) for s in header[2:2 + int(header[1])])
    nbytes = int(dtype.itemsize * int(np.prod(shape, dtype=np.int64)))
    if rank == root:
        payload = np.frombuffer(
            np.ascontiguousarray(value.astype(dtype, copy=False)).tobytes(),
            dtype=np.uint8)
    else:
        payload = np.zeros(nbytes, np.uint8)   # shape carrier; ignored
    out = _global_op(payload, "sum", root=root)
    return np.frombuffer(out.tobytes(), dtype=dtype).reshape(shape)


def allgather(value: Any) -> np.ndarray:
    """Gather each rank's array; returns [world, ...] on every rank."""
    return _global_op(np.asarray(value), "sum", gather=True)


def tracker_print(msg: str) -> None:
    """Print through the tracker on rank 0 (rabit::TrackerPrint)."""
    _require_init()
    if get_rank() == 0:
        sys.stderr.write(str(msg).rstrip("\n") + "\n")
        sys.stderr.flush()


def version_number() -> int:
    """Checkpoint version counter (rabit::VersionNumber)."""
    return _state["version"]


def _check_version_template(uri_template: str) -> None:
    CHECK(uri_template.format(version=1) != uri_template.format(version=2),
          "checkpoint uri_template must contain a {version} placeholder, "
          f"got {uri_template!r}")


def checkpoint(model: Any, uri_template: str = "") -> None:
    """Persist a model pytree for failure recovery (rabit::Checkpoint).

    Slice-granular resume (SURVEY.md §5.3): every rank writes rank-0-identical
    state via the URI-dispatched store; restart resumes from the latest version.
    """
    _state["version"] += 1
    if uri_template and get_rank() == 0:
        from dmlc_core_tpu.bridge.checkpoint import save_checkpoint

        _check_version_template(uri_template)
        save_checkpoint(uri_template.format(version=_state["version"]), model)


def load_checkpoint(uri_template: str = "", version: Optional[int] = None,
                    template: Any = None) -> Any:
    """Load the checkpoint saved by :func:`checkpoint`; None when absent.

    Like rabit's ``LoadCheckPoint``, a freshly restarted worker (version
    counter still 0) does not need to know which round died: rank 0
    discovers the latest version on the store (exponential ascent + binary
    search — O(log N) probes), falls back past a corrupt newest version,
    and BROADCASTS both the version and the model leaves to every rank, so
    ranks can never resume desynchronized even when the store is only
    reachable from rank 0 (this is the part of rabit's recovery that came
    from a surviving peer).  Multi-process recovery therefore requires
    ``template`` (the pytree structure to rebuild on non-root ranks).
    """
    if not uri_template:
        return None
    _check_version_template(uri_template)
    world = get_world_size() if _state["initialized"] else 1
    rank = get_rank() if _state["initialized"] else 0
    multi = world > 1
    if multi:
        CHECK(template is not None,
              "multi-process load_checkpoint needs template= (non-root "
              "ranks rebuild the model from broadcast leaves)")

    ver = version if version is not None else _state["version"]
    model = None
    if rank == 0 or not multi:
        if ver <= 0:
            ver = _discover_latest_version(uri_template)
        model, ver = _load_with_fallback(uri_template, ver, template)
    if multi:
        ver = int(broadcast(np.int64(ver if rank == 0 else 0), root=0))
        if ver > 0:
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(template)
            src_leaves = (jax.tree_util.tree_leaves(model) if rank == 0
                          else [np.zeros_like(np.asarray(l))
                                for l in leaves])
            model = jax.tree_util.tree_unflatten(
                treedef, [broadcast(np.asarray(s), root=0)
                          for s in src_leaves])
    if ver <= 0 or model is None:
        return None
    _state["version"] = ver
    return model


def _discover_latest_version(uri_template: str) -> int:
    """Largest contiguous existing version: exponential ascent to bracket,
    then binary search — O(log N) store probes instead of N."""
    if not _checkpoint_exists(uri_template, 1):
        return 0
    lo = 1                      # known to exist
    hi = 2
    while _checkpoint_exists(uri_template, hi):
        lo, hi = hi, hi * 2
    # invariant: lo exists, hi does not; find the boundary
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if _checkpoint_exists(uri_template, mid):
            lo = mid
        else:
            hi = mid
    return lo


def _load_with_fallback(uri_template: str, ver: int, template: Any):
    """Load version ``ver``, falling back past corrupt/truncated newer
    versions (a remote store without atomic rename can expose a partial
    newest file — same policy as CheckpointManager.restore)."""
    from dmlc_core_tpu.bridge.checkpoint import load_checkpoint as _load

    last_err: Optional[BaseException] = None
    while ver > 0:
        try:
            return _load(uri_template.format(version=ver), template), ver
        except Exception as e:  # noqa: BLE001 — fall back past bad versions
            log_info(f"checkpoint version {ver} unreadable ({e}); "
                     "falling back to previous version")
            last_err = e
            ver -= 1
    if last_err is not None and not _checkpoint_exists(uri_template, 1):
        # nothing restorable at all, and version 1 is genuinely absent:
        # treat as a fresh start rather than an error
        return None, 0
    if last_err is not None:
        raise RuntimeError(
            f"no restorable checkpoint for {uri_template!r}") from last_err
    return None, 0


def _checkpoint_exists(uri_template: str, version: int) -> bool:
    """Existence probe.  Only genuinely-missing paths count as absent;
    transient store errors (auth, network) must PROPAGATE — treating them
    as 'absent' would silently roll training back to an older version and
    later overwrite newer checkpoints with stale state."""
    from dmlc_core_tpu.io.stream import create_stream_for_read

    try:
        s = create_stream_for_read(uri_template.format(version=version))
    except (FileNotFoundError, IsADirectoryError):
        return False
    if s is None:
        return False
    s.close()
    return True
