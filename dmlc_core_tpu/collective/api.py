"""Process-level Rabit-shaped collective API.

Mirrors the client contract the reference tracker serves (rabit's
init/finalize/get_rank/get_world_size/allreduce/broadcast/version_number/
checkpoint — the env-var protocol in SURVEY.md §5.6): each *process* is a
rank; arrays are host numpy arrays; reduction happens across processes.

Implementation: ``jax.distributed`` global runtime + one global 1-D mesh over
every device of every process.  An allreduce builds a global array whose
process-local shard is this rank's contribution, then runs a jit-compiled
cross-device reduction (XLA lowers it to ICI/DCN collectives); the result is
fetched fully-replicated.  Single-process runs degrade to local identity, so
the same script works from a laptop to a pod (the reference's local-vs-cluster
symmetry).

Env contract (set by dmlc_core_tpu.tracker launchers, reference tracker.py):
``DMLC_TASK_ID`` → process id, ``DMLC_NUM_WORKER`` → world size,
``DMLC_COORDINATOR_URI``/``DMLC_COORDINATOR_PORT`` → jax.distributed
coordinator address.
"""

from __future__ import annotations

import atexit
import os
import socket
import sys
from typing import Any, Optional

import numpy as np

from dmlc_core_tpu.param import get_env
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ, log_info

__all__ = [
    "init",
    "finalize",
    "is_initialized",
    "get_rank",
    "get_world_size",
    "get_processor_name",
    "allreduce",
    "broadcast",
    "allgather",
    "tracker_print",
    "version_number",
    "checkpoint",
    "load_checkpoint",
]

_state: dict = {
    "initialized": False,
    "distributed": False,
    "mesh": None,
    "version": 0,
}

_OPS = {"sum": np.add, "max": np.maximum, "min": np.minimum, "prod": np.multiply}


def init(args: Optional[dict] = None) -> None:
    """Initialize the collective runtime (rabit::Init equivalent).

    In a tracker-launched job (DMLC_NUM_WORKER > 1 in the environment) this
    calls ``jax.distributed.initialize`` against the coordinator the launcher
    advertised; standalone it is a no-op beyond building the local mesh.
    """
    if _state["initialized"]:
        return
    import jax

    env = dict(os.environ)
    if args:
        env.update({k: str(v) for k, v in args.items()})
    num_worker = int(env.get("DMLC_NUM_WORKER", "1"))
    task_id = int(env.get("DMLC_TASK_ID", "0"))
    coord_uri = env.get("DMLC_COORDINATOR_URI", "")
    coord_port = env.get("DMLC_COORDINATOR_PORT", "")
    if num_worker > 1 and coord_uri:
        jax.distributed.initialize(
            coordinator_address=f"{coord_uri}:{coord_port}",
            num_processes=num_worker,
            process_id=task_id,
        )
        _state["distributed"] = True
    from dmlc_core_tpu.parallel.mesh import make_mesh

    _state["mesh"] = make_mesh({"world": len(jax.devices())})
    _state["initialized"] = True
    atexit.register(finalize)


def finalize() -> None:
    """rabit::Finalize equivalent."""
    if not _state["initialized"]:
        return
    if _state["distributed"]:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    _state.update(initialized=False, distributed=False, mesh=None)


def is_initialized() -> bool:
    return _state["initialized"]


def _require_init() -> None:
    CHECK(_state["initialized"], "collective.init() must be called first")


def get_rank() -> int:
    _require_init()
    import jax

    return jax.process_index()


def get_world_size() -> int:
    _require_init()
    import jax

    return jax.process_count()


def get_processor_name() -> str:
    return socket.gethostname()


def _global_op(value: np.ndarray, op: str, root: Optional[int] = None,
               gather: bool = False) -> np.ndarray:
    """Shared engine: stack per-process contributions on a leading axis,
    reduce (or gather) on device, return replicated result."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    _require_init()
    value = np.asarray(value)
    nproc = jax.process_count()
    if nproc == 1:
        if gather:
            return value[None]
        if root is not None:
            return value
        return value
    mesh = _state["mesh"]
    ndev = mesh.devices.size
    per_proc = ndev // nproc
    # leading axis = device slots; each process replicates its value into its
    # local slots so the global array's shard on process p holds value_p.
    local = np.broadcast_to(value[None], (per_proc,) + value.shape)
    sharding = NamedSharding(mesh, P("world"))
    garr = jax.make_array_from_process_local_data(sharding, local,
                                                  (ndev,) + value.shape)
    out_sharding = NamedSharding(mesh, P())
    if gather:
        # take one slot per process: slots are process-major
        fn = jax.jit(lambda x: x[::per_proc],
                     out_shardings=NamedSharding(mesh, P()))
        return np.asarray(fn(garr))
    if root is not None:
        fn = jax.jit(lambda x: x[root * per_proc],
                     out_shardings=out_sharding)
        return np.asarray(fn(garr))
    reducers = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min, "prod": jnp.prod}
    CHECK(op in reducers, f"unknown reduce op {op!r}")
    red = reducers[op]
    # each process's value appears per_proc times; correct for duplication
    if op == "sum":
        fn = jax.jit(lambda x: red(x[::per_proc], axis=0), out_shardings=out_sharding)
    elif op == "prod":
        fn = jax.jit(lambda x: red(x[::per_proc], axis=0), out_shardings=out_sharding)
    else:
        fn = jax.jit(lambda x: red(x, axis=0), out_shardings=out_sharding)
    return np.asarray(fn(garr))


def allreduce(value: Any, op: str = "sum") -> np.ndarray:
    """Elementwise reduce across all ranks; result identical on every rank
    (rabit::Allreduce).  ``op`` in {sum, max, min, prod}."""
    return _global_op(np.asarray(value), op)


def broadcast(value: Any, root: int = 0) -> np.ndarray:
    """Broadcast ``value`` from ``root`` to all ranks (rabit::Broadcast).
    Every rank must pass an array of the same shape/dtype."""
    return _global_op(np.asarray(value), "sum", root=root)


def allgather(value: Any) -> np.ndarray:
    """Gather each rank's array; returns [world, ...] on every rank."""
    return _global_op(np.asarray(value), "sum", gather=True)


def tracker_print(msg: str) -> None:
    """Print through the tracker on rank 0 (rabit::TrackerPrint)."""
    _require_init()
    if get_rank() == 0:
        sys.stderr.write(str(msg).rstrip("\n") + "\n")
        sys.stderr.flush()


def version_number() -> int:
    """Checkpoint version counter (rabit::VersionNumber)."""
    return _state["version"]


def checkpoint(model: Any, uri_template: str = "") -> None:
    """Persist a model pytree for failure recovery (rabit::Checkpoint).

    Slice-granular resume (SURVEY.md §5.3): every rank writes rank-0-identical
    state via the URI-dispatched store; restart resumes from the latest version.
    """
    _state["version"] += 1
    if uri_template and get_rank() == 0:
        from dmlc_core_tpu.bridge.checkpoint import save_checkpoint

        save_checkpoint(uri_template.format(version=_state["version"]), model)


def load_checkpoint(uri_template: str = "", version: Optional[int] = None) -> Any:
    """Load the checkpoint saved by :func:`checkpoint`; None when absent."""
    if not uri_template:
        return None
    from dmlc_core_tpu.bridge.checkpoint import load_checkpoint as _load

    ver = version if version is not None else _state["version"]
    if ver <= 0:
        return None
    try:
        model = _load(uri_template.format(version=ver))
    except (OSError, IOError):
        return None
    _state["version"] = ver
    return model
