"""ctypes loader + wrappers for the C++ native core (native/parsers.cc).

The reference's hot byte path is C++ (src/data/); here the same role is played
by ``libdmlc_tpu_native.so``: multi-threaded chunk parsers returning numpy
arrays.  The library is built from ``native/`` with ``make`` on first use
(g++ is in the image); every caller falls back to the numpy path when the
library is unavailable, so the pure-Python package remains fully functional.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["available", "parse_libsvm", "parse_libfm", "parse_csv",
           "find_magic_positions"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdmlc_tpu_native.so")


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=300)
        return os.path.exists(_SO_PATH)
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DMLC_TPU_DISABLE_NATIVE"):
            return None
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        # ABI handshake: a stale build with old entry-point signatures must
        # not be called through mismatched ctypes prototypes — rebuild once,
        # and disable the native path if the rebuild still disagrees
        _ABI = 5
        ver_fn = getattr(lib, "dmlc_tpu_abi_version", None)
        if ver_fn is None or int(ver_fn()) != _ABI:
            del lib
            # unlink BEFORE rebuilding: dlopen dedups by (dev, inode), so an
            # in-place relink would hand the second CDLL the already-mapped
            # stale library (and rewriting a mapped ELF risks clobbering its
            # pages); a fresh inode guarantees a fresh mapping
            try:
                os.unlink(_SO_PATH)
            except OSError:
                pass
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_SO_PATH)
            except OSError:
                return None
            ver_fn = getattr(lib, "dmlc_tpu_abi_version", None)
            if ver_fn is None or int(ver_fn()) != _ABI:
                return None
        for name in ("dmlc_tpu_parse_libsvm", "dmlc_tpu_parse_libfm"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_void_p
            fn.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
        lib.dmlc_tpu_parse_csv.restype = ctypes.c_void_p
        lib.dmlc_tpu_parse_csv.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_float]
        lib.dmlc_tpu_result_dims.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32)]
        lib.dmlc_tpu_error_msg.restype = ctypes.c_char_p
        lib.dmlc_tpu_error_msg.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_result_fill.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_void_p] * 6
        lib.dmlc_tpu_result_fill_csv.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]
        lib.dmlc_tpu_result_free.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_find_magic.restype = ctypes.c_int64
        lib.dmlc_tpu_find_magic.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_int64]
        lib.dmlc_tpu_recordio_scan.restype = ctypes.c_void_p
        lib.dmlc_tpu_recordio_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
        lib.dmlc_tpu_recordio_scan_dims.argtypes = [
            ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_int64)] * 3
        lib.dmlc_tpu_recordio_scan_error.restype = ctypes.c_char_p
        lib.dmlc_tpu_recordio_scan_error.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_recordio_scan_fill.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_void_p] * 3
        lib.dmlc_tpu_recordio_scan_free.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_recordio_extract.restype = ctypes.c_int64
        lib.dmlc_tpu_recordio_extract.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64]
        lib.dmlc_tpu_recordio_frame.restype = ctypes.c_void_p
        lib.dmlc_tpu_recordio_frame.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64]
        lib.dmlc_tpu_frame_dims.argtypes = [
            ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_int64)] * 3
        lib.dmlc_tpu_frame_error.restype = ctypes.c_char_p
        lib.dmlc_tpu_frame_error.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_frame_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.dmlc_tpu_frame_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(arr: Optional[np.ndarray]):
    if arr is None or arr.size == 0:
        return None
    return arr.ctypes.data_as(ctypes.c_void_p)


def _as_data_ptr(data):
    """bytes -> (c_char_p, len); (addr, len) -> zero-copy pointer pass.

    The (addr, len) form is the native-split fast path: the chunk stays in
    the split handle's buffer (valid until its next call) and the parser
    reads it in place — no Python bytes materialization between the C++
    split engine and the C++ parser.
    """
    if isinstance(data, tuple):
        addr, length = data
        return ctypes.c_char_p(addr), length
    return data, len(data)


def _parse_sparse(fn_name: str, data, nthread: int):
    lib = _load()
    assert lib is not None
    ptr, length = _as_data_ptr(data)
    handle = getattr(lib, fn_name)(ptr, length, nthread)
    try:
        n_rows = ctypes.c_int64()
        nnz = ctypes.c_int64()
        n_cols = ctypes.c_int64()
        flags = ctypes.c_int32()
        lib.dmlc_tpu_result_dims(handle, ctypes.byref(n_rows),
                                 ctypes.byref(nnz), ctypes.byref(n_cols),
                                 ctypes.byref(flags))
        if n_rows.value < 0:
            raise ValueError(lib.dmlc_tpu_error_msg(handle).decode())
        nr, nz, fl = n_rows.value, nnz.value, flags.value
        offset = np.empty(nr + 1, dtype=np.int64)
        label = np.empty(nr, dtype=np.float32)
        weight = np.empty(nr, dtype=np.float32) if (fl & 1) else None
        index = np.empty(nz, dtype=np.uint32)
        field = np.empty(nz, dtype=np.uint32) if (fl & 4) else None
        value = np.empty(nz, dtype=np.float32) if (fl & 2) else None
        lib.dmlc_tpu_result_fill(handle, _ptr(offset), _ptr(label),
                                 _ptr(weight), _ptr(index), _ptr(field),
                                 _ptr(value), None)
        return offset, label, weight, index, field, value
    finally:
        lib.dmlc_tpu_result_free(handle)


def parse_libsvm(data, nthread: int = 4):
    """Chunk (bytes or zero-copy ``(addr, len)``) ->
    (offset, label, weight|None, index, value|None)."""
    offset, label, weight, index, _, value = _parse_sparse(
        "dmlc_tpu_parse_libsvm", data, nthread)
    return offset, label, weight, index, value


def parse_libfm(data, nthread: int = 4):
    """Chunk (bytes or zero-copy ``(addr, len)``) ->
    (offset, label, weight|None, index, field, value)."""
    offset, label, weight, index, field, value = _parse_sparse(
        "dmlc_tpu_parse_libfm", data, nthread)
    return offset, label, weight, index, field, value


def parse_csv(data, nthread: int = 4, missing: float = 0.0,
              label_column: int = -1):
    """Chunk (bytes or zero-copy ``(addr, len)``) -> parsed CSV floats.

    With ``label_column`` out of range (default) returns the dense
    ``[n_rows, n_cols]`` float32 block.  With ``0 <= label_column <
    n_cols`` returns ``(labels, feats)`` — the split is one C pass
    (``dmlc_tpu_result_fill_csv``) instead of a full extra numpy copy.

    ``missing`` fills empty cells (reference strtof-on-empty parity = 0.0;
    NaN for sparsity-aware training).
    """
    lib = _load()
    assert lib is not None
    ptr, length = _as_data_ptr(data)
    handle = lib.dmlc_tpu_parse_csv(ptr, length, nthread,
                                    ctypes.c_float(missing))
    try:
        n_rows = ctypes.c_int64()
        nnz = ctypes.c_int64()
        n_cols = ctypes.c_int64()
        flags = ctypes.c_int32()
        lib.dmlc_tpu_result_dims(handle, ctypes.byref(n_rows),
                                 ctypes.byref(nnz), ctypes.byref(n_cols),
                                 ctypes.byref(flags))
        if n_rows.value < 0:
            raise ValueError(lib.dmlc_tpu_error_msg(handle).decode())
        if 0 <= label_column < n_cols.value:
            labels = np.empty(n_rows.value, dtype=np.float32)
            feats = np.empty((n_rows.value, n_cols.value - 1),
                             dtype=np.float32)
            lib.dmlc_tpu_result_fill_csv(handle, label_column,
                                         _ptr(labels),
                                         _ptr(feats.reshape(-1)))
            return labels, feats
        dense = np.empty((n_rows.value, n_cols.value), dtype=np.float32)
        lib.dmlc_tpu_result_fill(handle, None, None, None, None, None, None,
                                 _ptr(dense.reshape(-1)))
        return dense
    finally:
        lib.dmlc_tpu_result_free(handle)


def find_magic_positions(data: bytes, magic: int, limit: int) -> np.ndarray:
    """Aligned magic-word byte offsets (RecordIO writer escape scan)."""
    lib = _load()
    assert lib is not None
    out = np.empty(limit, dtype=np.int64)
    n = lib.dmlc_tpu_find_magic(data, len(data), magic, _ptr(out), limit)
    return out[:min(n, limit)]


def recordio_scan(data: bytes, begin: int, end: int
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int, int]:
    """One-pass record scan of a chunk partition.

    Returns ``(head, plen, escaped, pbegin, pend)``: per-record head byte
    offsets, logical payload lengths, escaped flags, and the resynced
    partition bounds (reference RecordIOChunkReader, src/recordio.cc:102-156).
    """
    lib = _load()
    assert lib is not None
    handle = lib.dmlc_tpu_recordio_scan(data, len(data), begin, end)
    try:
        n = ctypes.c_int64()
        pbegin = ctypes.c_int64()
        pend = ctypes.c_int64()
        lib.dmlc_tpu_recordio_scan_dims(handle, ctypes.byref(n),
                                        ctypes.byref(pbegin),
                                        ctypes.byref(pend))
        if n.value < 0:
            raise ValueError(lib.dmlc_tpu_recordio_scan_error(handle).decode())
        head = np.empty(n.value, dtype=np.int64)
        plen = np.empty(n.value, dtype=np.int64)
        escaped = np.empty(n.value, dtype=np.uint8)
        lib.dmlc_tpu_recordio_scan_fill(handle, _ptr(head), _ptr(plen),
                                        _ptr(escaped))
        return head, plen, escaped, pbegin.value, pend.value
    finally:
        lib.dmlc_tpu_recordio_scan_free(handle)


def recordio_extract(data: bytes, head: int, length: int) -> bytes:
    """Reassemble one (escaped) record whose head is at byte offset ``head``;
    ``length`` is its logical payload length from a prior scan."""
    lib = _load()
    assert lib is not None
    out = np.empty(length, dtype=np.uint8)
    got = lib.dmlc_tpu_recordio_extract(data, len(data), head, _ptr(out),
                                        length)
    if got < 0:
        raise ValueError("invalid RecordIO format: bad record head")
    return out[:got].tobytes()


def recordio_frame(payloads: bytes, lens: np.ndarray
                   ) -> Tuple[memoryview, np.ndarray, int]:
    """Batch-encode concatenated payloads into RecordIO framing.

    Returns ``(framed, offsets, except_count)`` where ``framed`` is a
    memoryview over a freshly-filled buffer (no extra copy) and
    ``offsets[i]`` is the start of record i within it (reference writer,
    recordio.cc:11-51).
    """
    lib = _load()
    assert lib is not None
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    handle = lib.dmlc_tpu_recordio_frame(payloads, _ptr(lens), len(lens))
    try:
        size = ctypes.c_int64()
        n_off = ctypes.c_int64()
        nexc = ctypes.c_int64()
        lib.dmlc_tpu_frame_dims(handle, ctypes.byref(size),
                                ctypes.byref(n_off), ctypes.byref(nexc))
        if size.value < 0:
            raise ValueError(lib.dmlc_tpu_frame_error(handle).decode())
        out = np.empty(size.value, dtype=np.uint8)
        offsets = np.empty(n_off.value, dtype=np.int64)
        lib.dmlc_tpu_frame_fill(handle, _ptr(out), _ptr(offsets))
        return memoryview(out).cast("B"), offsets, nexc.value
    finally:
        lib.dmlc_tpu_frame_free(handle)


# ---- native line-split engine (native/input_split.cc) ----------------------

# read-at callback signature: (ctx, file_idx, offset, buf, size) -> bytes
# read (0 = EOF), <0 = error.  Python implementations run on the native
# prefetch thread; ctypes acquires the GIL per call.
READ_AT_FN = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_void_p,
                              ctypes.c_int64, ctypes.c_int64,
                              ctypes.POINTER(ctypes.c_char), ctypes.c_int64)


def _load_lsplit():
    lib = _load()
    if lib is None:
        return None
    if not hasattr(lib, "dmlc_tpu_span_open"):
        return None  # stale library built before the full split engine existed
    if not getattr(lib, "_lsplit_wired", False):
        open_sig = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
        lib.dmlc_tpu_lsplit_open.restype = ctypes.c_void_p
        lib.dmlc_tpu_lsplit_open.argtypes = open_sig
        lib.dmlc_tpu_rsplit_open.restype = ctypes.c_void_p
        lib.dmlc_tpu_rsplit_open.argtypes = open_sig
        lib.dmlc_tpu_lsplit_open2.restype = ctypes.c_void_p
        lib.dmlc_tpu_lsplit_open2.argtypes = open_sig + [
            ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, READ_AT_FN,
            ctypes.c_void_p]
        lib.dmlc_tpu_lsplit_finish_cache.restype = ctypes.c_int64
        lib.dmlc_tpu_lsplit_finish_cache.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_creplay_open.restype = ctypes.c_void_p
        lib.dmlc_tpu_creplay_open.argtypes = [ctypes.c_char_p]
        lib.dmlc_tpu_creplay_reset.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_creplay_next_chunk.restype = ctypes.c_int64
        lib.dmlc_tpu_creplay_next_chunk.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p)]
        lib.dmlc_tpu_creplay_error.restype = ctypes.c_char_p
        lib.dmlc_tpu_creplay_error.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_creplay_close.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_span_open.restype = ctypes.c_void_p
        lib.dmlc_tpu_span_open.argtypes = open_sig[:4]
        lib.dmlc_tpu_span_open2.restype = ctypes.c_void_p
        lib.dmlc_tpu_span_open2.argtypes = open_sig[:4] + [
            READ_AT_FN, ctypes.c_void_p]
        lib.dmlc_tpu_span_set_plan.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64]
        lib.dmlc_tpu_span_next_chunk.restype = ctypes.c_int64
        lib.dmlc_tpu_span_next_chunk.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p)]
        lib.dmlc_tpu_span_error.restype = ctypes.c_char_p
        lib.dmlc_tpu_span_error.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_span_close.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_lsplit_hint.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.dmlc_tpu_lsplit_total.restype = ctypes.c_int64
        lib.dmlc_tpu_lsplit_total.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_lsplit_reset.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        lib.dmlc_tpu_lsplit_next_chunk.restype = ctypes.c_int64
        lib.dmlc_tpu_lsplit_next_chunk.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p)]
        lib.dmlc_tpu_lsplit_next_chunks.restype = ctypes.c_int64
        lib.dmlc_tpu_lsplit_next_chunks.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.dmlc_tpu_lsplit_error.restype = ctypes.c_char_p
        lib.dmlc_tpu_lsplit_error.argtypes = [ctypes.c_void_p]
        lib.dmlc_tpu_lsplit_close.argtypes = [ctypes.c_void_p]
        lib._lsplit_wired = True
    return lib


def lsplit_available() -> bool:
    return _load_lsplit() is not None


def _encode_files(paths, sizes):
    encoded = [p.encode() for p in paths]
    blob = b"".join(encoded)         # length-delimited: any filename byte ok
    lens = (ctypes.c_int64 * len(encoded))(*[len(e) for e in encoded])
    arr = (ctypes.c_int64 * len(sizes))(*sizes)
    return blob, lens, arr


class NativeLineSplit:
    """Handle over the C++ split engine (sharded read + prefetch thread).

    ``next_chunk`` returns bytes of whole records for the partition, or
    None at the end.  ``reset`` re-partitions (or rewinds, with the same
    arguments).  ``format`` selects the record kind: "line" or "recordio"
    (same engine, different realignment scan — native/input_split.cc).

    ``read_at`` (a ``READ_AT_FN``-compatible callable) routes all byte
    reads through Python — the remote-filesystem path; ``cache_path``
    tees epoch-1 chunks into a cache file (``finish_cache`` closes it,
    :class:`NativeCacheReplay` replays it).

    ``ring`` is the native prefetch-queue depth.  2 is the classic double
    buffer; deeper rings pre-post more read-ahead AND switch the consumer
    to the batched ``next_chunks`` pop — one Python↔C crossing (one GIL
    round-trip) amortizes over everything the ring had buffered, the
    VERDICT item-6 fix for the per-chunk crossing tax on the remote
    callback path.
    """

    def __init__(self, paths, sizes, part: int, nparts: int,
                 buffer_size: int = 8 << 20, format: str = "line",
                 read_at=None, cache_path: Optional[str] = None,
                 ring: int = 2):
        lib = _load_lsplit()
        assert lib is not None
        self._lib = lib
        self._ring = max(2, int(ring))
        # batched-pop state: arrays the C side fills in one crossing, and
        # the views already handed back from the last fill (addr, len)
        self._batch_ptrs = (ctypes.c_char_p * self._ring)()
        self._batch_lens = (ctypes.c_int64 * self._ring)()
        self._pending: list = []
        blob, lens, arr = _encode_files(paths, sizes)
        # the CFUNCTYPE object must outlive the handle (the prefetch thread
        # calls through it); keep the reference on self
        if read_at is not None and not isinstance(read_at, READ_AT_FN):
            read_at = READ_AT_FN(read_at)
        self._read_at = read_at
        self._handle = lib.dmlc_tpu_lsplit_open2(
            blob, lens, arr, len(sizes), part, nparts, buffer_size,
            1 if format == "recordio" else 0, self._ring,
            cache_path.encode() if cache_path else None,
            self._read_at if self._read_at is not None
            else ctypes.cast(None, READ_AT_FN), None)
        self._check()

    def finish_cache(self) -> None:
        """Drain the rest of the partition through the cache tee and close
        the cache file (the preproc finish of the cached split)."""
        if self._lib.dmlc_tpu_lsplit_finish_cache(self._require_open()) != 0:
            self._check()

    def _require_open(self):
        if self._handle is None:
            raise ValueError("NativeLineSplit is closed")
        return self._handle

    def _check(self):
        err = self._lib.dmlc_tpu_lsplit_error(self._require_open())
        if err:
            raise OSError(err.decode())

    def total_size(self) -> int:
        return self._lib.dmlc_tpu_lsplit_total(self._require_open())

    def reset(self, part: int, nparts: int) -> None:
        self._pending.clear()   # views into pre-reset chunks are stale
        self._lib.dmlc_tpu_lsplit_reset(self._require_open(), part, nparts)
        self._check()

    def hint_chunk_size(self, chunk_size: int) -> None:
        """Grow the typical chunk size; read position is unaffected."""
        self._lib.dmlc_tpu_lsplit_hint(self._require_open(), chunk_size)

    def next_chunk(self):
        view = self.next_chunk_view()
        if view is None:
            return None
        return ctypes.string_at(*view)

    def next_chunk_view(self):
        """Zero-copy ``(addr, len)`` over the next chunk — valid at least
        until the crossing after the batch it came from drains (with the
        default ``ring=2``: until the next call, the classic contract; the
        parser fast path consumes it in place before popping again).

        With ``ring > 2`` one batched ``next_chunks`` crossing drains
        everything the native ring had buffered and later calls serve from
        that batch without touching the GIL/ctypes boundary."""
        if self._ring > 2:
            if self._pending:
                return self._pending.pop(0)
            n = self._lib.dmlc_tpu_lsplit_next_chunks(
                self._require_open(), self._batch_ptrs, self._batch_lens,
                self._ring)
            if n < 0:
                self._check()
            if n <= 0:
                return None
            ptrs = ctypes.cast(self._batch_ptrs,
                               ctypes.POINTER(ctypes.c_void_p))
            self._pending = [(ptrs[i], self._batch_lens[i])
                             for i in range(n)]
            return self._pending.pop(0)
        ptr = ctypes.c_char_p()
        n = self._lib.dmlc_tpu_lsplit_next_chunk(self._require_open(),
                                                 ctypes.byref(ptr))
        if n < 0:
            self._check()
        if n <= 0:
            return None
        return ctypes.cast(ptr, ctypes.c_void_p).value, n

    def close(self) -> None:
        self._pending.clear()   # batched views die with the handle
        if self._handle is not None:
            self._lib.dmlc_tpu_lsplit_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeSpanReader:
    """C++ span-plan reader: index-driven batch reads with prefetch.

    The caller (IndexedRecordIOSplitter) computes a per-epoch plan — flat
    (offset, size) spans in the concatenated-file space plus per-batch span
    counts — and pops concatenated batch chunks; a native producer thread
    reads ahead (native/input_split.cc SpanReadEngine).
    """

    def __init__(self, paths, sizes, read_at=None):
        lib = _load_lsplit()
        assert lib is not None
        self._lib = lib
        blob, lens, arr = _encode_files(paths, sizes)
        if read_at is not None and not isinstance(read_at, READ_AT_FN):
            read_at = READ_AT_FN(read_at)
        self._read_at = read_at  # keep alive for the prefetch thread
        self._handle = lib.dmlc_tpu_span_open2(
            blob, lens, arr, len(sizes),
            self._read_at if self._read_at is not None
            else ctypes.cast(None, READ_AT_FN), None)

    def _require_open(self):
        if self._handle is None:
            raise ValueError("NativeSpanReader is closed")
        return self._handle

    def _check(self):
        err = self._lib.dmlc_tpu_span_error(self._require_open())
        if err:
            raise OSError(err.decode())

    def set_plan(self, offsets, sizes, counts) -> None:
        """Start a new epoch: spans (offsets[i], sizes[i]); batch b is the
        concatenation of counts[b] consecutive spans."""
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        szs = np.ascontiguousarray(sizes, dtype=np.int64)
        cnt = np.ascontiguousarray(counts, dtype=np.int64)
        assert len(offs) == len(szs)
        self._lib.dmlc_tpu_span_set_plan(
            self._require_open(),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            szs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(offs), len(cnt))

    def next_chunk(self):
        ptr = ctypes.c_char_p()
        n = self._lib.dmlc_tpu_span_next_chunk(self._require_open(),
                                               ctypes.byref(ptr))
        if n < 0:
            self._check()
        if n <= 0:
            return None
        return ctypes.string_at(ptr, n)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dmlc_tpu_span_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeCacheReplay:
    """Replays a (u64-LE length, chunk)-framed cache file with native
    read-ahead — epoch N of the cached split (native/input_split.cc
    CacheReplayEngine; frame format shared with the Python cache writer)."""

    def __init__(self, path: str):
        lib = _load_lsplit()
        assert lib is not None
        self._lib = lib
        self._handle = lib.dmlc_tpu_creplay_open(path.encode())
        self._check()

    def _require_open(self):
        if self._handle is None:
            raise ValueError("NativeCacheReplay is closed")
        return self._handle

    def _check(self):
        err = self._lib.dmlc_tpu_creplay_error(self._require_open())
        if err:
            raise OSError(err.decode())

    def reset(self) -> None:
        """Rewind to the first frame (epoch boundary)."""
        self._lib.dmlc_tpu_creplay_reset(self._require_open())
        self._check()

    def next_chunk(self):
        view = self.next_chunk_view()
        if view is None:
            return None
        return ctypes.string_at(*view)

    def next_chunk_view(self):
        """Zero-copy ``(addr, len)``, valid until the next call."""
        ptr = ctypes.c_char_p()
        n = self._lib.dmlc_tpu_creplay_next_chunk(self._require_open(),
                                                  ctypes.byref(ptr))
        if n < 0:
            self._check()
        if n <= 0:
            return None
        return ctypes.cast(ptr, ctypes.c_void_p).value, n

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dmlc_tpu_creplay_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
