"""hdfs:// filesystem, gated on pyarrow's libhdfs bindings.

The reference wraps libhdfs via JNI behind the DMLC_USE_HDFS compile flag
(src/io/hdfs_filesys.{h,cc}); the rebuild gates at import: when
``pyarrow.fs.HadoopFileSystem`` (which drives the same libhdfs) is available
it backs the Stream contract, otherwise any hdfs:// access raises an
actionable error — matching the reference's "compiled without HDFS" failure
mode (src/io.cc:38-42).
"""

from __future__ import annotations

from typing import List

from dmlc_core_tpu.io import filesys as fsys
from dmlc_core_tpu.io import fs_metrics
from dmlc_core_tpu.io.stream import SeekStream, Stream
from dmlc_core_tpu.registry import Registry
from dmlc_core_tpu.utils.logging import CHECK, log_fatal

__all__ = ["HDFSFileSystem"]


def _arrow_fs(uri: fsys.URI):
    try:
        from pyarrow import fs as pafs  # type: ignore
    except ImportError:
        log_fatal(
            "hdfs:// support requires pyarrow with libhdfs (the reference "
            "gates the same way with DMLC_USE_HDFS, src/io.cc:38-42); "
            "install pyarrow + a Hadoop client, or use file:///gs:///s3://")
    host = uri.host or "default"
    if ":" in host:
        name, port = host.rsplit(":", 1)
        return pafs.HadoopFileSystem(name, int(port))
    return pafs.HadoopFileSystem(host)


class _ArrowStream(SeekStream):
    def __init__(self, f, writable: bool):
        self._f = f
        self._writable = writable

    def read(self, nbytes: int) -> bytes:
        t0 = fs_metrics.request_start()
        data = self._f.read(nbytes)
        fs_metrics.note_request("hdfs", "read", t0, nread=len(data))
        return data

    def write(self, data: bytes) -> None:
        CHECK(self._writable, "stream opened read-only")
        t0 = fs_metrics.request_start()
        self._f.write(data)
        fs_metrics.note_request("hdfs", "write", t0, nwritten=len(data))

    def seek(self, pos: int) -> None:
        self._f.seek(pos)

    def tell(self) -> int:
        return self._f.tell()

    def close(self) -> None:
        self._f.close()


class HDFSFileSystem(fsys.FileSystem):
    def get_path_info(self, path: fsys.URI) -> fsys.FileInfo:
        from pyarrow import fs as pafs  # type: ignore

        hdfs = _arrow_fs(path)
        info = hdfs.get_file_info(path.name)
        if info.type == pafs.FileType.NotFound:
            raise FileNotFoundError(path.str())
        ftype = (fsys.FileType.DIRECTORY
                 if info.type == pafs.FileType.Directory else fsys.FileType.FILE)
        return fsys.FileInfo(path.copy(), info.size or 0, ftype)

    def list_directory(self, path: fsys.URI) -> List[fsys.FileInfo]:
        from pyarrow import fs as pafs  # type: ignore

        hdfs = _arrow_fs(path)
        sel = pafs.FileSelector(path.name)
        out = []
        for info in hdfs.get_file_info(sel):
            sub = path.copy()
            sub.name = info.path
            ftype = (fsys.FileType.DIRECTORY
                     if info.type == pafs.FileType.Directory
                     else fsys.FileType.FILE)
            out.append(fsys.FileInfo(sub, info.size or 0, ftype))
        return out

    def open(self, path: fsys.URI, mode: str) -> Stream:
        hdfs = _arrow_fs(path)
        if mode == "r":
            return _ArrowStream(hdfs.open_input_file(path.name), False)
        if mode == "w":
            return _ArrowStream(hdfs.open_output_stream(path.name), True)
        return _ArrowStream(hdfs.open_append_stream(path.name), True)

    def delete(self, path: fsys.URI) -> None:
        # hdfs writes stream THROUGH to the target (no abort/commit point),
        # so abandoning a half-written file means deleting it
        _arrow_fs(path).delete_file(path.name)

    def open_for_read(self, path: fsys.URI) -> SeekStream:
        hdfs = _arrow_fs(path)
        return _ArrowStream(hdfs.open_input_file(path.name), False)


Registry.get("filesystem").add("hdfs", HDFSFileSystem,
                               description="HDFS via pyarrow/libhdfs (gated)")
