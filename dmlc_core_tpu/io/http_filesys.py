"""Read-only http:// and https:// filesystem.

The reference routes http/https URIs to its S3 reader (src/io.cc:44-48);
here they get a plain ranged-GET stream with no signing, useful for public
datasets.  Seek uses Range requests when the server supports them, else
re-streams from the start.
"""

from __future__ import annotations

import http.client
import urllib.parse
from typing import List

from dmlc_core_tpu.io import filesys as fsys
from dmlc_core_tpu.io import fs_metrics
from dmlc_core_tpu.io.stream import SeekStream, Stream
from dmlc_core_tpu.registry import Registry
from dmlc_core_tpu.utils.logging import CHECK, log_fatal

__all__ = ["HTTPFileSystem"]


class _HTTPReadStream(SeekStream):
    def __init__(self, secure: bool, host: str, path: str, size: int,
                 accept_ranges: bool, buffer_bytes: int = 4 << 20):
        self._secure = secure
        self._host = host
        self._path = path
        self._size = size
        self._ranges = accept_ranges
        self._pos = 0
        self._buf = b""
        self._buf_start = 0
        self._buffer_bytes = buffer_bytes

    def _fetch(self, start: int, length: int) -> bytes:
        conn = (http.client.HTTPSConnection if self._secure
                else http.client.HTTPConnection)(self._host, timeout=60)
        t0 = fs_metrics.request_start()
        try:
            headers = {}
            if self._ranges:
                headers["Range"] = f"bytes={start}-{start + length - 1}"
            conn.request("GET", self._path, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            fs_metrics.note_request("http", "GET", t0, nread=len(data))
            CHECK(resp.status in (200, 206),
                  f"http error {resp.status} for {self._path}")
            if resp.status == 200 and self._ranges:
                self._ranges = False
            if not self._ranges:
                return data[start:start + length]
            return data
        finally:
            conn.close()

    def read(self, nbytes: int) -> bytes:
        if self._size and self._pos >= self._size:
            return b""
        off = self._pos - self._buf_start
        if not (0 <= off < len(self._buf)):
            want = max(nbytes, self._buffer_bytes)
            if self._size:
                want = min(want, self._size - self._pos)
            self._buf = self._fetch(self._pos, want)
            self._buf_start = self._pos
            off = 0
            if not self._buf:
                return b""
        out = self._buf[off:off + nbytes]
        self._pos += len(out)
        return out

    def write(self, data: bytes) -> None:
        log_fatal("http streams are read-only")

    def seek(self, pos: int) -> None:
        self._pos = pos

    def tell(self) -> int:
        return self._pos


class HTTPFileSystem(fsys.FileSystem):
    def _head(self, path: fsys.URI):
        secure = path.protocol == "https://"
        conn = (http.client.HTTPSConnection if secure
                else http.client.HTTPConnection)(path.host, timeout=60)
        try:
            conn.request("HEAD", path.name or "/")
            resp = conn.getresponse()
            resp.read()
            headers = {k.lower(): v for k, v in resp.getheaders()}
            return resp.status, headers, secure
        finally:
            conn.close()

    def get_path_info(self, path: fsys.URI) -> fsys.FileInfo:
        status, headers, _ = self._head(path)
        if status >= 400:
            raise FileNotFoundError(path.str())
        return fsys.FileInfo(path.copy(),
                             int(headers.get("content-length", 0)),
                             fsys.FileType.FILE)

    def list_directory(self, path: fsys.URI) -> List[fsys.FileInfo]:
        log_fatal("http filesystem does not support directory listing")

    def open(self, path: fsys.URI, mode: str) -> Stream:
        CHECK(mode == "r", "http streams are read-only")
        return self.open_for_read(path)

    def open_for_read(self, path: fsys.URI) -> SeekStream:
        status, headers, secure = self._head(path)
        if status >= 400:
            raise FileNotFoundError(path.str())
        return _HTTPReadStream(secure, path.host, path.name or "/",
                               int(headers.get("content-length", 0)),
                               headers.get("accept-ranges", "") == "bytes")


Registry.get("filesystem").add("http", HTTPFileSystem,
                               description="read-only http")
Registry.get("filesystem").add("https", HTTPFileSystem,
                               description="read-only https")
