"""Shared telemetry naming for filesystem backends.

One helper so s3/gs, azure, http and hdfs all emit the same
``dmlc_filesystem_*`` metric families with the same label shape (``fs`` =
protocol, ``op`` = request verb) — the per-backend clients call
:func:`note_request` once per remote round-trip and cannot drift apart in
naming.  Everything is a no-op while telemetry is disabled.
"""

from __future__ import annotations

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.telemetry import clock

__all__ = ["request_start", "note_request"]


def request_start() -> float:
    """Monotonic begin-of-request reading (0.0 while disabled — callers can
    pass it straight back to :func:`note_request` unconditionally)."""
    return clock.monotonic() if telemetry.enabled() else 0.0


def note_request(fs: str, op: str, start: float,
                 nread: int = 0, nwritten: int = 0) -> None:
    """Record one remote round-trip: latency histogram + byte counters."""
    if not telemetry.enabled():
        return
    if start:
        # a 0.0 start means telemetry was enabled mid-request: the latency
        # was never measured, so skip the sample rather than fabricate 0.0s
        telemetry.observe("dmlc_filesystem_request_seconds",
                          clock.elapsed(start), fs=fs, op=op)
    if nread:
        telemetry.count("dmlc_filesystem_read_bytes_total", nread, fs=fs)
    if nwritten:
        telemetry.count("dmlc_filesystem_write_bytes_total", nwritten, fs=fs)
