"""Sharded multi-file input: deterministic byte-range partitioning with
record-boundary realignment, plus threaded/cached/shuffled decorators.

This is the data-parallel heart of the reference — "distributed training" in
dmlc-core *is* this partition math (SURVEY.md §2.9). Capability parity with:

- ``InputSplitBase`` engine (src/io/input_split_base.{h,cc}): ';'-separated
  multi-file lists with regex glob expansion (ConvertToURIs .cc:95-146),
  cumulative size table, aligned partition math with record realignment at both
  shard edges (ResetPartition .cc:29-63), boundary-safe chunk reads with
  overflow carry (ReadChunk .cc:205-233);
- ``LineSplitter`` (src/io/line_split.cc), ``RecordIOSplitter``
  (src/io/recordio_split.cc), ``IndexedRecordIOSplitter``
  (src/io/indexed_recordio_split.cc), ``SingleFileSplit`` (stdin,
  src/io/single_file_split.h);
- ``ThreadedInputSplit`` double-buffered prefetch (src/io/threaded_input_split.h),
  ``CachedInputSplit`` epoch-cache (src/io/cached_input_split.h),
  ``InputSplitShuffle`` macro-shuffling (include/dmlc/input_split_shuffle.h);
- the factory (InputSplit::Create, src/io.cc:63-117).

The invariant that makes partitions disjoint and exhaustive: partition k covers
aligned byte range [k*nstep, (k+1)*nstep) of the *concatenated* file bytes,
with each edge moved forward to the next record head **within the file that
contains it** (file starts are always record heads, so realignment never
crosses a file boundary).

TPU mapping: per-host input sharding is exactly
``part_index=jax.process_index(), num_parts=jax.process_count()`` — see
:mod:`dmlc_core_tpu.bridge`.

Hot-loop note: record/boundary scans are numpy-vectorized here; the C++ native
core (dmlc_core_tpu/native) accelerates the same entry points when built.
"""

from __future__ import annotations

import ctypes
import os
import random
import re
import struct
import sys
from typing import Callable, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu.io import filesys as fsys
from dmlc_core_tpu.io import recordio as rio
from dmlc_core_tpu.io.stream import SeekStream, Stream
from dmlc_core_tpu.io.threadediter import ThreadedIter
from dmlc_core_tpu.io.uri_spec import URISpec
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ, CHECK_LT, CHECK_NE, log_warning

__all__ = [
    "InputSplit",
    "InputSplitBase",
    "LineSplitter",
    "NativeLineSplitter",
    "RecordIOSplitter",
    "IndexedRecordIOSplitter",
    "SingleFileSplit",
    "ThreadedInputSplit",
    "CachedInputSplit",
    "InputSplitShuffle",
    "create_input_split",
]

# default chunk buffer: 8 MB (reference kBufferSize = 2<<20 uint32 words,
# src/io/input_split_base.h:40)
DEFAULT_BUFFER_SIZE = 8 << 20


class ChunkCursor:
    """A consumer-side view over one chunk of bytes, advanced record by record
    (the reference's Chunk begin/end pointer pair)."""

    __slots__ = ("data", "pos")

    def __init__(self, data: bytes = b""):
        self.data = data
        self.pos = len(data) if not data else 0

    def exhausted(self) -> bool:
        return self.pos >= len(self.data)


class InputSplit:
    """Abstract record input split (reference include/dmlc/io.h:135-280)."""

    def before_first(self) -> None:
        raise NotImplementedError

    def next_record(self) -> Optional[memoryview]:
        """Next record as a zero-copy view (invalidated by the next call)."""
        raise NotImplementedError

    def next_chunk(self) -> Optional[bytes]:
        """Next chunk of whole records, for chunk-parallel parsing."""
        raise NotImplementedError

    def hint_chunk_size(self, chunk_size: int) -> None:
        pass

    def get_total_size(self) -> int:
        raise NotImplementedError

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __iter__(self):
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec

    # factory — see create_input_split below
    @staticmethod
    def create(uri: str, part_index: int, num_parts: int, type: str = "text",
               **kwargs) -> "InputSplit":
        return create_input_split(uri, part_index, num_parts, type, **kwargs)


def _convert_to_uris(fs: fsys.FileSystem, uri: str) -> List[fsys.URI]:
    """';'-list + regex-glob expansion (reference ConvertToURIs, .cc:95-146)."""
    expanded: List[fsys.URI] = []
    for token in uri.split(";"):
        if not token:
            continue
        path = fsys.URI(token)
        pos = path.name.rfind("/")
        if pos < 0 or pos + 1 == len(path.name):
            expanded.append(path)
            continue
        parent = path.copy()
        parent.name = path.name[:pos]
        try:
            dfiles = fs.list_directory(parent)
        except OSError:
            expanded.append(path)
            continue
        stripped_target = path.name.rstrip("/")
        exact = [f for f in dfiles if f.path.name.rstrip("/") == stripped_target]
        if exact:
            expanded.append(exact[0].path)
            continue
        # regex expansion against the directory listing
        try:
            pattern = re.compile(path.name)
        except re.error as exc:
            from dmlc_core_tpu.utils.logging import log_fatal
            log_fatal(f"bad regex {path.name!r}: {exc}")
        for f in dfiles:
            if f.type != fsys.FileType.FILE or f.size == 0:
                continue
            if pattern.fullmatch(f.path.name.rstrip("/")):
                expanded.append(f.path)
    return expanded


def _expand_input_files(fs: fsys.FileSystem, uri: str) -> List[fsys.FileInfo]:
    """Expanded, non-empty input files for a (possibly ;-listed/glob) URI."""
    files: List[fsys.FileInfo] = []
    for path in _convert_to_uris(fs, uri):
        info = fs.get_path_info(path)
        if info.type == fsys.FileType.DIRECTORY:
            for sub in fs.list_directory(info.path):
                if sub.size != 0 and sub.type == fsys.FileType.FILE:
                    files.append(sub)
        elif info.size != 0:
            files.append(info)
    CHECK_NE(len(files), 0,
             f"cannot find any files that match the URI pattern {uri!r}")
    return files


def _next_record_from_chunks(holder, fetch_chunk: Callable, extract: Callable
                             ) -> Optional[memoryview]:
    """Shared drain-cursor-else-refill loop; ``holder`` owns ``._cursor``."""
    while True:
        rec = extract(holder._cursor)
        if rec is not None:
            return rec
        chunk = fetch_chunk()
        if chunk is None:
            return None
        holder._cursor = ChunkCursor(chunk)


class InputSplitBase(InputSplit):
    """Byte-range sharding engine over a list of files."""

    def __init__(self, fs: fsys.FileSystem, uri: str, align_bytes: int):
        self._filesys = fs
        self._align = align_bytes
        self._files: List[fsys.FileInfo] = []
        self._init_input_file_info(uri)
        offsets = [0]
        for info in self._files:
            CHECK_EQ(info.size % align_bytes, 0,
                     f"file {info.path.str()} does not align by {align_bytes} bytes")
            offsets.append(offsets[-1] + info.size)
        self._file_offset = offsets
        self._fs: Optional[SeekStream] = None
        self._file_ptr = 0
        self._file_ptr_end = 0
        self._offset_begin = 0
        self._offset_end = 0
        self._offset_curr = 0
        self._overflow = b""
        self._buffer_size = DEFAULT_BUFFER_SIZE
        self._cursor = ChunkCursor()

    # -- file-list expansion (reference ConvertToURIs, .cc:95-146) -----------
    def _convert_to_uris(self, uri: str) -> List[fsys.URI]:
        return _convert_to_uris(self._filesys, uri)

    def _init_input_file_info(self, uri: str) -> None:
        self._files.extend(_expand_input_files(self._filesys, uri))

    # -- partition math (reference ResetPartition, .cc:29-63) ----------------
    def reset_partition(self, part_index: int, num_parts: int) -> None:
        ntotal = self._file_offset[-1]
        nstep = (ntotal + num_parts - 1) // num_parts
        nstep = ((nstep + self._align - 1) // self._align) * self._align
        self._offset_begin = min(nstep * part_index, ntotal)
        self._offset_end = min(nstep * (part_index + 1), ntotal)
        self._offset_curr = self._offset_begin
        if self._offset_begin == self._offset_end:
            self._cursor = ChunkCursor()
            self._overflow = b""
            return
        self._file_ptr = self._upper_bound(self._offset_begin)
        self._file_ptr_end = self._upper_bound(self._offset_end)
        self._close_fs()
        # realign the end edge to the next record head inside its file
        if self._offset_end != self._file_offset[self._file_ptr_end]:
            fs = self._filesys.open_for_read(self._files[self._file_ptr_end].path)
            fs.seek(self._offset_end - self._file_offset[self._file_ptr_end])
            self._offset_end += self.seek_record_begin(fs)
            fs.close()
        # realign the begin edge likewise
        self._fs = self._filesys.open_for_read(self._files[self._file_ptr].path)
        if self._offset_begin != self._file_offset[self._file_ptr]:
            self._fs.seek(self._offset_begin - self._file_offset[self._file_ptr])
            self._offset_begin += self.seek_record_begin(self._fs)
        self.before_first()

    def _upper_bound(self, offset: int) -> int:
        """Index of the file containing byte `offset` of the concatenation."""
        import bisect

        return bisect.bisect_right(self._file_offset, offset) - 1

    def before_first(self) -> None:
        if self._offset_begin >= self._offset_end:
            return
        fp = self._upper_bound(self._offset_begin)
        if self._fs is None or self._file_ptr != fp:
            self._close_fs()
            self._file_ptr = fp
            self._fs = self._filesys.open_for_read(self._files[fp].path)
        self._fs.seek(self._offset_begin - self._file_offset[self._file_ptr])
        self._offset_curr = self._offset_begin
        self._cursor = ChunkCursor()
        self._overflow = b""

    def get_total_size(self) -> int:
        return self._file_offset[-1]

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._buffer_size = max(chunk_size, self._buffer_size)

    def _close_fs(self) -> None:
        if self._fs is not None:
            self._fs.close()
            self._fs = None

    def close(self) -> None:
        self._close_fs()

    # -- reading (reference Read/ReadChunk, .cc:171-233) ---------------------
    def read(self, size: int) -> bytes:
        """Read up to `size` bytes of this partition, crossing file boundaries."""
        if self._offset_begin >= self._offset_end or self._fs is None:
            return b""
        size = min(size, self._offset_end - self._offset_curr)
        if size == 0:
            return b""
        out = bytearray()
        while len(out) < size:
            chunk = self._fs.read(size - len(out))
            if chunk:
                out.extend(chunk)
                self._offset_curr += len(chunk)
                continue
            CHECK_EQ(self._offset_curr, self._file_offset[self._file_ptr + 1],
                     "file offset not calculated correctly")
            if self._file_ptr + 1 >= len(self._files):
                break
            self._file_ptr += 1
            self._close_fs()
            self._fs = self._filesys.open_for_read(self._files[self._file_ptr].path)
        return bytes(out)

    def read_chunk(self, max_size: int) -> Optional[bytes]:
        """One chunk ending at a record boundary.

        Returns None at partition end; b"" when `max_size` is too small to hold
        one full record (caller grows the buffer — reference's *size=0 signal).
        """
        if max_size <= len(self._overflow):
            return b""
        head, self._overflow = self._overflow, b""
        data = head + self.read(max_size - len(head))
        if not data:
            return None
        if len(data) != max_size:
            return data  # partition tail: ends exactly at the realigned edge
        cut = self.find_last_record_begin(data)
        self._overflow = data[cut:]
        return data[:cut]

    def next_chunk_bytes(self) -> Optional[bytes]:
        """Next non-empty chunk, growing the buffer for oversized records
        (reference Chunk::Load, .cc:235-252)."""
        size = self._buffer_size
        while True:
            chunk = self.read_chunk(size)
            if chunk is None:
                return None
            if chunk == b"":
                size *= 2
                continue
            return chunk

    def next_chunk(self) -> Optional[bytes]:
        return self.next_chunk_bytes()

    def next_record(self) -> Optional[memoryview]:
        return _next_record_from_chunks(self, self.next_chunk_bytes,
                                        self.extract_next_record)

    # -- per-format hooks ----------------------------------------------------
    def seek_record_begin(self, fs: Stream) -> int:
        """Bytes to skip from the current position to the next record head."""
        raise NotImplementedError

    def find_last_record_begin(self, data: bytes) -> int:
        """Offset of the last record head in `data` (0 if none beyond start)."""
        raise NotImplementedError

    def extract_next_record(self, cursor: ChunkCursor) -> Optional[memoryview]:
        raise NotImplementedError


def _next_line_record(cursor: ChunkCursor) -> Optional[memoryview]:
    """Advance a cursor over a chunk of lines (reference line_split.cc:36-55)."""
    if cursor.exhausted():
        return None
    data, pos = cursor.data, cursor.pos
    ln = data.find(b"\n", pos)
    lr = data.find(b"\r", pos)
    if ln < 0:
        p = lr if lr >= 0 else len(data)
    elif lr < 0:
        p = ln
    else:
        p = min(ln, lr)
    rec = memoryview(data)[pos:p]
    # skip the newline run (reference line_split.cc:42-45)
    while p < len(data) and data[p] in (0x0A, 0x0D):
        p += 1
    cursor.pos = p
    return rec


class LineSplitter(InputSplitBase):
    """Record = line (reference src/io/line_split.cc)."""

    def __init__(self, fs: fsys.FileSystem, uri: str, part_index: int, num_parts: int):
        super().__init__(fs, uri, align_bytes=1)
        self.reset_partition(part_index, num_parts)

    def seek_record_begin(self, fs: Stream) -> int:
        # scan to the first end-of-line, then past the newline run
        # (reference line_split.cc:9-26); over-reading is fine because the
        # engine re-seeks before reading data.
        nstep = 0
        seen_eol = False
        while True:
            block = fs.read(4096)
            if not block:
                return nstep
            for b in block:
                if not seen_eol:
                    nstep += 1
                    if b in (0x0A, 0x0D):
                        seen_eol = True
                else:
                    if b in (0x0A, 0x0D):
                        nstep += 1
                    else:
                        return nstep

    def find_last_record_begin(self, data: bytes) -> int:
        n = max(data.rfind(b"\n"), data.rfind(b"\r"))
        return n + 1 if n > 0 else 0

    def extract_next_record(self, cursor: ChunkCursor) -> Optional[memoryview]:
        return _next_line_record(cursor)


def _next_recordio_record(cursor: ChunkCursor) -> Optional[memoryview]:
    """Advance a cursor over a chunk of RecordIO frames, reassembling escaped
    (multi-part) records (reference recordio.cc NextRecord)."""
    if cursor.exhausted():
        return None
    data = cursor.data
    CHECK(cursor.pos + 8 <= len(data), "invalid RecordIO format")
    magic, lrec = struct.unpack_from("<II", data, cursor.pos)
    CHECK_EQ(magic, rio.RECORDIO_MAGIC, "invalid RecordIO format")
    cflag, clen = rio.decode_flag(lrec), rio.decode_length(lrec)
    start = cursor.pos + 8
    cursor.pos = start + (((clen + 3) >> 2) << 2)
    CHECK(cursor.pos <= len(data), "invalid RecordIO format")
    if cflag == 0:
        return memoryview(data)[start:start + clen]
    CHECK_EQ(cflag, 1, "invalid RecordIO format")
    parts = [bytes(memoryview(data)[start:start + clen])]
    while cflag != 3:
        CHECK(cursor.pos + 8 <= len(data), "invalid RecordIO format")
        magic, lrec = struct.unpack_from("<II", data, cursor.pos)
        CHECK_EQ(magic, rio.RECORDIO_MAGIC, "invalid RecordIO format")
        cflag, clen = rio.decode_flag(lrec), rio.decode_length(lrec)
        start = cursor.pos + 8
        parts.append(rio._MAGIC_BYTES)
        parts.append(bytes(memoryview(data)[start:start + clen]))
        cursor.pos = start + (((clen + 3) >> 2) << 2)
    return memoryview(b"".join(parts))


class RecordIOSplitter(InputSplitBase):
    """Record = magic-framed RecordIO blob (reference src/io/recordio_split.cc)."""

    def __init__(self, fs: fsys.FileSystem, uri: str, part_index: int, num_parts: int):
        super().__init__(fs, uri, align_bytes=4)
        self.reset_partition(part_index, num_parts)

    def seek_record_begin(self, fs: Stream) -> int:
        # word-scan for magic followed by cflag 0/1 (reference recordio_split.cc:9-26)
        nstep = 0
        pending: bytes = b""
        saw_magic = False
        while True:
            block = pending + fs.read(4096)
            pending = b""
            if len(block) < 4:
                return nstep
            nwords = len(block) // 4
            words = np.frombuffer(block, dtype="<u4", count=nwords)
            i = 0
            while i < nwords:
                if saw_magic:
                    nstep += 4
                    cflag = rio.decode_flag(int(words[i]))
                    saw_magic = False
                    if cflag in (0, 1):
                        return nstep - 8
                    i += 1
                    continue
                if int(words[i]) == rio.RECORDIO_MAGIC:
                    nstep += 4
                    saw_magic = True
                    i += 1
                else:
                    nstep += 4
                    i += 1
            pending = block[nwords * 4:]

    def find_last_record_begin(self, data: bytes) -> int:
        nwords = len(data) // 4
        if nwords < 2:
            return 0
        words = np.frombuffer(data, dtype="<u4", count=nwords)
        cand = np.nonzero(words[:nwords - 1] == rio.RECORDIO_MAGIC)[0]
        flags = (words[cand + 1] >> 29) & 7
        cand = cand[(flags == 0) | (flags == 1)]
        cand = cand[cand > 0]
        return int(cand[-1]) * 4 if cand.size else 0

    def extract_next_record(self, cursor: ChunkCursor) -> Optional[memoryview]:
        return _next_recordio_record(cursor)


class IndexedRecordIOSplitter(RecordIOSplitter):
    """Index-file-driven record partitioning with optional shuffled batches
    (reference src/io/indexed_recordio_split.cc)."""

    KRAND_MAGIC = 111

    def __init__(self, fs: fsys.FileSystem, uri: str, index_uri: str,
                 part_index: int, num_parts: int, batch_size: int = 256,
                 shuffle: bool = False, seed: int = 0):
        InputSplitBase.__init__(self, fs, uri, align_bytes=4)
        self._shuffle = shuffle
        self._rng = random.Random(self.KRAND_MAGIC + seed)
        self._batch_size = batch_size
        self._index: List[Tuple[int, int]] = []  # (offset, size) per record batch head
        self._read_index_file(index_uri)
        self._permutation: List[int] = []
        self._current_index = 0
        self._index_begin = 0
        self._index_end = 0
        self._n_overflow = 0
        # native span reader: index policy (partitioning, shuffle) stays
        # here; the byte-moving + read-ahead runs in C++ when available.
        # _native_unavailable is permanent (remote fs / no library); a
        # mid-epoch plan abandonment just drops the reader — before_first
        # recreates it with a fresh plan
        self._span_reader = None
        self._span_adapter = None
        self._native_unavailable = False
        self._plan_batch = batch_size
        self._popped = 0
        self.reset_partition(part_index, num_parts)

    def _read_index_file(self, index_uri: str) -> None:
        paths = self._convert_to_uris(index_uri)
        CHECK_EQ(len(paths), 1, "IndexedRecordIOSplitter supports a single index file")
        stream = self._filesys.open_for_read(paths[0])
        text = stream.as_file().read().decode("utf-8")
        stream.close()
        offsets = sorted(int(tok.split()[1]) for tok in text.splitlines() if tok.strip())
        CHECK(len(offsets) > 0, "empty index file")
        total = self._file_offset[-1]
        for a, b in zip(offsets, offsets[1:] + [total]):
            self._index.append((a, b - a))
        # array mirror of _index for bulk plan construction (shuffle epochs
        # index one span per record; per-tuple Python loops would pay ~the
        # cost of a small read per epoch at millions of records)
        self._index_arr = np.asarray(self._index, dtype=np.int64)

    # record-count-based partitioning (reference .cc:12-41)
    def reset_partition(self, part_index: int, num_parts: int) -> None:
        ntotal = len(self._index)
        nstep = (ntotal + num_parts - 1) // num_parts
        if part_index * nstep >= ntotal:
            # empty partition: clear ALL cursor state (a previous partition's
            # index window / open stream / native plan must not replay)
            self._offset_begin = self._offset_end = 0
            self._index_begin = self._index_end = 0
            self._current_index = 0
            self._n_overflow = 0
            self._permutation = []
            self._cursor = ChunkCursor()
            self._close_fs()
            if self._span_reader is not None:
                self._span_reader.set_plan([], [], [])
                self._popped = 0
            return
        self._index_begin = part_index * nstep
        self._offset_begin = self._index[self._index_begin][0]
        if (part_index + 1) * nstep < ntotal:
            self._index_end = (part_index + 1) * nstep
            self._offset_end = self._index[self._index_end][0]
        else:
            self._index_end = ntotal
            self._offset_end = self._file_offset[-1]
        self._offset_curr = self._offset_begin
        self._file_ptr = self._upper_bound(self._offset_begin)
        self._file_ptr_end = self._upper_bound(self._offset_end)
        self._close_fs()
        self._fs = self._filesys.open_for_read(self._files[self._file_ptr].path)
        self._n_overflow = 0
        self.before_first()

    def before_first(self) -> None:
        if self._shuffle:
            self._permutation = list(range(self._index_begin, self._index_end))
            self._rng.shuffle(self._permutation)
            self._current_index = 0
        else:
            self._current_index = self._index_begin
        self._n_overflow = 0
        if self._offset_begin < self._offset_end:
            InputSplitBase.before_first(self)
        reader = self._native_reader()
        if reader is not None:
            # set_plan's native Invalidate() sentinel drops cached remote
            # streams + stale errors with the producer joined (race-free)
            offs, szs, counts = self._epoch_plan()
            reader.set_plan(offs, szs, counts)
            self._plan_batch = self._batch_size
            self._popped = 0

    # -- native span fast path ----------------------------------------------
    def _native_reader(self):
        """The C++ span reader, created on first use; non-local filesystems
        read through a _ReadAtAdapter callback (opt-in, same gate as the
        factory's native_ok)."""
        if self._native_unavailable:
            return None
        if self._span_reader is None:
            from dmlc_core_tpu import native_bridge

            if not native_bridge.lsplit_available():
                self._native_unavailable = True
                return None
            if (not isinstance(self._filesys, fsys.LocalFileSystem)
                    and os.environ.get("DMLC_TPU_NATIVE_REMOTE", "") != "1"):
                self._native_unavailable = True
                return None
            self._span_adapter = (
                None if isinstance(self._filesys, fsys.LocalFileSystem)
                else _ReadAtAdapter(self._filesys, self._files))
            self._span_reader = native_bridge.NativeSpanReader(
                [info.path.name for info in self._files],
                [info.size for info in self._files],
                read_at=self._span_adapter)
        return self._span_reader

    def _epoch_plan(self):
        """(offsets, sizes, batch counts) for one epoch of batch reads."""
        bs = self._batch_size
        if self._offset_begin >= self._offset_end:
            return [], [], []
        if self._shuffle:
            # one span per record, numpy-gathered from the index mirror
            perm = np.asarray(self._permutation, dtype=np.int64)
            spans = self._index_arr[perm]               # [n, 2] (off, size)
            n = len(perm)
            counts = np.full(-(-n // bs), bs, dtype=np.int64)
            if n % bs:
                counts[-1] = n % bs
            return spans[:, 0], spans[:, 1], counts
        # contiguous batches: one span per batch
        heads = np.arange(self._index_begin, self._index_end, bs,
                          dtype=np.int64)
        lasts = np.minimum(heads + bs, self._index_end)
        offs = self._index_arr[heads, 0]
        ends = np.where(lasts == self._index_end, self._offset_end,
                        self._index_arr[np.minimum(lasts,
                                                   len(self._index) - 1), 0])
        return offs, ends - offs, np.ones(len(heads), dtype=np.int64)

    def _resync_from_native(self) -> None:
        """Abandon the native plan (batch size changed mid-epoch): restore
        the Python cursor from the number of batches already delivered.
        The next before_first() recreates the reader with a fresh plan."""
        consumed = self._popped * self._plan_batch
        if self._shuffle:
            self._current_index = min(consumed, len(self._permutation))
        else:
            self._current_index = min(self._index_begin + consumed,
                                      self._index_end)
        self._n_overflow = 0
        if self._span_reader is not None:
            self._span_reader.close()
            self._span_reader = None
        if self._span_adapter is not None:
            self._span_adapter.close()
            self._span_adapter = None

    def _index_offset_end(self, idx: int) -> int:
        if idx < len(self._index):
            return self._index[idx][0]
        return self._file_offset[-1]

    def _seek_to(self, offset: int) -> None:
        fp = self._upper_bound(offset)
        if fp != self._file_ptr or self._fs is None:
            self._close_fs()
            self._file_ptr = fp
            self._fs = self._filesys.open_for_read(self._files[fp].path)
        self._fs.seek(offset - self._file_offset[fp])
        self._offset_curr = offset

    def _read_exact_span(self, offset: int, size: int) -> bytes:
        self._seek_to(offset)
        saved_end = self._offset_end
        self._offset_end = max(self._offset_end, offset + size)
        data = self.read(size)
        self._offset_end = saved_end
        return data

    def next_batch_bytes(self, n_records: int) -> Optional[bytes]:
        """Read the next `n_records` batch as one chunk (reference NextBatchEx)."""
        if self._span_reader is not None and not self._native_unavailable:
            if n_records == self._plan_batch and not self._n_overflow:
                try:
                    chunk = self._span_reader.next_chunk()
                except OSError as exc:
                    _raise_native_error(self._span_adapter, exc)
                if chunk is not None:
                    self._popped += 1
                return chunk
            self._resync_from_native()
        if self._shuffle:
            n = self._n_overflow if self._n_overflow else n_records
            parts: List[bytes] = []
            n_read = 0
            while n_read < n and self._current_index < len(self._permutation):
                off, size = self._index[self._permutation[self._current_index]]
                parts.append(self._read_exact_span(off, size))
                n_read += 1
                self._current_index += 1
            if n_read == 0:
                return None
            self._n_overflow = n - n_read
            return b"".join(parts)
        n = self._n_overflow if self._n_overflow else n_records
        last = min(self._current_index + n, self._index_end)
        self._n_overflow = self._current_index + n - last
        if last == self._current_index:
            return None
        begin_off = self._index[self._current_index][0]
        end_off = self._offset_end if last == self._index_end else self._index[last][0]
        size = end_off - begin_off
        self._current_index = last
        data = self._read_exact_span(begin_off, size)
        return data if data else None

    def next_chunk(self) -> Optional[bytes]:
        return self.next_batch_bytes(self._batch_size)

    def next_chunk_bytes(self) -> Optional[bytes]:
        return self.next_batch_bytes(self._batch_size)

    def next_batch(self, n_records: int) -> Optional[bytes]:
        return self.next_batch_bytes(n_records)

    def set_batch_size(self, batch_size: int) -> None:
        self._batch_size = batch_size

    def set_random_seed(self, seed: int) -> None:
        self._rng = random.Random(self.KRAND_MAGIC + seed)

    def close(self) -> None:
        if self._span_reader is not None:
            self._span_reader.close()
            self._span_reader = None
        if self._span_adapter is not None:
            self._span_adapter.close()
            self._span_adapter = None
        InputSplitBase.close(self)


class SingleFileSplit(InputSplit):
    """Line records from a single file or stdin, no partitioning
    (reference src/io/single_file_split.h:27-173)."""

    def __init__(self, uri: str):
        self._cursor = ChunkCursor()
        self._buffer_size = DEFAULT_BUFFER_SIZE
        self._eof = False
        # opened last: a constructor failure after the open would orphan
        # the fd (no caller ever holds the instance to close it)
        if uri in ("stdin", "-"):
            self._f = sys.stdin.buffer
            self._stdin = True
        else:
            self._f = open(uri, "rb")
            self._stdin = False

    def before_first(self) -> None:
        CHECK(not self._stdin, "cannot rewind stdin")
        self._f.seek(0)
        self._cursor = ChunkCursor()
        self._eof = False

    def get_total_size(self) -> int:
        if self._stdin:
            return 0
        import os

        return os.fstat(self._f.fileno()).st_size

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        CHECK_EQ(num_parts, 1, "SingleFileSplit does not support partitioning")
        self.before_first()

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._buffer_size = max(chunk_size, self._buffer_size)

    def next_chunk(self) -> Optional[bytes]:
        if self._eof:
            return None
        data = self._f.read(self._buffer_size)
        if not data:
            self._eof = True
            return None
        if data[-1:] not in (b"\n", b"\r"):
            # extend to the end of the line
            extra = bytearray()
            while True:
                c = self._f.read(1)
                if not c:
                    self._eof = True
                    break
                extra += c
                if c in (b"\n", b"\r"):
                    break
            data += bytes(extra)
        return data

    def next_record(self) -> Optional[memoryview]:
        while True:
            rec = LineSplitter.extract_next_record(self, self._cursor)  # type: ignore[arg-type]
            if rec is not None:
                return rec
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._cursor = ChunkCursor(chunk)

    def close(self) -> None:
        if not self._stdin:
            self._f.close()


class _ChunkProducer:
    """ThreadedIter producer yielding chunks from an InputSplitBase."""

    def __init__(self, base: InputSplitBase):
        self._base = base

    def before_first(self) -> None:
        self._base.before_first()

    def next(self, reuse):
        return self._base.next_chunk_bytes()


class ThreadedInputSplit(InputSplit):
    """Double-buffered read-ahead decorator (reference
    src/io/threaded_input_split.h:23-101; ThreadedIter capacity 2)."""

    def __init__(self, base: InputSplitBase):
        self._base = base
        self._iter: ThreadedIter = ThreadedIter(_ChunkProducer(base), max_capacity=2,
                                                name="split_chunk")
        self._cursor = ChunkCursor()

    def before_first(self) -> None:
        self._iter.before_first()
        self._cursor = ChunkCursor()

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._base.hint_chunk_size(chunk_size)

    def get_total_size(self) -> int:
        return self._base.get_total_size()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        # pause the producer, reshard, restart (reference threaded_input_split.h:55-60)
        self._iter.destroy()
        self._base.reset_partition(part_index, num_parts)
        self._iter = ThreadedIter(_ChunkProducer(self._base), max_capacity=2,
                                  name="split_chunk")
        self._cursor = ChunkCursor()

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[memoryview]:
        return _next_record_from_chunks(self, self._iter.next,
                                        self._base.extract_next_record)

    def close(self) -> None:
        self._iter.destroy()
        self._base.close()


class CachedInputSplit(InputSplit):
    """Epoch-1 streams from the source while teeing chunks into a local cache
    file; later epochs replay the cache (reference src/io/cached_input_split.h)."""

    def __init__(self, base: InputSplitBase, cache_file: str):
        self._base = base
        self._cache_file = cache_file
        self._cursor = ChunkCursor()
        self._preproc = True
        self._cache_fo = open(cache_file, "wb")
        try:
            self._iter = ThreadedIter(self._make_preproc_producer(),
                                      max_capacity=2, name="split_preproc")
        except BaseException:
            # a failed producer bring-up orphans the cache fd (and leaves a
            # zero-byte cache file): the caller never gets the instance,
            # so close() is unreachable
            self._cache_fo.close()
            raise

    def _make_preproc_producer(self):
        parent = self

        class _Producer:
            def before_first(self) -> None:
                parent._base.before_first()

            def next(self, reuse):
                chunk = parent._base.next_chunk_bytes()
                if chunk is None:
                    return None
                parent._cache_fo.write(struct.pack("<Q", len(chunk)))
                parent._cache_fo.write(chunk)
                return chunk

        return _Producer()

    def _make_cache_producer(self):
        parent = self

        class _Producer:
            def __init__(self) -> None:
                self._fi = open(parent._cache_file, "rb")

            def before_first(self) -> None:
                self._fi.seek(0)

            def next(self, reuse):
                header = self._fi.read(8)
                if len(header) < 8:
                    return None
                (size,) = struct.unpack("<Q", header)
                data = self._fi.read(size)
                CHECK_EQ(len(data), size, "corrupt cache file")
                return data

        return _Producer()

    def _finish_preproc(self) -> None:
        # drain the remaining chunks into the cache, then swap producers
        # (reference cached_input_split.h:63-86)
        while self._iter.next() is not None:
            pass
        self._iter.destroy()
        self._cache_fo.close()
        self._base.close()
        self._preproc = False
        self._iter = ThreadedIter(self._make_cache_producer(), max_capacity=2,
                                  name="split_cache")

    def before_first(self) -> None:
        if self._preproc:
            self._finish_preproc()
        else:
            self._iter.before_first()
        self._cursor = ChunkCursor()

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._base.hint_chunk_size(chunk_size)

    def get_total_size(self) -> int:
        return self._base.get_total_size()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        from dmlc_core_tpu.utils.logging import log_fatal

        log_fatal("CachedInputSplit does not support reset_partition; "
                  "recreate it with the new shard (cache files are per-part)")

    def next_chunk(self) -> Optional[bytes]:
        chunk = self._iter.next()
        if chunk is None and self._preproc:
            # first epoch exhausted: finalize cache so the next epoch replays it
            self._finish_preproc_tail()
        return chunk

    def _finish_preproc_tail(self) -> None:
        if self._preproc:
            self._iter.destroy()
            self._cache_fo.close()
            self._base.close()
            self._preproc = False
            self._iter = ThreadedIter(self._make_cache_producer(), max_capacity=2,
                                      name="split_cache")
            # leave the new iterator at end-of-epoch state: consume nothing; the
            # caller's before_first() rewinds it.
            while self._iter.next() is not None:
                pass

    def next_record(self) -> Optional[memoryview]:
        return _next_record_from_chunks(self, self.next_chunk,
                                        self._base.extract_next_record)

    def close(self) -> None:
        self._iter.destroy()
        if self._preproc:
            self._cache_fo.close()
        self._base.close()


class InputSplitShuffle(InputSplit):
    """Macro-shuffle: divide this rank's shard into N sub-parts and visit them
    in a reshuffled order each epoch (reference include/dmlc/input_split_shuffle.h)."""

    KRAND_MAGIC = 666

    def __init__(self, uri: str, part_index: int, num_parts: int, type: str,
                 num_shuffle_parts: int, shuffle_seed: int = 0):
        CHECK(num_shuffle_parts > 0, "number of shuffle parts must be positive")
        self._part_index = part_index
        self._num_parts = num_parts
        self._num_shuffle = num_shuffle_parts
        self._rng = random.Random(
            self.KRAND_MAGIC + part_index + num_parts + num_shuffle_parts + shuffle_seed)
        self._indexes = list(range(num_shuffle_parts))
        self._rng.shuffle(self._indexes)
        self._cur = 0
        idx = self._indexes[0] + part_index * num_shuffle_parts
        self._source = create_input_split(
            uri, idx, num_parts * num_shuffle_parts, type)

    @staticmethod
    def create(uri: str, part_index: int, num_parts: int, type: str,
               num_shuffle_parts: int, shuffle_seed: int = 0) -> InputSplit:
        return InputSplitShuffle(uri, part_index, num_parts, type,
                                 num_shuffle_parts, shuffle_seed)

    def _advance_subpart(self) -> bool:
        if self._cur == self._num_shuffle - 1:
            return False
        self._cur += 1
        idx = self._indexes[self._cur] + self._part_index * self._num_shuffle
        self._source.reset_partition(idx, self._num_parts * self._num_shuffle)
        return True

    def before_first(self) -> None:
        if self._num_shuffle > 1:
            self._rng.shuffle(self._indexes)
            idx = self._indexes[0] + self._part_index * self._num_shuffle
            self._source.reset_partition(idx, self._num_parts * self._num_shuffle)
            self._cur = 0
        else:
            self._source.before_first()

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._source.hint_chunk_size(chunk_size)

    def get_total_size(self) -> int:
        return self._source.get_total_size()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        CHECK_EQ(num_parts, self._num_parts, "num_parts is not consistent")
        self._part_index = part_index
        idx = self._indexes[0] + part_index * self._num_shuffle
        self._source.reset_partition(idx, num_parts * self._num_shuffle)
        self._cur = 0

    def next_record(self) -> Optional[memoryview]:
        while True:
            rec = self._source.next_record()
            if rec is not None:
                return rec
            if not self._advance_subpart():
                return None

    def next_chunk(self) -> Optional[bytes]:
        while True:
            chunk = self._source.next_chunk()
            if chunk is not None:
                return chunk
            if not self._advance_subpart():
                return None

    def close(self) -> None:
        self._source.close()


class _ReadAtAdapter:
    """Python half of the native engine's remote path: a READ_AT_FN-shaped
    callable serving (file_idx, offset, size) reads from any FileSystem's
    SeekStreams.  Runs on the native prefetch thread (ctypes takes the GIL
    per call); the first exception is parked on ``.error`` and surfaces as
    the stream error when the consumer next pops a chunk.

    Epoch boundaries arrive as an ``idx < 0`` sentinel call: the native
    engines issue it from ``Invalidate()`` strictly between joining the
    old producer and starting the new one, so dropping cached streams and
    forgetting a stale parked error here can never race an in-flight read
    (ADVICE r4: the old consumer-side reopen flag could clear ``.error``
    just before a dying read re-parked its dead-epoch exception)."""

    def __init__(self, fs: fsys.FileSystem, files):
        self._fs = fs
        self._files = files
        self._streams: dict = {}
        self._pos: dict = {}
        self.error: Optional[BaseException] = None

    def __call__(self, ctx, idx, offset, buf, size) -> int:
        try:
            if idx < 0:
                # invalidate sentinel (new epoch / replaced files): no
                # producer is alive, so teardown + error clear are race-free
                self._close_streams()
                self.error = None
                return 0
            stream = self._streams.get(idx)
            if stream is None:
                stream = self._fs.open_for_read(self._files[idx].path)
                self._streams[idx] = stream
                self._pos[idx] = 0
            if self._pos[idx] != offset:
                stream.seek(offset)
            data = stream.read(size)
            self._pos[idx] = offset + len(data)
            if data:
                ctypes.memmove(buf, data, len(data))
            return len(data)
        except BaseException as exc:  # noqa: BLE001 — ferried to the consumer
            self.error = exc
            return -1

    def _close_streams(self) -> None:
        for stream in self._streams.values():
            try:
                stream.close()
            except Exception:
                pass
        self._streams.clear()

    def close(self) -> None:
        """Final teardown — only call once the native producer is stopped
        (engine closed/drained)."""
        self._close_streams()


def _raise_native_error(adapter: Optional[_ReadAtAdapter],
                        exc: OSError) -> None:
    """Surface the Python-side exception that made the native reader fail,
    falling back to the native error text.  The parked error is consumed so
    a stale epoch's exception can never mask a later unrelated failure."""
    if adapter is not None and adapter.error is not None:
        err, adapter.error = adapter.error, None
        raise err
    raise exc


def _native_split_setup(fs: fsys.FileSystem, uri: str, format: str):
    """Shared NativeLineSplitter/NativeCachedSplitter construction: expand
    the file list exactly like the Python engine, check recordio alignment,
    pick the record extractor, and build the remote read-at adapter."""
    files = _expand_input_files(fs, uri)
    if format == "recordio":
        for info in files:
            CHECK_EQ(info.size % 4, 0,
                     f"file {info.path.str()} does not align by 4 bytes")
    extract = (_next_recordio_record if format == "recordio"
               else _next_line_record)
    adapter = (None if isinstance(fs, fsys.LocalFileSystem)
               else _ReadAtAdapter(fs, files))
    return files, extract, adapter


def _native_ring(adapter) -> int:
    """Native prefetch-ring depth: the classic double buffer locally, a
    deeper pre-posted ring on the remote callback path so one batched
    ``next_chunks`` crossing amortizes the Python↔C round-trip over
    everything the ring buffered (VERDICT item 6; ``DMLC_NATIVE_RING``
    overrides either default)."""
    from dmlc_core_tpu.param import get_env

    return max(2, get_env("DMLC_NATIVE_RING", int,
                          2 if adapter is None else 8))


class NativeLineSplitter(InputSplit):
    """C++ split engine with built-in prefetch (native/input_split.cc).

    Drop-in for ``ThreadedInputSplit(LineSplitter(...))`` (or the RecordIO
    equivalent, ``format="recordio"``): the chunk sharding/realignment loop
    AND the double-buffered read-ahead run natively (reference
    src/io/input_split_base.cc + line_split.cc/recordio_split.cc +
    threaded_input_split.h in one).  Local files are read with FILE*
    directly; any other filesystem routes its byte reads through a
    :class:`_ReadAtAdapter` callback, so remote URIs ride the same native
    hot path.  Selected by the factory whenever the native core is built.
    """

    def __init__(self, fs: fsys.FileSystem, uri: str, part_index: int,
                 num_parts: int, format: str = "line"):
        from dmlc_core_tpu import native_bridge

        files, self._extract, self._adapter = _native_split_setup(
            fs, uri, format)
        self._part, self._nparts = part_index, num_parts
        self._buffer_size = DEFAULT_BUFFER_SIZE
        self._native = native_bridge.NativeLineSplit(
            [info.path.name for info in files],
            [info.size for info in files], part_index, num_parts,
            buffer_size=self._buffer_size, format=format,
            read_at=self._adapter, ring=_native_ring(self._adapter))
        self._cursor = ChunkCursor()

    def before_first(self) -> None:
        # reset()'s native Invalidate() sentinel reopens remote streams and
        # clears stale adapter errors between producer join and restart
        self._native.reset(self._part, self._nparts)
        self._cursor = ChunkCursor()

    def hint_chunk_size(self, chunk_size: int) -> None:
        # like the Python engines: grows the chunk buffer in place without
        # disturbing the read position
        if chunk_size > self._buffer_size:
            self._buffer_size = chunk_size
            self._native.hint_chunk_size(chunk_size)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._part, self._nparts = part_index, num_parts
        self.before_first()

    def next_chunk(self) -> Optional[bytes]:
        try:
            return self._native.next_chunk()
        except OSError as exc:
            _raise_native_error(self._adapter, exc)

    def next_chunk_view(self):
        """Zero-copy ``(addr, len)`` chunk view, valid until the next call
        on this split (consumed in place by the native parsers)."""
        try:
            return self._native.next_chunk_view()
        except OSError as exc:
            _raise_native_error(self._adapter, exc)

    def next_record(self) -> Optional[memoryview]:
        return _next_record_from_chunks(self, self.next_chunk,
                                        self._extract)

    def get_total_size(self) -> int:
        return self._native.total_size()

    def close(self) -> None:
        self._native.close()
        if self._adapter is not None:
            self._adapter.close()


class NativeCachedSplitter(InputSplit):
    """Native cached split: epoch 1 streams the partition through the C++
    engine whose producer tees every chunk into a length-framed cache
    file; later epochs replay the cache with native read-ahead (reference
    src/io/cached_input_split.h:28-189 — both halves native, unlike the
    pure-Python :class:`CachedInputSplit`).  Works over local and remote
    sources (epoch 1 uses the same read-at callback path as
    :class:`NativeLineSplitter`; the cache itself is always local)."""

    def __init__(self, fs: fsys.FileSystem, uri: str, part_index: int,
                 num_parts: int, cache_file: str, format: str = "line"):
        from dmlc_core_tpu import native_bridge

        self._bridge = native_bridge
        files, self._extract, self._adapter = _native_split_setup(
            fs, uri, format)
        self._cache_file = cache_file
        self._native = native_bridge.NativeLineSplit(
            [info.path.name for info in files],
            [info.size for info in files], part_index, num_parts,
            format=format, read_at=self._adapter, cache_path=cache_file,
            ring=_native_ring(self._adapter))
        self._total = self._native.total_size()
        self._replay = None
        self._at_end = False   # replay exhausted (or just swapped in)
        self._cursor = ChunkCursor()

    def _swap_to_replay(self, at_end: bool) -> None:
        """Finish the preproc epoch (drain + close cache) and hand the
        chunk stream to the native replay engine."""
        try:
            self._native.finish_cache()
        except OSError as exc:
            _raise_native_error(self._adapter, exc)
        self._native.close()
        self._native = None
        if self._adapter is not None:
            self._adapter.close()
            self._adapter = None
        self._replay = self._bridge.NativeCacheReplay(self._cache_file)
        self._at_end = at_end

    def before_first(self) -> None:
        if self._replay is None:
            self._swap_to_replay(at_end=False)
        else:
            self._replay.reset()
            self._at_end = False
        self._cursor = ChunkCursor()

    def _next_chunk_impl(self, preproc_fetch, replay_fetch):
        if self._replay is None:
            try:
                chunk = preproc_fetch()
            except OSError as exc:
                _raise_native_error(self._adapter, exc)
            if chunk is None:
                # first epoch exhausted: finalize the cache; stay at end
                # until the caller's before_first() rewinds the replay
                self._swap_to_replay(at_end=True)
            return chunk
        if self._at_end:
            return None
        chunk = replay_fetch()
        if chunk is None:
            self._at_end = True
        return chunk

    def next_chunk(self) -> Optional[bytes]:
        return self._next_chunk_impl(
            lambda: self._native.next_chunk(),
            lambda: self._replay.next_chunk())

    def next_chunk_view(self):
        """Zero-copy ``(addr, len)`` chunk view, valid until the next call
        on this split."""
        return self._next_chunk_impl(
            lambda: self._native.next_chunk_view(),
            lambda: self._replay.next_chunk_view())

    def next_record(self) -> Optional[memoryview]:
        return _next_record_from_chunks(self, self.next_chunk,
                                        self._extract)

    def hint_chunk_size(self, chunk_size: int) -> None:
        if self._native is not None:
            self._native.hint_chunk_size(chunk_size)

    def get_total_size(self) -> int:
        return self._total

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        from dmlc_core_tpu.utils.logging import log_fatal

        log_fatal("NativeCachedSplitter does not support reset_partition; "
                  "recreate it with the new shard (cache files are per-part)")

    def close(self) -> None:
        if self._native is not None:
            self._native.close()
            self._native = None
        if self._adapter is not None:
            self._adapter.close()
            self._adapter = None
        if self._replay is not None:
            self._replay.close()
            self._replay = None


def create_input_split(
    uri: str,
    part_index: int,
    num_parts: int,
    type: str = "text",
    index_uri: Optional[str] = None,
    shuffle: bool = False,
    seed: int = 0,
    batch_size: int = 256,
    threaded: bool = True,
) -> InputSplit:
    """Factory (reference InputSplit::Create, src/io.cc:63-117).

    Supports the URI sugar ``path?k=v#cachefile``; "stdin" or "-" gives a
    :class:`SingleFileSplit`.  ``type`` is "text", "recordio", or
    "indexed_recordio" (requires ``index_uri``).
    """
    spec = URISpec(uri, part_index, num_parts)
    if spec.uri in ("stdin", "-"):
        return SingleFileSplit(spec.uri)
    CHECK_LT(part_index, num_parts, "invalid input parameters for create_input_split")
    path = fsys.URI(spec.uri)
    fs = fsys.get_filesystem(path)
    def native_ok() -> bool:
        # the native engine serves every filesystem: local files via FILE*,
        # anything else through the read-at callback (_ReadAtAdapter).
        # Local is the default fast path (measured: 2.7-4x on recordio/
        # indexed scans).  Remote defaults to the Python engines — on a
        # loopback store the callback's extra per-chunk copy measures
        # slower (385 vs 699 MB/s text; real networks are wire-bound so
        # both saturate) — and is opt-in via DMLC_TPU_NATIVE_REMOTE=1
        # (correctness held by tests/test_native_remote_cached.py).
        if not threaded:
            return False
        from dmlc_core_tpu import native_bridge

        if not native_bridge.lsplit_available():
            return False
        if isinstance(fs, fsys.LocalFileSystem):
            return True
        return os.environ.get("DMLC_TPU_NATIVE_REMOTE", "") == "1"

    if type == "text":
        if native_ok():
            if spec.cache_file:
                return NativeCachedSplitter(fs, spec.uri, part_index,
                                            num_parts, spec.cache_file)
            return NativeLineSplitter(fs, spec.uri, part_index, num_parts)
        split: InputSplitBase = LineSplitter(fs, spec.uri, part_index, num_parts)
    elif type == "recordio":
        if native_ok():
            if spec.cache_file:
                return NativeCachedSplitter(fs, spec.uri, part_index,
                                            num_parts, spec.cache_file,
                                            format="recordio")
            return NativeLineSplitter(fs, spec.uri, part_index, num_parts,
                                      format="recordio")
        split = RecordIOSplitter(fs, spec.uri, part_index, num_parts)
    elif type == "indexed_recordio":
        CHECK(index_uri is not None, "need an index file to use indexed_recordio")
        index_spec = URISpec(index_uri, part_index, num_parts)
        split = IndexedRecordIOSplitter(fs, spec.uri, index_spec.uri, part_index,
                                        num_parts, batch_size, shuffle, seed)
    else:
        from dmlc_core_tpu.utils.logging import log_fatal

        log_fatal(f"unknown input split type {type!r}")
    if spec.cache_file:
        return CachedInputSplit(split, spec.cache_file)
    if threaded:
        return ThreadedInputSplit(split)
    return split
