"""URI sugar: ``path?format=...&k=v#cachefile`` (reference src/io/uri_spec.h:29-77)."""

from __future__ import annotations

from typing import Dict

from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ

__all__ = ["URISpec"]


class URISpec:
    """Parse dmlc URI sugar.

    - ``#cachefile`` names a local cache; with ``num_parts != 1`` the cache
      path becomes ``<cache>.split<num_parts>.part<part_index>`` so each shard
      caches independently (reference uri_spec.h:48-55);
    - ``?k=v&k2=v2`` query args land in :attr:`args` (e.g. ``format=csv``,
      ``label_column=0`` consumed by the parser factory, reference
      src/data.cc:70-76).
    """

    def __init__(self, uri: str, part_index: int = 0, num_parts: int = 1):
        name_cache = uri.split("#")
        CHECK(len(name_cache) <= 2,
              "only one `#` is allowed in file path for cachefile specification")
        self.cache_file = ""
        if len(name_cache) == 2:
            self.cache_file = name_cache[1]
            if num_parts != 1:
                self.cache_file += f".split{num_parts}.part{part_index}"
        name_args = name_cache[0].split("?")
        CHECK(len(name_args) <= 2, "only one `?` is allowed in file path")
        self.args: Dict[str, str] = {}
        if len(name_args) == 2 and name_args[1]:
            for i, kv in enumerate(name_args[1].split("&")):
                CHECK_EQ(kv.count("="), 1,
                         f"invalid uri argument format in arg {i + 1}: {kv!r}")
                key, value = kv.split("=")
                self.args[key] = value
        self.uri = name_args[0]

    def __repr__(self) -> str:
        return f"URISpec(uri={self.uri!r}, args={self.args}, cache_file={self.cache_file!r})"
