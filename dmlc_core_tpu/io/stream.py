"""Abstract byte streams + typed read/write helpers.

Capability parity with the reference's ``dmlc::Stream``/``SeekStream``/
``Serializable`` (include/dmlc/io.h:29-126) and the iostream adapters
(io.h:295-419; in Python, :meth:`Stream.as_file` wraps a stream into a
file-like object).

Typed helpers use little-endian fixed-width layouts with ``uint64`` length
prefixes for strings/vectors, matching the reference serializer's on-disk
layout (include/dmlc/serializer.h POD + vector handlers) so that blobs written
by either side of the C++/Python boundary interoperate.
"""

from __future__ import annotations

import struct
from typing import Any, Optional

import numpy as np

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ

__all__ = [
    "Stream",
    "SeekStream",
    "Serializable",
    "create_stream",
    "create_stream_for_read",
]


class Stream:
    """Abstract byte stream (reference io.h:29-86)."""

    def read(self, nbytes: int) -> bytes:
        """Read up to ``nbytes``; b"" at end of stream."""
        raise NotImplementedError

    def write(self, data: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- exact-size reads ----------------------------------------------------
    def read_exact(self, nbytes: int) -> bytes:
        """Read exactly ``nbytes`` or raise (short read = corrupt input)."""
        chunks = []
        remaining = nbytes
        if fault.enabled():
            # an injected truncation models a cut object/dropped connection:
            # the stream "ends" early and the short-read CHECK below fires
            remaining = fault.truncate("io.stream.read", nbytes)
        while remaining > 0:
            chunk = self.read(remaining)
            if not chunk:
                break
            chunks.append(chunk)
            remaining -= len(chunk)
        data = b"".join(chunks)
        CHECK_EQ(len(data), nbytes, "short read: truncated stream")
        return data

    # -- typed scalar IO (reference io.h:71-85 Write<T>/Read<T>) -------------
    def write_scalar(self, value: Any, fmt: str) -> None:
        """Write one scalar with a struct format char, little-endian."""
        self.write(struct.pack("<" + fmt, value))

    def read_scalar(self, fmt: str) -> Any:
        size = struct.calcsize("<" + fmt)
        return struct.unpack("<" + fmt, self.read_exact(size))[0]

    def write_u32(self, v: int) -> None:
        self.write_scalar(v, "I")

    def read_u32(self) -> int:
        return self.read_scalar("I")

    def write_u64(self, v: int) -> None:
        self.write_scalar(v, "Q")

    def read_u64(self) -> int:
        return self.read_scalar("Q")

    def write_i64(self, v: int) -> None:
        self.write_scalar(v, "q")

    def read_i64(self) -> int:
        return self.read_scalar("q")

    def write_f64(self, v: float) -> None:
        self.write_scalar(v, "d")

    def read_f64(self) -> float:
        return self.read_scalar("d")

    # -- string / array IO ---------------------------------------------------
    def write_string(self, s: bytes | str) -> None:
        """uint64 length + raw bytes (reference serializer string layout)."""
        if isinstance(s, str):
            s = s.encode("utf-8")
        self.write_u64(len(s))
        self.write(s)

    def read_string(self) -> bytes:
        n = self.read_u64()
        return self.read_exact(n)

    def write_array(self, arr: np.ndarray) -> None:
        """uint64 element count + raw little-endian POD data (vector<T>
        layout).  LE is pinned regardless of host order (reference
        include/dmlc/endian.h contract); on LE hosts the astype is a
        no-copy no-op."""
        arr = np.ascontiguousarray(arr)
        CHECK(arr.dtype.kind in "iuf", f"write_array: non-POD dtype {arr.dtype}")
        self.write_u64(arr.size)
        self.write(arr.astype(arr.dtype.newbyteorder("<"),
                              copy=False).tobytes())

    def read_array(self, dtype: np.dtype) -> np.ndarray:
        dtype = np.dtype(dtype)
        n = self.read_u64()
        data = self.read_exact(n * dtype.itemsize)
        # bytes on the wire are LE; hand back the caller's native dtype
        return (np.frombuffer(data, dtype=dtype.newbyteorder("<"))
                .astype(dtype, copy=False).copy())

    # -- adapters -------------------------------------------------------------
    def as_file(self) -> "_StreamFile":
        """File-like wrapper (the reference's dmlc::ostream/istream, io.h:295-419)."""
        return _StreamFile(self)


class SeekStream(Stream):
    """Stream with random access (reference io.h:89-109)."""

    def seek(self, pos: int) -> None:
        raise NotImplementedError

    def tell(self) -> int:
        raise NotImplementedError


class Serializable:
    """Objects that save/load onto a Stream (reference io.h:112-126).

    This is the checkpoint contract: "checkpoint = save to any URI" — the
    TPU-side counterpart for jax pytrees lives in
    :mod:`dmlc_core_tpu.bridge.checkpoint`.
    """

    def save(self, stream: Stream) -> None:
        raise NotImplementedError

    def load(self, stream: Stream) -> None:
        raise NotImplementedError


class _StreamFile:
    """Minimal file-object adapter over a Stream."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self._readbuf = b""

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            chunks = [self._readbuf]
            self._readbuf = b""
            while True:
                chunk = self._stream.read(1 << 20)
                if not chunk:
                    break
                chunks.append(chunk)
            return b"".join(chunks)
        out = self._readbuf[:n]
        self._readbuf = self._readbuf[n:]
        while len(out) < n:
            chunk = self._stream.read(n - len(out))
            if not chunk:
                break
            out += chunk
        return out

    def readline(self) -> bytes:
        while b"\n" not in self._readbuf:
            chunk = self._stream.read(1 << 16)
            if not chunk:
                out, self._readbuf = self._readbuf, b""
                return out
            self._readbuf += chunk
        idx = self._readbuf.index(b"\n") + 1
        out, self._readbuf = self._readbuf[:idx], self._readbuf[idx:]
        return out

    def __iter__(self):
        while True:
            line = self.readline()
            if not line:
                return
            yield line

    def write(self, data: bytes) -> int:
        self._stream.write(data)
        return len(data)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._stream.close()


def create_stream(uri: str, mode: str, allow_null: bool = False) -> Optional[Stream]:
    """URI-dispatched stream factory (reference Stream::Create, src/io.cc:119-125).

    ``mode`` is "r"/"w"/"a".  Dispatch by URI protocol is handled by
    :func:`dmlc_core_tpu.io.filesys.get_filesystem`.
    """
    from dmlc_core_tpu.io import filesys

    CHECK(mode in ("r", "w", "a"), f"invalid stream mode {mode!r}")
    uri_obj = filesys.URI(uri)
    fs = filesys.get_filesystem(uri_obj)
    try:
        with telemetry.span("io.stream.open",
                            protocol=uri_obj.protocol or "file://",
                            mode=mode):
            if fault.enabled():
                fault.inject("io.stream.open", uri=uri, mode=mode)
            return fs.open(uri_obj, mode)
    except (OSError, IOError):
        if allow_null:
            return None
        raise


def create_stream_for_read(uri: str, allow_null: bool = False) -> Optional[SeekStream]:
    """Seekable read stream (reference SeekStream::CreateForRead, io.h:107-108)."""
    from dmlc_core_tpu.io import filesys

    uri_obj = filesys.URI(uri)
    fs = filesys.get_filesystem(uri_obj)
    try:
        with telemetry.span("io.stream.open",
                            protocol=uri_obj.protocol or "file://",
                            mode="r"):
            if fault.enabled():
                fault.inject("io.stream.open", uri=uri, mode="r")
            return fs.open_for_read(uri_obj)
    except (OSError, IOError):
        if allow_null:
            return None
        raise
