"""Any-URI filesystem CLI (the reference's filesys_test harness as an
operator tool, test/filesys_test.cc:8-40):

    python -m dmlc_core_tpu.io ls  <uri>
    python -m dmlc_core_tpu.io cat <uri>
    python -m dmlc_core_tpu.io cp  <src-uri> <dst-uri>

Works across every registered protocol (file/s3/gs/azure/hdfs/http) and
honors the same environment credential contract as the library
(AWS_ACCESS_KEY_ID/..., AZURE_STORAGE_*, S3_ENDPOINT, etc.) — this is the
one-command smoke tool for poking a real bucket/namenode the moment an
endpoint is reachable.
"""

import sys

from dmlc_core_tpu.io.filesys import URI, FileType, get_filesystem
from dmlc_core_tpu.io.stream import create_stream, create_stream_for_read

USAGE = __doc__

CHUNK = 4 << 20


def cmd_ls(uri: str) -> int:
    fs = get_filesystem(URI(uri))
    infos = fs.list_directory(URI(uri))
    for info in infos:
        marker = "/" if info.type == FileType.DIRECTORY else ""
        print(f"{info.size:>16}  {info.path.str()}{marker}")
    print(f"{len(infos)} entries", file=sys.stderr)
    return 0


def cmd_cat(uri: str) -> int:
    src = create_stream_for_read(uri)
    out = sys.stdout.buffer
    total = 0
    while True:
        data = src.read(CHUNK)
        if not data:
            break
        out.write(data)
        total += len(data)
    out.flush()
    print(f"{total} bytes", file=sys.stderr)
    return 0


def cmd_cp(src_uri: str, dst_uri: str) -> int:
    src = create_stream_for_read(src_uri)
    try:
        dst = create_stream(dst_uri, "w")
        total = 0
        try:
            while True:
                data = src.read(CHUNK)
                if not data:
                    break
                dst.write(data)
                total += len(data)
        except BaseException:
            # do NOT commit a truncated destination: closing a half-written
            # remote stream would finalize the upload and leave an object
            # that looks complete.  Best effort: remove a local partial;
            # for remote targets say so explicitly.
            _discard_partial_dest(dst, dst_uri)
            raise
        dst.close()
    finally:
        src.close()
    print(f"copied {total} bytes {src_uri} -> {dst_uri}", file=sys.stderr)
    return 0


def _discard_partial_dest(dst, dst_uri: str) -> None:
    import os

    if "://" not in dst_uri or dst_uri.startswith("file://"):
        path = dst_uri[len("file://"):] if dst_uri.startswith("file://") \
            else dst_uri
        try:
            dst.close()
        except Exception:
            pass
        try:
            os.remove(path)
        except OSError:
            pass
    else:
        print(f"warning: copy failed mid-stream; a partial object may "
              f"remain at {dst_uri}", file=sys.stderr)


def main(argv) -> int:
    if len(argv) < 2:
        print(USAGE, file=sys.stderr)
        return 2
    cmd, args = argv[0], argv[1:]
    try:
        if cmd == "ls" and len(args) == 1:
            return cmd_ls(args[0])
        if cmd == "cat" and len(args) == 1:
            return cmd_cat(args[0])
        if cmd == "cp" and len(args) == 2:
            return cmd_cp(args[0], args[1])
    except Exception as e:  # noqa: BLE001 — operator tool: message, not trace
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(USAGE, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
