"""AWS Signature Version 4 signing (pure stdlib).

The reference signs S3 requests with SigV2 HMAC-SHA1 over libcurl
(src/io/s3_filesys.cc:86-121); the rebuild uses SigV4 (required by all
post-2014 AWS regions and by GCS's S3-compatible XML API) implemented on
hashlib/hmac — no SDK, keeping the zero-dependency stance of the reference.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
from typing import Dict, Optional, Tuple

__all__ = ["sign_request", "Credentials"]


class Credentials:
    def __init__(self, access_key: str, secret_key: str,
                 session_token: Optional[str] = None, region: str = "us-east-1"):
        self.access_key = access_key
        self.secret_key = secret_key
        self.session_token = session_token
        self.region = region


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _quote(s: str, safe: str = "-_.~") -> str:
    return urllib.parse.quote(s, safe=safe)


def sign_request(
    creds: Credentials,
    method: str,
    host: str,
    path: str,
    query: Dict[str, str],
    headers: Dict[str, str],
    payload_sha256: str,
    service: str = "s3",
    now: Optional[datetime.datetime] = None,
) -> Dict[str, str]:
    """Return headers with SigV4 Authorization added.

    ``payload_sha256`` is the hex sha256 of the body ("UNSIGNED-PAYLOAD" is
    also accepted by S3 for streaming).
    """
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    out = dict(headers)
    out["host"] = host
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_sha256
    if creds.session_token:
        out["x-amz-security-token"] = creds.session_token

    canon_uri = _quote(path, safe="/-_.~")
    canon_query = "&".join(
        f"{_quote(k)}={_quote(str(v))}" for k, v in sorted(query.items()))
    signed_names = sorted(k.lower() for k in out)
    canon_headers = "".join(
        f"{name}:{str(out[_orig(out, name)]).strip()}\n" for name in signed_names)
    signed_headers = ";".join(signed_names)
    canonical_request = "\n".join([
        method.upper(), canon_uri, canon_query, canon_headers, signed_headers,
        payload_sha256,
    ])
    scope = f"{datestamp}/{creds.region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    k = _hmac(b"AWS4" + creds.secret_key.encode(), datestamp)
    k = _hmac(k, creds.region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={creds.access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}")
    return out


def _orig(headers: Dict[str, str], lower_name: str) -> str:
    for k in headers:
        if k.lower() == lower_name:
            return k
    return lower_name
