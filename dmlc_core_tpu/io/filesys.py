"""URI parsing + virtual filesystem dispatch + local filesystem.

Capability parity with the reference's src/io/filesys.h:18-118 (``URI``,
``FileInfo``, ``FileSystem``) and src/io.cc:31-60 (protocol dispatch), plus the
local implementation (src/io/local_filesys.{h,cc}).

Filesystems register themselves in the ``"filesystem"`` registry keyed by
protocol (``file``, ``s3``, ``gs``, ``http`` ...), so remote backends plug in
without touching this module (the reference gates them with compile-time
DMLC_USE_* flags; we gate at import/registration time).
"""

from __future__ import annotations

import enum
import os
import stat as statmod
import sys
from typing import List

from dmlc_core_tpu.registry import Registry
from dmlc_core_tpu.io.stream import SeekStream, Stream
from dmlc_core_tpu.utils.logging import CHECK

__all__ = [
    "URI",
    "FileInfo",
    "FileType",
    "FileSystem",
    "get_filesystem",
    "LocalFileSystem",
]


class URI:
    """``protocol://host/path`` split (reference filesys.h:18-52).

    - no ``://`` -> protocol is ``file://``, whole string is the name;
    - otherwise host is the segment before the next '/', name the remainder
      (for ``file://`` the host is empty and the name absolute).
    """

    def __init__(self, uri: str = ""):
        self.protocol = ""
        self.host = ""
        self.name = ""
        if not uri:
            return
        idx = uri.find("://")
        if idx < 0:
            self.protocol = "file://"
            self.name = uri
        else:
            self.protocol = uri[: idx + 3]
            rest = uri[idx + 3:]
            slash = rest.find("/")
            if slash < 0:
                self.host, self.name = rest, ""
            else:
                self.host, self.name = rest[:slash], rest[slash:]
            if self.protocol == "file://":
                # file://host is not meaningful; treat everything as the path
                self.name = rest if not rest.startswith("/") else rest
                self.host = ""

    def str(self) -> str:
        if self.protocol in ("", "file://"):
            return self.name
        return f"{self.protocol}{self.host}{self.name}"

    def __str__(self) -> str:
        return self.str()

    def __repr__(self) -> str:
        return f"URI({self.str()!r})"

    def copy(self) -> "URI":
        out = URI()
        out.protocol, out.host, out.name = self.protocol, self.host, self.name
        return out


class FileType(enum.Enum):
    FILE = 0
    DIRECTORY = 1


class FileInfo:
    """Metadata for one path (reference filesys.h:63-72)."""

    def __init__(self, path: URI, size: int = 0, type: FileType = FileType.FILE):
        self.path = path
        self.size = size
        self.type = type

    def __repr__(self) -> str:
        return f"FileInfo({self.path.str()!r}, size={self.size}, type={self.type.name})"


class FileSystem:
    """Abstract filesystem (reference filesys.h:75-118)."""

    def get_path_info(self, path: URI) -> FileInfo:
        raise NotImplementedError

    def list_directory(self, path: URI) -> List[FileInfo]:
        raise NotImplementedError

    def open(self, path: URI, mode: str) -> Stream:
        """Open for "r"/"w"/"a"."""
        raise NotImplementedError

    def open_for_read(self, path: URI) -> SeekStream:
        raise NotImplementedError


_fs_registry = Registry.get("filesystem")


def get_filesystem(uri: URI) -> FileSystem:
    """Protocol dispatch (reference FileSystem::GetInstance, src/io.cc:31-60)."""
    proto = uri.protocol or "file://"
    key = proto[:-3] if proto.endswith("://") else proto
    entry = _fs_registry.find(key)
    CHECK(entry is not None,
          f"unknown filesystem protocol {proto!r}; known: {_fs_registry.list_names()}. "
          f"(remote backends such as hdfs:// must be enabled/registered first)")
    return entry()


class _LocalFileStream(SeekStream):
    """stdio-backed stream (reference local_filesys.cc:28-60)."""

    def __init__(self, fileobj, seekable: bool = True):
        self._f = fileobj
        self._seekable = seekable

    def read(self, nbytes: int) -> bytes:
        return self._f.read(nbytes)

    def write(self, data: bytes) -> None:
        self._f.write(data)

    def seek(self, pos: int) -> None:
        CHECK(self._seekable, "stream is not seekable")
        self._f.seek(pos)

    def tell(self) -> int:
        return self._f.tell()

    def close(self) -> None:
        if self._f not in (getattr(sys.stdin, "buffer", None),
                           getattr(sys.stdout, "buffer", None)):
            self._f.close()


class LocalFileSystem(FileSystem):
    """Local disk implementation (reference src/io/local_filesys.cc:28-160)."""

    _instance: "LocalFileSystem" = None

    def __new__(cls) -> "LocalFileSystem":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def get_path_info(self, path: URI) -> FileInfo:
        st = os.stat(path.name)
        ftype = FileType.DIRECTORY if statmod.S_ISDIR(st.st_mode) else FileType.FILE
        return FileInfo(path.copy(), size=st.st_size, type=ftype)

    def list_directory(self, path: URI) -> List[FileInfo]:
        out: List[FileInfo] = []
        base = path.name
        for entry in sorted(os.scandir(base), key=lambda e: e.name):
            sub = path.copy()
            sub.name = os.path.join(base, entry.name)
            st = entry.stat()
            ftype = FileType.DIRECTORY if entry.is_dir() else FileType.FILE
            out.append(FileInfo(sub, size=st.st_size, type=ftype))
        return out

    def open(self, path: URI, mode: str) -> Stream:
        CHECK(mode in ("r", "w", "a"), f"invalid mode {mode!r}")
        # '-' means stdin/stdout (reference local_filesys.cc:129-150)
        if path.name == "-":
            if mode == "r":
                return _LocalFileStream(sys.stdin.buffer, seekable=False)
            return _LocalFileStream(sys.stdout.buffer, seekable=False)
        return _LocalFileStream(open(path.name, mode + "b"))

    def open_for_read(self, path: URI) -> SeekStream:
        return _LocalFileStream(open(path.name, "rb"))

    def delete(self, path: URI) -> None:
        os.unlink(path.name)


_fs_registry.add("file", LocalFileSystem, description="local disk (default protocol)")
