"""Standard-file adapter over the ranged-read FS layer.

The remote page-cache fetch (data/page_cache.py `_open_remote_layout`)
taught the FS layer open-by-footer discipline: `get_path_info` for the
object size, then seek+read spans through one `open_for_read` stream.
Columnar consumers (the Parquet footer/row-group reader) need the same
capability but through the *standard* Python file protocol — relative
`seek(offset, whence)`, `tell`, `read(-1)`, `closed` — because pyarrow
drives the file object itself (footer last, then per-row-group column
chunk ranges).

:class:`RangedReadFile` is that adapter: size learned once up front, every
read a bounded ranged read on the underlying seekable stream, nothing
buffered beyond what the FS stream itself buffers.  It works over any
registered filesystem (s3/http/azure/hdfs/file), so a remote Parquet
source costs exactly footer + touched row groups — never a whole-object
download.
"""

from __future__ import annotations

from dmlc_core_tpu import telemetry

__all__ = ["RangedReadFile"]


class RangedReadFile:
    """Read-only, seekable file object over ``fs.open_for_read(uri)``.

    Implements the subset of the io protocol random-access consumers
    (``pyarrow.parquet.ParquetFile``, zipfile, …) drive: ``read``/``seek``
    (all three whences)/``tell``/``close``/``closed``/``readable``/
    ``seekable`` plus ``size()``.  Reads past EOF return short/empty bytes
    like a regular file, never raise.
    """

    def __init__(self, uri: str):
        from dmlc_core_tpu.io import filesys as fsys

        self._uri = uri
        uri_obj = fsys.URI(uri)
        fs = fsys.get_filesystem(uri_obj)
        self._size = fs.get_path_info(uri_obj).size  # FileNotFoundError here
        self._stream = fs.open_for_read(uri_obj)
        self._pos = 0
        self._closed = False

    # -- io protocol ----------------------------------------------------------
    def read(self, nbytes: int = -1) -> bytes:
        self._check_open()
        if nbytes is None or nbytes < 0:
            nbytes = self._size - self._pos
        nbytes = max(0, min(nbytes, self._size - self._pos))
        if nbytes == 0:
            return b""
        self._stream.seek(self._pos)
        chunks = []
        remaining = nbytes
        while remaining > 0:
            chunk = self._stream.read(remaining)
            if not chunk:
                break
            chunks.append(chunk)
            remaining -= len(chunk)
        data = b"".join(chunks)
        self._pos += len(data)
        telemetry.count("dmlc_ranged_file_read_bytes_total", len(data))
        return data

    def seek(self, offset: int, whence: int = 0) -> int:
        self._check_open()
        if whence == 0:
            pos = offset
        elif whence == 1:
            pos = self._pos + offset
        elif whence == 2:
            pos = self._size + offset
        else:
            raise ValueError(f"invalid whence: {whence}")
        if pos < 0:
            raise OSError(f"negative seek position {pos}")
        self._pos = pos
        return self._pos

    def tell(self) -> int:
        self._check_open()
        return self._pos

    def size(self) -> int:
        return self._size

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def flush(self) -> None:
        pass

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._stream.close()

    def __enter__(self) -> "RangedReadFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError(f"I/O operation on closed file {self._uri!r}")
