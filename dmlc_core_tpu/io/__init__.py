"""IO layer: streams, URI-dispatched filesystems, RecordIO, InputSplit, ThreadedIter.

Reference: include/dmlc/io.h, include/dmlc/recordio.h, include/dmlc/threadediter.h,
src/io/ (the compiled virtual-filesystem + sharded-input engine).
"""

from dmlc_core_tpu.io.stream import (  # noqa: F401
    Stream,
    SeekStream,
    Serializable,
    create_stream,
    create_stream_for_read,
)
from dmlc_core_tpu.io.memory_io import MemoryFixedSizeStream, MemoryStringStream  # noqa: F401
from dmlc_core_tpu.io.filesys import URI, FileInfo, FileSystem, FileType  # noqa: F401
from dmlc_core_tpu.io.recordio import (  # noqa: F401
    RECORDIO_MAGIC,
    RecordIOWriter,
    RecordIOReader,
    RecordIOChunkReader,
)
from dmlc_core_tpu.io.threadediter import ThreadedIter  # noqa: F401
from dmlc_core_tpu.io.input_split import InputSplit, create_input_split  # noqa: F401
from dmlc_core_tpu.io.uri_spec import URISpec  # noqa: F401

# remote filesystems register themselves on import (the reference gates these
# with DMLC_USE_S3/HDFS compile flags; here the gate is import/credential time)
from dmlc_core_tpu.io import s3_filesys as _s3  # noqa: F401,E402
from dmlc_core_tpu.io import http_filesys as _http  # noqa: F401,E402
from dmlc_core_tpu.io import hdfs_filesys as _hdfs  # noqa: F401,E402
from dmlc_core_tpu.io import azure_filesys as _azure  # noqa: F401,E402
