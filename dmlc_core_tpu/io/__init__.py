"""IO layer: streams, URI-dispatched filesystems, RecordIO, InputSplit, ThreadedIter.

Reference: include/dmlc/io.h, include/dmlc/recordio.h, include/dmlc/threadediter.h,
src/io/ (the compiled virtual-filesystem + sharded-input engine).
"""

from dmlc_core_tpu.io.stream import (  # noqa: F401
    Stream,
    SeekStream,
    Serializable,
    create_stream,
    create_stream_for_read,
)
from dmlc_core_tpu.io.memory_io import MemoryFixedSizeStream, MemoryStringStream  # noqa: F401
from dmlc_core_tpu.io.filesys import URI, FileInfo, FileSystem, FileType  # noqa: F401
from dmlc_core_tpu.io.recordio import (  # noqa: F401
    RECORDIO_MAGIC,
    RecordIOWriter,
    RecordIOReader,
    RecordIOChunkReader,
)
from dmlc_core_tpu.io.threadediter import ThreadedIter  # noqa: F401
from dmlc_core_tpu.io.input_split import InputSplit, create_input_split  # noqa: F401
from dmlc_core_tpu.io.uri_spec import URISpec  # noqa: F401
