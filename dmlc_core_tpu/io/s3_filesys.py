"""S3 (and S3-compatible) filesystem: buffered range-GET reads, multipart
uploads, ListObjectsV2 — over http.client with SigV4 signing.

Capability parity with the reference's src/io/s3_filesys.{h,cc} (1.1k LoC of
libcurl state machine):

- :class:`S3ReadStream` — seekable buffered reads via ranged GETs
  (CURLReadStreamBase::FillBuffer, s3_filesys.cc:392+);
- :class:`S3WriteStream` — multipart upload: parts buffered to
  ``DMLC_S3_WRITE_BUFFER_MB`` (default 64, reference s3_filesys.cc:560) and
  PUT on overflow; completion XML POSTed on close (s3_filesys.cc:551-798);
  small objects fall back to a single PUT;
- list/stat via ListObjectsV2 + HEAD (ListObjects, s3_filesys.cc:801+);
- credentials/region from the same env contract (AWS_ACCESS_KEY_ID,
  AWS_SECRET_ACCESS_KEY, AWS_SESSION_TOKEN, AWS_REGION, s3_filesys.cc:890-918),
  plus ``S3_ENDPOINT`` / ``S3_VERIFY_SSL`` overrides for S3-compatible stores
  and test servers.

GCS rides the same engine through its S3-interoperability XML API — see
:class:`GCSFileSystem`.
"""

from __future__ import annotations

import hashlib
import http.client
import os
import ssl
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from dmlc_core_tpu.io import filesys as fsys
from dmlc_core_tpu.io import fs_metrics
from dmlc_core_tpu.io.aws_sig import Credentials, sign_request
from dmlc_core_tpu.io.net_retry import request_with_retries
from dmlc_core_tpu.io.stream import SeekStream, Stream
from dmlc_core_tpu.param import get_env
from dmlc_core_tpu.registry import Registry
from dmlc_core_tpu.utils.logging import CHECK, log_fatal

__all__ = ["S3FileSystem", "GCSFileSystem"]

_EMPTY_SHA = hashlib.sha256(b"").hexdigest()

# metadata key marking which multipart upload produced an object (see
# S3WriteStream._init_multipart)
_TOKEN_HEADER = "x-amz-meta-dmlc-write-token"


class _S3Client:
    """One bucket-scoped signed HTTP client."""

    def __init__(self, bucket: str, env_prefix: str = "AWS",
                 default_endpoint: Optional[str] = None, service: str = "s3"):
        self.bucket = bucket
        key_id = (os.environ.get(f"{env_prefix}_ACCESS_KEY_ID")
                  or os.environ.get("AWS_ACCESS_KEY_ID"))
        secret = (os.environ.get(f"{env_prefix}_SECRET_ACCESS_KEY")
                  or os.environ.get("AWS_SECRET_ACCESS_KEY"))
        if not key_id or not secret:
            log_fatal(
                f"Need {env_prefix}_ACCESS_KEY_ID/{env_prefix}_SECRET_ACCESS_KEY "
                f"(or AWS_*) in the environment to access {service}://{bucket}")
        region = (os.environ.get("AWS_REGION")
                  or os.environ.get("AWS_DEFAULT_REGION") or "us-east-1")
        self.creds = Credentials(key_id, secret,
                                 os.environ.get("AWS_SESSION_TOKEN"), region)
        endpoint = (os.environ.get("S3_ENDPOINT") or default_endpoint
                    or f"https://s3.{region}.amazonaws.com")
        parsed = urllib.parse.urlparse(endpoint)
        self.secure = parsed.scheme != "http"
        self.host = parsed.netloc
        # path-style addressing keeps one endpoint working for real S3,
        # GCS-interop, minio, and the in-process mock server
        self.base_path = f"/{bucket}"
        self.service = service

    def _connect(self) -> http.client.HTTPConnection:
        if self.secure:
            ctx = None
            if get_env("S3_VERIFY_SSL", str, "1") == "0":
                ctx = ssl._create_unverified_context()
            return http.client.HTTPSConnection(self.host, context=ctx, timeout=60)
        return http.client.HTTPConnection(self.host, timeout=60)

    def request(self, method: str, key: str, query: Optional[Dict] = None,
                body: bytes = b"", headers: Optional[Dict] = None,
                ok: Tuple[int, ...] = (200,)) -> Tuple[int, Dict[str, str], bytes]:
        """One signed request with connection-reestablishing retry (see
        :mod:`.net_retry` for the shared failure/backoff policy).

        All client request types are safe to repeat: GETs/HEADs are
        idempotent, part PUTs re-upload the same part, and a retried
        complete-multipart POST is reconciled by the 404 handling in
        :meth:`S3WriteStream.close`.
        """
        query = {k: str(v) for k, v in (query or {}).items()}
        path = self.base_path + ("/" + key.lstrip("/") if key else "")
        payload_hash = hashlib.sha256(body).hexdigest() if body else _EMPTY_SHA
        # quote_via=quote: spaces must travel as %20, the form sign_request
        # canonicalizes — urlencode's default '+' is signed differently and
        # real endpoints 403 it (SignatureDoesNotMatch)
        qs = urllib.parse.urlencode(sorted(query.items()),
                                    quote_via=urllib.parse.quote)
        # the wire path must be the percent-encoded form (spaces/unicode in
        # keys are illegal in an HTTP request line); sign_request encodes
        # the raw path identically for the canonical URI, so wire == signed
        url = (urllib.parse.quote(path, safe="/-_.~")
               + (f"?{qs}" if qs else ""))

        def perform():
            # sign per attempt: long backoffs must not outlive the SigV4
            # clock-skew window on a replayed x-amz-date
            signed = sign_request(self.creds, method, self.host, path, query,
                                  dict(headers or {}), payload_hash,
                                  service="s3")
            conn = self._connect()
            try:
                conn.request(method, url, body=body or None, headers=signed)
                resp = conn.getresponse()
                data = resp.read()
                return (resp.status,
                        {k.lower(): v for k, v in resp.getheaders()}, data)
            finally:
                conn.close()

        def timed_perform():
            # timed per attempt so dmlc_filesystem_request_seconds keeps
            # its one-round-trip meaning (backoff between attempts already
            # lands in dmlc_net_retry_backoff_seconds_total)
            t0 = fs_metrics.request_start()
            attempt = perform()
            fs_metrics.note_request(self.service, method, t0,
                                    nread=len(attempt[2]),
                                    nwritten=len(body))
            return attempt

        status, rheaders, data = request_with_retries(
            timed_perform, ok, f"{method} {self.host}{url}")
        if status not in ok:
            log_fatal(f"{self.service} error {status} on "
                      f"{method} {url}: {data[:500]!r}")
        return status, rheaders, data


class S3ReadStream(SeekStream):
    """Buffered ranged-GET reader (reference ReadStream, s3_filesys.cc:462+)."""

    def __init__(self, client: _S3Client, key: str, size: int,
                 buffer_bytes: int = 4 << 20):
        self._client = client
        self._key = key
        self._size = size
        self._pos = 0
        self._buf = b""
        self._buf_start = 0
        self._buffer_bytes = buffer_bytes

    def read(self, nbytes: int) -> bytes:
        if self._pos >= self._size:
            return b""
        # serve from buffer when possible
        off = self._pos - self._buf_start
        if not (0 <= off < len(self._buf)):
            fetch = max(nbytes, self._buffer_bytes)
            end = min(self._pos + fetch, self._size) - 1
            status, _, data = self._client.request(
                "GET", self._key, headers={"Range": f"bytes={self._pos}-{end}"},
                ok=(200, 206))
            self._buf = data
            self._buf_start = self._pos
            off = 0
        out = self._buf[off:off + nbytes]
        self._pos += len(out)
        return out

    def write(self, data: bytes) -> None:
        log_fatal("S3ReadStream is read-only")

    def seek(self, pos: int) -> None:
        CHECK(0 <= pos <= self._size, f"seek out of range: {pos}")
        self._pos = pos

    def tell(self) -> int:
        return self._pos


class S3WriteStream(Stream):
    """Multipart-upload writer (reference WriteStream, s3_filesys.cc:551-798)."""

    def __init__(self, client: _S3Client, key: str):
        self._client = client
        self._key = key
        self._buffer = bytearray()
        self._buffer_mb = get_env("DMLC_S3_WRITE_BUFFER_MB", int, 64)
        self._part_bytes = max(5, self._buffer_mb) << 20
        self._upload_id: Optional[str] = None
        self._etags: List[str] = []
        self._total_bytes = 0
        self._write_token = ""
        self._closed = False

    def _init_multipart(self) -> None:
        # unique write token carried as object metadata: the one
        # store-agnostic way to later prove "the object at this key is THIS
        # upload" (ETag arithmetic breaks on SSE-KMS/interop stores whose
        # part ETags are not plain part-MD5s)
        self._write_token = uuid.uuid4().hex
        _, _, data = self._client.request(
            "POST", self._key, query={"uploads": ""},
            headers={_TOKEN_HEADER: self._write_token})
        root = ET.fromstring(data)
        node = root.find("{*}UploadId")
        if node is None:
            node = root.find("UploadId")
        CHECK(node is not None, "malformed InitiateMultipartUpload response")
        self._upload_id = node.text

    def write(self, data: bytes) -> None:
        self._total_bytes += len(data)
        self._buffer.extend(data)
        while len(self._buffer) >= self._part_bytes:
            self._upload_part(bytes(self._buffer[:self._part_bytes]))
            del self._buffer[:self._part_bytes]

    def _upload_part(self, part: bytes) -> None:
        if self._upload_id is None:
            self._init_multipart()
        part_no = len(self._etags) + 1
        _, headers, _ = self._client.request(
            "PUT", self._key, query={"partNumber": part_no,
                                     "uploadId": self._upload_id},
            body=part)
        self._etags.append(headers.get("etag", ""))

    def abort(self) -> None:
        """Abandon the upload WITHOUT committing — nothing lands at the key.

        :meth:`close` is the commit point (CompleteMultipartUpload, or the
        small-object PUT), so error paths must call this instead: completing
        a partial upload would land a truncated object for every reader to
        trip over.  Best-effort AbortMultipartUpload frees the parts already
        uploaded; an orphaned upload id only costs storage until the
        bucket's abort-incomplete-uploads lifecycle rule."""
        if self._closed:
            return
        self._closed = True
        self._buffer.clear()
        if self._upload_id is not None:
            try:
                self._client.request(
                    "DELETE", self._key,
                    query={"uploadId": self._upload_id},
                    ok=(200, 204, 404))  # 404: already expired/reconciled
            except Exception:
                pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._upload_id is None:
            # small object: single PUT (cheaper than multipart)
            self._client.request("PUT", self._key, body=bytes(self._buffer))
            return
        if self._buffer:
            self._upload_part(bytes(self._buffer))
            self._buffer.clear()
        parts = "".join(
            f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{etag}</ETag></Part>"
            for i, etag in enumerate(self._etags))
        body = (f"<CompleteMultipartUpload>{parts}"
                f"</CompleteMultipartUpload>").encode()
        # CompleteMultipartUpload is the one non-idempotent call: if a
        # transport retry re-sends it after S3 already committed, S3 answers
        # 404 NoSuchUpload.  Accept the 404 only when the object at the key
        # is provably THIS upload: it must carry the unique write token we
        # attached at initiate (object metadata survives the complete), and
        # have exactly the bytes we wrote — a stale same-size object under
        # an overwritten key (the fixed-shape checkpoint case) has neither.
        status, _, _ = self._client.request(
            "POST", self._key, query={"uploadId": self._upload_id},
            body=body, ok=(200, 404))
        if status == 404:
            hs, headers, _ = self._client.request("HEAD", self._key,
                                                  ok=(200, 404))
            landed = (hs == 200
                      and int(headers.get("content-length", -1))
                      == self._total_bytes
                      and headers.get(_TOKEN_HEADER.lower(), "")
                      == self._write_token)
            CHECK(landed,
                  f"multipart upload of {self._key} lost: complete returned "
                  f"NoSuchUpload and the object at the key is missing or is "
                  f"not this upload (expected {self._total_bytes} bytes, "
                  f"write token {self._write_token})")

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class S3FileSystem(fsys.FileSystem):
    """s3:// filesystem (reference S3FileSystem)."""

    env_prefix = "AWS"
    default_endpoint: Optional[str] = None
    service = "s3"

    def _client(self, uri: fsys.URI) -> _S3Client:
        return _S3Client(uri.host, self.env_prefix, self.default_endpoint,
                         self.service)

    def get_path_info(self, path: fsys.URI) -> fsys.FileInfo:
        client = self._client(path)
        key = path.name.lstrip("/")
        status, headers, _ = client.request("HEAD", key, ok=(200, 404))
        if status == 404:
            # directories exist implicitly when any key has the prefix
            entries = self.list_directory(path)
            if entries:
                return fsys.FileInfo(path.copy(), 0, fsys.FileType.DIRECTORY)
            raise FileNotFoundError(path.str())
        return fsys.FileInfo(path.copy(), int(headers.get("content-length", 0)),
                             fsys.FileType.FILE)

    def list_directory(self, path: fsys.URI) -> List[fsys.FileInfo]:
        client = self._client(path)
        prefix = path.name.lstrip("/")
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        out: List[fsys.FileInfo] = []
        token: Optional[str] = None
        while True:
            query = {"list-type": "2", "prefix": prefix, "delimiter": "/"}
            if token:
                query["continuation-token"] = token
            _, _, data = client.request("GET", "", query=query)
            root = ET.fromstring(data)
            ns = root.tag.split("}")[0] + "}" if "}" in root.tag else ""
            for item in root.findall(f"{ns}Contents"):
                key = item.find(f"{ns}Key").text
                size = int(item.find(f"{ns}Size").text)
                sub = path.copy()
                sub.name = "/" + key
                out.append(fsys.FileInfo(sub, size, fsys.FileType.FILE))
            for item in root.findall(f"{ns}CommonPrefixes"):
                sub = path.copy()
                sub.name = "/" + item.find(f"{ns}Prefix").text.rstrip("/")
                out.append(fsys.FileInfo(sub, 0, fsys.FileType.DIRECTORY))
            next_node = root.find(f"{ns}NextContinuationToken")
            if next_node is None or not next_node.text:
                return out
            token = next_node.text

    def open(self, path: fsys.URI, mode: str) -> Stream:
        if mode == "r":
            return self.open_for_read(path)
        CHECK(mode == "w", "s3 streams support 'r' and 'w' only "
              "(append is not an object-store operation)")
        return S3WriteStream(self._client(path), path.name.lstrip("/"))

    def open_for_read(self, path: fsys.URI) -> SeekStream:
        info = self.get_path_info(path)
        return S3ReadStream(self._client(path), path.name.lstrip("/"),
                            info.size)


class GCSFileSystem(S3FileSystem):
    """gs:// via GCS's S3-interoperability XML API (HMAC keys).

    Credentials: ``GCS_ACCESS_KEY_ID``/``GCS_SECRET_ACCESS_KEY`` (interop HMAC
    keys) falling back to AWS_*; endpoint https://storage.googleapis.com
    (override with S3_ENDPOINT).  This is the TPU-world default object store
    (SURVEY.md §7 stage 2).
    """

    env_prefix = "GCS"
    default_endpoint = "https://storage.googleapis.com"
    service = "gs"


Registry.get("filesystem").add("s3", S3FileSystem,
                               description="Amazon S3 / S3-compatible stores")
Registry.get("filesystem").add("gs", GCSFileSystem,
                               description="Google Cloud Storage (interop XML API)")
