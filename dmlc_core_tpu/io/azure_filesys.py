"""Azure Blob Storage filesystem (azure://container/path).

Capability parity with the reference's src/io/azure_filesys.{h,cc} (which
wraps the azure-storage-cpp SDK; account/key from env, azure_filesys.cc:38-39).
The rebuild talks the Blob REST API directly with SharedKey authorization —
same zero-SDK stance as the S3 engine:

- ranged GET reads through the same buffered SeekStream pattern;
- writes via Put Block + Put Block List (the multipart-upload analog),
  small blobs as a single Put Blob;
- listing via ``?restype=container&comp=list`` with prefix/delimiter.

Env contract: ``AZURE_STORAGE_ACCOUNT`` + ``AZURE_STORAGE_ACCESS_KEY``
(base64), optional ``AZURE_ENDPOINT`` override (mock/azurite/sovereign clouds).
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import http.client
import os
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from dmlc_core_tpu.io import filesys as fsys
from dmlc_core_tpu.io import fs_metrics
from dmlc_core_tpu.io.net_retry import request_with_retries
from dmlc_core_tpu.io.stream import SeekStream, Stream
from dmlc_core_tpu.param import get_env
from dmlc_core_tpu.registry import Registry
from dmlc_core_tpu.utils.logging import CHECK, log_fatal

__all__ = ["AzureFileSystem"]


class _AzureClient:
    def __init__(self, container: str):
        self.account = os.environ.get("AZURE_STORAGE_ACCOUNT", "")
        key_b64 = os.environ.get("AZURE_STORAGE_ACCESS_KEY", "")
        if not self.account or not key_b64:
            log_fatal("Need AZURE_STORAGE_ACCOUNT and AZURE_STORAGE_ACCESS_KEY "
                      "in the environment to access azure:// paths "
                      "(reference azure_filesys.cc:38-39)")
        self.key = base64.b64decode(key_b64)
        self.container = container
        endpoint = os.environ.get(
            "AZURE_ENDPOINT", f"https://{self.account}.blob.core.windows.net")
        parsed = urllib.parse.urlparse(endpoint)
        self.secure = parsed.scheme != "http"
        self.host = parsed.netloc

    def _sign(self, method: str, path: str, query: Dict[str, str],
              headers: Dict[str, str], content_length: str) -> str:
        canon_headers = "".join(
            f"{k}:{headers[k]}\n" for k in sorted(headers)
            if k.startswith("x-ms-"))
        canon_resource = f"/{self.account}/{self.container}"
        if path:
            canon_resource += f"/{path}"
        for k in sorted(query):
            canon_resource += f"\n{k}:{query[k]}"
        string_to_sign = "\n".join([
            method, "", "", content_length, "", "", "", "", "", "", "",
            headers.get("Range", ""), canon_headers + canon_resource,
        ])
        sig = base64.b64encode(hmac.new(self.key, string_to_sign.encode(),
                                        hashlib.sha256).digest()).decode()
        return f"SharedKey {self.account}:{sig}"

    def request(self, method: str, path: str, query: Optional[Dict] = None,
                body: bytes = b"", headers: Optional[Dict] = None,
                ok: Tuple[int, ...] = (200, 201),
                ) -> Tuple[int, Dict[str, str], bytes]:
        query = {k: str(v) for k, v in (query or {}).items()}
        base_headers = dict(headers or {})
        url = f"/{self.container}"
        if path:
            url += "/" + urllib.parse.quote(path)
        if query:
            url += "?" + urllib.parse.urlencode(sorted(query.items()))

        def perform():
            # sign per attempt: x-ms-date must stay within Azure's clock-skew
            # window even after long retry backoffs
            hdrs = dict(base_headers)
            now = datetime.datetime.now(datetime.timezone.utc)
            hdrs["x-ms-date"] = now.strftime("%a, %d %b %Y %H:%M:%S GMT")
            hdrs["x-ms-version"] = "2021-08-06"
            clen = str(len(body)) if body else ""
            hdrs["Authorization"] = self._sign(method, path, query, hdrs,
                                               clen)
            if body:
                hdrs["Content-Length"] = clen
            conn = (http.client.HTTPSConnection if self.secure
                    else http.client.HTTPConnection)(self.host, timeout=60)
            try:
                conn.request(method, url, body=body or None, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                return (resp.status,
                        {k.lower(): v for k, v in resp.getheaders()}, data)
            finally:
                conn.close()

        # shared retry policy (net_retry); Put Block / Put Block List are
        # idempotent per block id, so replays are safe
        def timed_perform():
            # timed per attempt so dmlc_filesystem_request_seconds keeps
            # its one-round-trip meaning (backoff between attempts already
            # lands in dmlc_net_retry_backoff_seconds_total)
            t0 = fs_metrics.request_start()
            attempt = perform()
            fs_metrics.note_request("azure", method, t0,
                                    nread=len(attempt[2]),
                                    nwritten=len(body))
            return attempt

        status, rheaders, data = request_with_retries(
            timed_perform, ok, f"{method} {self.host}{url}")
        if status not in ok:
            log_fatal(f"azure error {status} on {method} {url}: "
                      f"{data[:500]!r}")
        return status, rheaders, data


class _AzureReadStream(SeekStream):
    def __init__(self, client: _AzureClient, path: str, size: int,
                 buffer_bytes: int = 4 << 20):
        self._client = client
        self._path = path
        self._size = size
        self._pos = 0
        self._buf = b""
        self._buf_start = 0
        self._buffer_bytes = buffer_bytes

    def read(self, nbytes: int) -> bytes:
        if self._pos >= self._size:
            return b""
        off = self._pos - self._buf_start
        if not (0 <= off < len(self._buf)):
            end = min(self._pos + max(nbytes, self._buffer_bytes),
                      self._size) - 1
            _, _, data = self._client.request(
                "GET", self._path,
                headers={"Range": f"bytes={self._pos}-{end}"}, ok=(200, 206))
            self._buf, self._buf_start, off = data, self._pos, 0
        out = self._buf[off:off + nbytes]
        self._pos += len(out)
        return out

    def write(self, data: bytes) -> None:
        log_fatal("azure read stream is read-only")

    def seek(self, pos: int) -> None:
        CHECK(0 <= pos <= self._size, f"seek out of range: {pos}")
        self._pos = pos

    def tell(self) -> int:
        return self._pos


class _AzureWriteStream(Stream):
    """Put Block / Put Block List writer (the multipart analog)."""

    def __init__(self, client: _AzureClient, path: str):
        self._client = client
        self._path = path
        self._buffer = bytearray()
        self._block_bytes = get_env("DMLC_AZURE_WRITE_BUFFER_MB", int, 64) << 20
        self._block_ids: List[str] = []
        self._closed = False

    def write(self, data: bytes) -> None:
        self._buffer.extend(data)
        while len(self._buffer) >= self._block_bytes:
            self._put_block(bytes(self._buffer[:self._block_bytes]))
            del self._buffer[:self._block_bytes]

    def _put_block(self, block: bytes) -> None:
        block_id = base64.b64encode(
            f"block-{len(self._block_ids):08d}".encode()).decode()
        self._client.request("PUT", self._path,
                             query={"comp": "block", "blockid": block_id},
                             body=block)
        self._block_ids.append(block_id)

    def abort(self) -> None:
        """Abandon without committing: nothing lands at the path (Put Block
        List in :meth:`close` is the commit point); uncommitted blocks are
        garbage-collected by the service after a week."""
        self._closed = True
        self._buffer.clear()
        self._block_ids.clear()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if not self._block_ids:
            self._client.request("PUT", self._path, body=bytes(self._buffer),
                                 headers={"x-ms-blob-type": "BlockBlob"})
            return
        if self._buffer:
            self._put_block(bytes(self._buffer))
            self._buffer.clear()
        blocks = "".join(f"<Latest>{b}</Latest>" for b in self._block_ids)
        body = f"<BlockList>{blocks}</BlockList>".encode()
        self._client.request("PUT", self._path, query={"comp": "blocklist"},
                             body=body)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class AzureFileSystem(fsys.FileSystem):
    """azure://container/path (reference AzureFileSystem)."""

    def _split(self, path: fsys.URI) -> Tuple[_AzureClient, str]:
        return _AzureClient(path.host), path.name.lstrip("/")

    def get_path_info(self, path: fsys.URI) -> fsys.FileInfo:
        client, key = self._split(path)
        status, headers, _ = client.request("HEAD", key, ok=(200, 404))
        if status == 404:
            if self.list_directory(path):
                return fsys.FileInfo(path.copy(), 0, fsys.FileType.DIRECTORY)
            raise FileNotFoundError(path.str())
        return fsys.FileInfo(path.copy(),
                             int(headers.get("content-length", 0)),
                             fsys.FileType.FILE)

    def list_directory(self, path: fsys.URI) -> List[fsys.FileInfo]:
        client, prefix = self._split(path)
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        _, _, data = client.request(
            "GET", "", query={"restype": "container", "comp": "list",
                              "prefix": prefix, "delimiter": "/"})
        root = ET.fromstring(data)
        out: List[fsys.FileInfo] = []
        for blob in root.iter("Blob"):
            sub = path.copy()
            sub.name = "/" + blob.find("Name").text
            size_node = blob.find("Properties/Content-Length")
            size = int(size_node.text) if size_node is not None else 0
            out.append(fsys.FileInfo(sub, size, fsys.FileType.FILE))
        for pfx in root.iter("BlobPrefix"):
            sub = path.copy()
            sub.name = "/" + pfx.find("Name").text.rstrip("/")
            out.append(fsys.FileInfo(sub, 0, fsys.FileType.DIRECTORY))
        return out

    def open(self, path: fsys.URI, mode: str) -> Stream:
        if mode == "r":
            return self.open_for_read(path)
        CHECK(mode == "w", "azure streams support 'r' and 'w' only")
        client, key = self._split(path)
        return _AzureWriteStream(client, key)

    def open_for_read(self, path: fsys.URI) -> SeekStream:
        info = self.get_path_info(path)
        client, key = self._split(path)
        return _AzureReadStream(client, key, info.size)


Registry.get("filesystem").add("azure", AzureFileSystem,
                               description="Azure Blob Storage (SharedKey REST)")
