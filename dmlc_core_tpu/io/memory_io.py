"""In-memory streams (reference include/dmlc/memory_io.h:21-103)."""

from __future__ import annotations

from dmlc_core_tpu.io.stream import SeekStream
from dmlc_core_tpu.utils.logging import CHECK, CHECK_LE

__all__ = ["MemoryFixedSizeStream", "MemoryStringStream"]


class MemoryFixedSizeStream(SeekStream):
    """Stream over a fixed-size caller-owned buffer (memory_io.h:21-60).

    Writes past the end raise; reads stop at the buffer end.  The buffer must
    support the writable buffer protocol (bytearray / writable memoryview /
    numpy uint8 array).
    """

    def __init__(self, buffer) -> None:
        self._buf = memoryview(buffer).cast("B")
        self._pos = 0

    def read(self, nbytes: int) -> bytes:
        end = min(self._pos + nbytes, len(self._buf))
        out = bytes(self._buf[self._pos:end])
        self._pos = end
        return out

    def write(self, data: bytes) -> None:
        CHECK_LE(self._pos + len(data), len(self._buf),
                 "MemoryFixedSizeStream: write beyond fixed buffer")
        self._buf[self._pos:self._pos + len(data)] = data
        self._pos += len(data)

    def seek(self, pos: int) -> None:
        CHECK(0 <= pos <= len(self._buf), f"seek out of range: {pos}")
        self._pos = pos

    def tell(self) -> int:
        return self._pos


class MemoryStringStream(SeekStream):
    """Growable stream over a bytearray (memory_io.h:66-103).

    The backing bytearray is shared with the caller: pass one in to write into
    it, or read :attr:`data` afterwards.
    """

    def __init__(self, data: bytearray | None = None) -> None:
        self.data = bytearray() if data is None else data
        self._pos = 0

    def read(self, nbytes: int) -> bytes:
        end = min(self._pos + nbytes, len(self.data))
        out = bytes(self.data[self._pos:end])
        self._pos = end
        return out

    def write(self, data: bytes) -> None:
        end = self._pos + len(data)
        if end > len(self.data):
            self.data.extend(b"\x00" * (end - len(self.data)))
        self.data[self._pos:end] = data
        self._pos = end

    def seek(self, pos: int) -> None:
        CHECK(0 <= pos <= len(self.data), f"seek out of range: {pos}")
        self._pos = pos

    def tell(self) -> int:
        return self._pos
