"""RecordIO: splittable magic-framed binary record format.

Capability parity with the reference (include/dmlc/recordio.h:38-187,
src/recordio.cc:11-156) and format-compatible with it, so ``.rec`` files
written by either implementation interchange:

- every record part is ``[magic u32][lrec u32][payload][pad to 4B]``;
- ``lrec`` packs ``cflag`` (top 3 bits) and payload length (low 29 bits);
- a payload containing the 4-byte-aligned magic word in-band is *escaped* by
  splitting it at each magic cell into parts with cflag 1 (start) / 2 (middle)
  / 3 (end); a plain record has cflag 0 (recordio.h:33-36);
- readers resync from any 4-byte-aligned position by scanning for
  ``magic`` followed by cflag 0/1 — which is what makes the format splittable
  (src/recordio.cc:85-100).

The magic-cell scan is vectorized with numpy (the reference's hand loop,
src/recordio.cc:22-38); escape hits are rare so the per-hit work stays scalar.
"""

from __future__ import annotations

import struct
from typing import List, Optional

import numpy as np

from dmlc_core_tpu import native_bridge
from dmlc_core_tpu.io.stream import Stream
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ

__all__ = [
    "RECORDIO_MAGIC",
    "RecordIOWriter",
    "RecordIOReader",
    "RecordIOChunkReader",
    "encode_lrec",
    "decode_flag",
    "decode_length",
]

# (magic >> 29) & 7 == 6 > 3, so an lrec word can never equal the magic
# (reference recordio.h:40-44).
RECORDIO_MAGIC = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", RECORDIO_MAGIC)


def encode_lrec(cflag: int, length: int) -> int:
    return (cflag << 29) | length


def decode_flag(lrec: int) -> int:
    return (lrec >> 29) & 7


def decode_length(lrec: int) -> int:
    return lrec & ((1 << 29) - 1)


def _aligned_magic_positions(data: bytes, limit: int) -> np.ndarray:
    """Byte offsets (multiples of 4, < limit) where the magic word occurs."""
    nwords = limit // 4
    if nwords == 0:
        return np.empty(0, dtype=np.int64)
    words = np.frombuffer(data, dtype="<u4", count=nwords)
    return (np.nonzero(words == RECORDIO_MAGIC)[0] * 4).astype(np.int64)


class RecordIOWriter:
    """Write records onto a stream (reference RecordIOWriter, recordio.cc:11-51)."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self.except_counter = 0  # number of in-band magic escapes performed

    def write_record(self, data: bytes) -> None:
        CHECK(len(data) < (1 << 29), "RecordIO only accepts records below 2^29 bytes")
        if isinstance(data, (bytearray, memoryview)):
            data = bytes(data)
        lower_align = (len(data) >> 2) << 2
        out: List[bytes] = []
        dptr = 0
        for pos in _aligned_magic_positions(data, lower_align):
            pos = int(pos)
            out.append(_MAGIC_BYTES)
            out.append(struct.pack("<I", encode_lrec(1 if dptr == 0 else 2, pos - dptr)))
            out.append(data[dptr:pos])
            dptr = pos + 4
            self.except_counter += 1
        out.append(_MAGIC_BYTES)
        out.append(struct.pack("<I", encode_lrec(3 if dptr != 0 else 0, len(data) - dptr)))
        out.append(data[dptr:])
        pad = (-(len(data) - dptr)) % 4
        if pad:
            out.append(b"\x00" * pad)
        self._stream.write(b"".join(out))

    def write_records(self, records: List[bytes]) -> List[int]:
        """Batch write; returns the stream offset of each record.  Uses the
        native batch framer (native/recordio.cc) when available."""
        base = self.tell()
        if native_bridge.available():
            lens = np.fromiter((len(r) for r in records), dtype=np.int64,
                               count=len(records))
            CHECK(bool((lens < (1 << 29)).all()),
                  "RecordIO only accepts records below 2^29 bytes")
            framed, offsets, nexc = native_bridge.recordio_frame(
                b"".join(records), lens)
            self._stream.write(framed)
            self.except_counter += nexc
            return [base + int(o) for o in offsets]
        out = []
        for rec in records:
            out.append(self.tell())
            # unbound base call: subclasses (IndexedRecordIOWriter) track
            # offsets in their write_records override, not per record here
            RecordIOWriter.write_record(self, rec)
        return out

    def tell(self) -> int:
        return self._stream.tell()


class IndexedRecordIOWriter(RecordIOWriter):
    """RecordIO writer that also tracks the index-file entries consumed by
    :class:`dmlc_core_tpu.io.input_split.IndexedRecordIOSplitter` (text lines
    of ``<record-id> <byte-offset>``, reference indexed_recordio_split.cc
    ReadIndexFile)."""

    def __init__(self, stream: Stream):
        super().__init__(stream)
        self.offsets: List[int] = []
        self._next_id = 0

    def write_record(self, data: bytes) -> None:
        self.offsets.append(self.tell())
        super().write_record(data)
        self._next_id += 1

    def write_records(self, records: List[bytes]) -> List[int]:
        offs = super().write_records(records)
        self.offsets.extend(offs)
        self._next_id += len(records)
        return offs

    def save_index(self, index_stream: Stream) -> None:
        text = "".join(f"{i} {off}\n" for i, off in enumerate(self.offsets))
        index_stream.write(text.encode("ascii"))


class RecordIOReader:
    """Sequentially read records from a stream (reference recordio.cc:53-83)."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self._eos = False

    def next_record(self) -> Optional[bytes]:
        """Next logical record, or None at end of stream."""
        if self._eos:
            return None
        parts: List[bytes] = []
        while True:
            header = self._stream.read(8)
            if len(header) == 0 and not parts:
                self._eos = True
                return None
            CHECK_EQ(len(header), 8, "invalid RecordIO file: truncated header")
            magic, lrec = struct.unpack("<II", header)
            CHECK_EQ(magic, RECORDIO_MAGIC, "invalid RecordIO file: bad magic")
            cflag, length = decode_flag(lrec), decode_length(lrec)
            upper_align = ((length + 3) >> 2) << 2
            payload = self._stream.read_exact(upper_align) if upper_align else b""
            parts.append(payload[:length])
            if cflag in (0, 3):
                break
            parts.append(_MAGIC_BYTES)  # escaped in-band magic cell
        return b"".join(parts)

    def __iter__(self):
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


def find_next_record_head(chunk: bytes, start: int, end: int) -> int:
    """First 4-aligned offset in [start, end) holding a record head (magic +
    cflag 0/1); ``end`` when none (reference FindNextRecordIOHead,
    recordio.cc:85-100)."""
    CHECK_EQ(start % 4, 0)
    words = np.frombuffer(chunk, dtype="<u4", count=len(chunk) // 4)
    sw, ew = start // 4, end // 4
    for widx in np.nonzero(words[sw:ew - 1] == RECORDIO_MAGIC)[0]:
        cflag = decode_flag(int(words[sw + int(widx) + 1]))
        if cflag in (0, 1):
            return (sw + int(widx)) * 4
    return end


class RecordIOChunkReader:
    """Parse records out of an in-memory chunk, optionally sub-partitioned for
    parallel parsing (reference RecordIOChunkReader, recordio.cc:102-156)."""

    def __init__(self, chunk: bytes, part_index: int = 0, num_parts: int = 1):
        self._chunk = bytes(chunk) if isinstance(chunk, (bytearray, memoryview)) else chunk
        size = len(self._chunk)
        nstep = (size + num_parts - 1) // num_parts
        nstep = ((nstep + 3) >> 2) << 2
        begin = min(size, nstep * part_index)
        end = min(size, nstep * (part_index + 1))
        # native fast path: single C++ pass over the partition up front
        # (native/recordio.cc), then per-record emission is array walking.
        self._scan = None
        self._scan_i = 0
        if native_bridge.available():
            head, plen, escaped, pbegin, pend = native_bridge.recordio_scan(
                self._chunk, begin, end)
            self._scan = (head, plen, escaped)
            self._pbegin, self._pend = pbegin, pend
            return
        self._pbegin = find_next_record_head(self._chunk, begin, size)
        self._pend = find_next_record_head(self._chunk, end, size)

    def _next_record_scanned(self) -> Optional[memoryview]:
        head, plen, escaped = self._scan
        i = self._scan_i
        if i >= len(head):
            return None
        self._scan_i = i + 1
        start = int(head[i])
        length = int(plen[i])
        view = memoryview(self._chunk)
        if not escaped[i]:
            return view[start + 8:start + 8 + length]
        # rare: reassemble the escaped parts natively (restores the in-band
        # magic cells; the scan already validated the part structure)
        out = native_bridge.recordio_extract(self._chunk, start, length)
        CHECK_EQ(len(out), length, "invalid RecordIO format")
        return memoryview(out)

    def next_record(self) -> Optional[memoryview]:
        """Next record (zero-copy memoryview for unescaped records), or None."""
        if self._scan is not None:
            return self._next_record_scanned()
        if self._pbegin >= self._pend:
            return None
        view = memoryview(self._chunk)
        magic, lrec = struct.unpack_from("<II", self._chunk, self._pbegin)
        CHECK_EQ(magic, RECORDIO_MAGIC, "invalid RecordIO format")
        cflag, clen = decode_flag(lrec), decode_length(lrec)
        if cflag == 0:
            start = self._pbegin + 8
            self._pbegin = start + (((clen + 3) >> 2) << 2)
            CHECK(self._pbegin <= self._pend, "invalid RecordIO format")
            return view[start:start + clen]
        CHECK_EQ(cflag, 1, "invalid RecordIO format")
        parts: List[bytes] = []
        while True:
            CHECK(self._pbegin + 8 <= self._pend, "invalid RecordIO format")
            magic, lrec = struct.unpack_from("<II", self._chunk, self._pbegin)
            CHECK_EQ(magic, RECORDIO_MAGIC, "invalid RecordIO format")
            cflag, clen = decode_flag(lrec), decode_length(lrec)
            parts.append(self._chunk[self._pbegin + 8:self._pbegin + 8 + clen])
            self._pbegin += 8 + (((clen + 3) >> 2) << 2)
            if cflag == 3:
                break
            parts.append(_MAGIC_BYTES)
        return memoryview(b"".join(parts))

    def __iter__(self):
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec
