"""Threaded producer/consumer iterator — the universal pipeline primitive.

Capability parity with the reference's ``dmlc::ThreadedIter<DType>``
(include/dmlc/threadediter.h:45-394): a single producer thread fills a bounded
queue; the consumer pulls with :meth:`next` and hands buffers back with
:meth:`recycle` so the producer can reuse them (free-cell recycling,
threadediter.h:359-394); :meth:`before_first` restarts the epoch
(kBeforeFirst signal, threadediter.h:167-190) and :meth:`destroy` tears the
thread down (kDestroy).

Producer protocol (reference Producer subclass, threadediter.h:87-134)::

    class MyProducer:
        def before_first(self):   # reset to the beginning (optional)
        def next(self, reuse):    # return next item, reusing `reuse` (may be
                                  # None) as scratch; return None at the end

Exceptions raised by the producer are captured and re-raised on the consumer
side at the next :meth:`next` call, matching the reference's exception-ferrying
(threadediter.h:300-356).

TPU note: this is the host-side prefetch idiom. The device-facing recast of the
same pattern (double-buffered ``device_put`` against a mesh) lives in
:mod:`dmlc_core_tpu.bridge.loader`.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, Generic, Iterator, Optional, TypeVar

from dmlc_core_tpu import fault, telemetry

logger = logging.getLogger("dmlc_core_tpu.io")

T = TypeVar("T")

__all__ = ["ThreadedIter", "IteratorProducer"]

_END = object()


class IteratorProducer:
    """Adapts a factory of plain Python iterables to the producer protocol."""

    def __init__(self, factory: Callable[[], Any]):
        self._factory = factory
        self._it: Optional[Iterator] = None

    def before_first(self) -> None:
        self._it = None

    def next(self, reuse: Any) -> Any:
        if self._it is None:
            self._it = iter(self._factory())
        try:
            return next(self._it)
        except StopIteration:
            return None


class ThreadedIter(Generic[T]):
    """Single-producer bounded-queue prefetch iterator.

    Capacity is bounded by item count (``max_capacity``) and, when a
    ``cost_fn`` is given, by total queued cost (``max_bytes``): the producer
    blocks while ``sum(cost_fn(item))`` of queued items is at or over the
    bound.  At least one item is always admitted, so a single over-budget
    item flows instead of deadlocking.  The bound is checked *before*
    producing — the queue can overshoot by at most one item.

    Observability: :meth:`qsize` reports current queue occupancy (and
    :meth:`qbytes` the queued cost); ``producer_stalls`` /
    ``consumer_stalls`` count wait *episodes* (a producer blocked on a full
    queue / a consumer blocked on an empty one — each stall names the side
    that is the bottleneck); the optional ``on_producer_stall`` /
    ``on_consumer_stall`` hooks fire once per episode (called under the
    iterator lock: keep them cheap and never call back into the iterator).
    With telemetry enabled the same signals feed the
    ``dmlc_threadediter_*`` metric families, labeled by ``name``.
    """

    def __init__(self, producer: Any = None, max_capacity: int = 8,
                 name: str = "threadediter",
                 max_bytes: Optional[int] = None,
                 cost_fn: Optional[Callable[[Any], int]] = None):
        self._cap = max(1, int(max_capacity))
        self._name = name
        self._max_bytes = int(max_bytes) if max_bytes else None
        self._cost_fn = cost_fn
        self._queue_bytes = 0             # summed cost of queued items
        self._cond = threading.Condition()
        self._queue: deque = deque()      # (generation, item-or-_END, cost)
        self._free: deque = deque()       # recycled buffers
        self._gen = 0                     # current consumer generation
        self._destroyed = False
        self._error: Optional[BaseException] = None
        self._producer = None
        self._thread: Optional[threading.Thread] = None
        self.producer_stalls = 0
        self.consumer_stalls = 0
        self.on_producer_stall: Optional[Callable[[], None]] = None
        self.on_consumer_stall: Optional[Callable[[], None]] = None
        if producer is not None:
            self.init(producer)

    @classmethod
    def from_factory(cls, factory: Callable[[], Any], max_capacity: int = 8,
                     name: str = "threadediter") -> "ThreadedIter":
        """ThreadedIter over ``iter(factory())`` per epoch."""
        return cls(IteratorProducer(factory), max_capacity=max_capacity,
                   name=name)

    # -- observability --------------------------------------------------------
    def qsize(self) -> int:
        """Real items of the current generation queued right now (end-of-
        epoch/error sentinels and stale-generation leftovers excluded)."""
        with self._cond:
            return self._qsize_locked()

    def qbytes(self) -> int:
        """Summed ``cost_fn`` cost of queued items (0 without a cost_fn)."""
        with self._cond:
            return self._queue_bytes

    def _qsize_locked(self) -> int:
        return sum(1 for gen, item, _ in self._queue
                   if gen == self._gen and item is not _END)

    def _full_locked(self) -> bool:
        if len(self._queue) >= self._cap:
            return True
        return (self._max_bytes is not None and len(self._queue) > 0
                and self._queue_bytes >= self._max_bytes)

    def _note_depth_locked(self) -> None:
        try:
            if telemetry.enabled():
                telemetry.gauge_set("dmlc_threadediter_queue_depth",
                                    self._qsize_locked(), name=self._name)
                if self._cost_fn is not None:
                    telemetry.gauge_set("dmlc_threadediter_queue_bytes",
                                        self._queue_bytes, name=self._name)
        except Exception:
            # observability must never kill the producer thread (a dead
            # producer with no _error/_END posted hangs next() forever)
            logger.exception("queue-depth telemetry failed")

    def _note_producer_stall_locked(self) -> None:
        self.producer_stalls += 1
        # counter first: a raising user hook must not desync the exported
        # count from the attribute just incremented
        try:
            telemetry.count("dmlc_threadediter_producer_stalls_total",
                            name=self._name)
        except Exception:
            logger.exception("producer-stall telemetry failed")
        try:
            if self.on_producer_stall is not None:
                self.on_producer_stall()
        except Exception:
            logger.exception("producer-stall hook failed")

    def _note_consumer_stall_locked(self) -> None:
        self.consumer_stalls += 1
        try:
            telemetry.count("dmlc_threadediter_consumer_stalls_total",
                            name=self._name)
        except Exception:
            logger.exception("consumer-stall telemetry failed")
        try:
            if self.on_consumer_stall is not None:
                self.on_consumer_stall()
        except Exception:
            logger.exception("consumer-stall hook failed")

    def init(self, producer: Any) -> None:
        assert self._thread is None, "ThreadedIter already initialized"
        self._producer = producer
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dmlc-threadediter")
        self._thread.start()

    # -- producer thread ------------------------------------------------------
    def _run(self) -> None:
        cur_gen = 0
        need_reset = False
        while True:
            epoch_ok = True
            if need_reset:
                try:
                    self._producer.before_first()
                except BaseException as exc:  # noqa: BLE001 - ferried to consumer
                    self._post_error(cur_gen, exc)
                    epoch_ok = False
            if epoch_ok:
                finished = self._produce_epoch(cur_gen)
                if finished is None:
                    return  # destroyed
            # epoch over (EOF, error, or reset): wait for the next
            # generation.  An error ends the epoch but NOT the thread —
            # exiting here would make every post-error before_first()
            # restart hang the consumer forever (no producer left)
            with self._cond:
                while not self._destroyed and self._gen == cur_gen:
                    self._cond.wait()
                if self._destroyed:
                    return
                cur_gen = self._gen
            need_reset = True

    def _produce_epoch(self, cur_gen: int) -> Optional[bool]:
        """Produce items for ``cur_gen`` until EOF/reset. None means destroyed."""
        while True:
            with self._cond:
                if (self._full_locked() and not self._destroyed
                        and self._gen == cur_gen):
                    # queue full: the consumer is the bottleneck right now
                    self._note_producer_stall_locked()
                while (self._full_locked() and not self._destroyed
                       and self._gen == cur_gen):
                    self._cond.wait()
                if self._destroyed:
                    return None
                if self._gen != cur_gen:
                    return True  # reset requested mid-epoch
                reuse = self._free.popleft() if self._free else None
            try:
                if fault.enabled():
                    # injected producer faults ride the normal ferrying path:
                    # the consumer sees them at next(), the thread survives
                    fault.inject("threadediter.produce", name=self._name)
                with telemetry.span("threadediter.produce", name=self._name):
                    item = self._producer.next(reuse)
            except BaseException as exc:  # noqa: BLE001
                if reuse is not None:
                    # the buffer was never handed to the consumer; without
                    # this, every failed epoch shrinks the recycle pool
                    with self._cond:
                        self._free.append(reuse)
                self._post_error(cur_gen, exc)
                return True  # epoch over; stay alive for a restart
            cost = 0
            if item is not None and self._cost_fn is not None:
                try:
                    cost = max(0, int(self._cost_fn(item)))
                except Exception:
                    logger.exception("cost hook failed; item costed as 0")
            with self._cond:
                if self._destroyed:
                    return None
                if self._gen != cur_gen:
                    # reset raced the produce: the consumer will never see
                    # this item — re-pool its buffer (and reuse too, when
                    # the producer ignored it and allocated fresh)
                    if item is not None and item is not reuse:
                        self._free.append(item)
                    if reuse is not None:
                        self._free.append(reuse)
                    return True
                self._queue.append((cur_gen, _END if item is None else item,
                                    cost))
                self._queue_bytes += cost
                self._note_depth_locked()
                self._cond.notify_all()
                if item is None:
                    # EOF probe: the popped reuse buffer was never consumed
                    if reuse is not None:
                        self._free.append(reuse)
                    return True

    def _post_error(self, gen: int, exc: BaseException) -> None:
        with self._cond:
            if gen != self._gen:
                # the consumer already abandoned this epoch via
                # before_first(); surfacing its error into the NEXT epoch
                # would make an otherwise-successful restart raise at EOF
                return
            self._error = exc
            self._queue.append((gen, _END, 0))
            self._cond.notify_all()

    # -- consumer side ---------------------------------------------------------
    def next(self) -> Optional[T]:
        """Next item, or None at end of the current epoch (reference Next)."""
        with self._cond:
            stalled = False
            while True:
                if self._destroyed:
                    return None
                # drop items from stale generations, recycling their buffers
                while self._queue and self._queue[0][0] != self._gen:
                    _, item, cost = self._queue.popleft()
                    self._queue_bytes -= cost
                    if item is not _END:
                        self._free.append(item)
                    self._cond.notify_all()
                if self._queue:
                    gen, item, cost = self._queue[0]
                    if item is _END:
                        if self._error is not None:
                            err, self._error = self._error, None
                            # leave _END queued: the epoch stays "ended"
                            # after the raise (next call returns None
                            # instead of waiting on an epoch that will
                            # never produce again)
                            raise err
                        return None  # leave _END queued: epoch stays "ended"
                    self._queue.popleft()
                    self._queue_bytes -= cost
                    self._note_depth_locked()
                    self._cond.notify_all()
                    return item
                if not stalled:
                    # empty queue: the producer is the bottleneck right now
                    stalled = True
                    self._note_consumer_stall_locked()
                self._cond.wait()

    def recycle(self, item: T) -> None:
        """Return a consumed buffer for producer reuse (reference Recycle)."""
        with self._cond:
            self._free.append(item)
            self._cond.notify_all()

    def before_first(self) -> None:
        """Restart from the beginning (reference BeforeFirst signal protocol).

        Discards the current epoch wholesale: queued items AND a pending
        error both belong to the epoch being abandoned (the producer posts
        late errors generation-checked, so none can leak in afterwards)."""
        with self._cond:
            self._gen += 1
            self._error = None
            # drop everything already queued
            while self._queue:
                _, item, _ = self._queue.popleft()
                if item is not _END:
                    self._free.append(item)
            self._queue_bytes = 0
            self._note_depth_locked()
            self._cond.notify_all()

    def destroy(self) -> None:
        with self._cond:
            if self._destroyed:
                return
            self._destroyed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass

    def __iter__(self) -> Iterator[T]:
        while True:
            item = self.next()
            if item is None:
                return
            yield item
