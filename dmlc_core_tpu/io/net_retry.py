"""Connection-reestablishing retry shared by the remote-FS HTTP clients.

The reference re-connects on curl errors and short reads
(src/io/s3_filesys.cc:318-341, 703-733).  Every client here opens a fresh
connection per request, so a retry IS a re-connect; this module is the one
place the transport failure set, transient status set, and backoff policy
live, so the S3/GCS and Azure clients cannot drift.

Backoff policy (docs/robustness.md):

- **full jitter**: each sleep is uniform in ``[0, min(cap, base * 2^attempt))``
  — a fleet of workers thundering against a throttling endpoint must not
  re-synchronize on the retry schedule;
- **Retry-After honored**: when a 429/503 carries a ``Retry-After`` header
  (delta-seconds or HTTP-date), the sleep is at least that long (capped at
  :data:`RETRY_AFTER_CAP`) — the server knows its own recovery better than
  our exponent does;
- **total deadline**: ``DMLC_NET_RETRY_DEADLINE`` (seconds, 0 = off) bounds
  the whole retry envelope; a sleep that would cross it is skipped and the
  caller gets the final failure *now* instead of minutes of doomed backoff.

The ``net.request`` fault site (:mod:`dmlc_core_tpu.fault`) lets chaos runs
inject 503 storms, resets, and stalls here without a real flaky endpoint.
"""

from __future__ import annotations

import datetime
import email.utils
import http.client
import logging
import random
import socket
import ssl
import time
from typing import Callable, Dict, Optional, Tuple

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.param import get_env

__all__ = ["RETRYABLE_EXC", "RETRYABLE_STATUS", "request_with_retries",
           "BACKOFF_BASE", "BACKOFF_CAP", "RETRY_AFTER_CAP"]

logger = logging.getLogger("dmlc_core_tpu.io.net")

# transport-level failures worth re-establishing a connection for
RETRYABLE_EXC = (ConnectionError, socket.timeout, ssl.SSLError,
                 http.client.IncompleteRead, http.client.BadStatusLine,
                 http.client.CannotSendRequest, http.client.ResponseNotReady)
# server statuses that are transient by contract (503 SlowDown on S3,
# 429 rateLimitExceeded on the GCS interop API / Azure throttling,
# 5xx incl. 504 from front-end proxies)
RETRYABLE_STATUS = (429, 500, 502, 503, 504)

BACKOFF_BASE = 0.1    # seconds; doubles per attempt (pre-jitter ceiling)
BACKOFF_CAP = 30.0    # ceiling on any single backoff window
RETRY_AFTER_CAP = 60.0  # never trust a Retry-After past this

Response = Tuple[int, Dict[str, str], bytes]

# module-level so tests can seed it for deterministic jitter
_rng = random.Random()


def request_with_retries(perform: Callable[[], Response],
                         ok: Tuple[int, ...],
                         describe: str) -> Response:
    """Run ``perform`` (one full connect+send+read) with retry.

    Transport failures and transient statuses retry up to
    ``S3_MAX_ERROR_RETRY`` times (default 3) with full-jitter doubling
    backoff, honoring ``Retry-After`` and the ``DMLC_NET_RETRY_DEADLINE``
    total budget; ``perform`` is called fresh each attempt, so
    time-sensitive signatures re-sign.  Statuses in ``ok`` are returned
    immediately; non-ok final statuses are returned to the caller to report
    (not raised here).
    """
    max_retry = get_env("S3_MAX_ERROR_RETRY", int, 3)
    deadline_s = get_env("DMLC_NET_RETRY_DEADLINE", float, 0.0)
    start = time.monotonic()
    for attempt in range(max_retry + 1):
        try:
            injected = (fault.http_response("net.request", describe=describe,
                                            attempt=attempt)
                        if fault.enabled() else None)
            if injected is not None:
                status, headers, data = injected
            else:
                if fault.enabled():
                    fault.inject("net.request", describe=describe,
                                 attempt=attempt)
                status, headers, data = perform()
        except RETRYABLE_EXC as exc:
            if attempt >= max_retry:
                telemetry.count("dmlc_net_retry_exhausted_total",
                                status_class="transport")
                raise
            sleep_s = _backoff(attempt, None, deadline_s, start)
            if sleep_s is None:
                telemetry.count("dmlc_net_retry_deadline_total",
                                status_class="transport")
                logger.warning("%s: retry deadline (%gs) reached; giving up "
                               "after %d attempt(s): %s", describe,
                               deadline_s, attempt + 1, exc)
                raise
            logger.warning("re-establishing connection (%s, retry %d): %s",
                           describe, attempt + 1, exc)
            _note_retry("transport", sleep_s)
            time.sleep(sleep_s)
            continue
        if status in RETRYABLE_STATUS and status not in ok \
                and attempt < max_retry:
            sleep_s = _backoff(attempt, _retry_after(headers), deadline_s,
                               start)
            if sleep_s is None:
                telemetry.count("dmlc_net_retry_deadline_total",
                                status_class=_status_class(status))
                logger.warning("%s returned %d; retry deadline (%gs) "
                               "reached, giving up", describe, status,
                               deadline_s)
                return status, headers, data
            logger.warning("%s returned %d; retry %d", describe, status,
                           attempt + 1)
            _note_retry(_status_class(status), sleep_s)
            time.sleep(sleep_s)
            continue
        if status in RETRYABLE_STATUS and attempt >= max_retry:
            telemetry.count("dmlc_net_retry_exhausted_total",
                            status_class=_status_class(status))
        return status, headers, data
    raise AssertionError("unreachable")


def _backoff(attempt: int, retry_after: Optional[float],
             deadline_s: float, start: float) -> Optional[float]:
    """One backoff decision: full-jitter window for ``attempt`` (0-based),
    raised to the server's Retry-After when present, or None when the sleep
    would cross the total deadline (the caller stops retrying)."""
    window = min(BACKOFF_CAP, BACKOFF_BASE * (2 ** attempt))
    delay = _rng.uniform(0.0, window)
    if retry_after is not None:
        delay = max(delay, min(retry_after, RETRY_AFTER_CAP))
    if deadline_s and (time.monotonic() - start) + delay > deadline_s:
        return None
    return delay


def _retry_after(headers: Dict[str, str]) -> Optional[float]:
    """Parse a Retry-After header (delta-seconds or HTTP-date) to seconds."""
    value = None
    for key, v in headers.items():
        if key.lower() == "retry-after":
            value = v
            break
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        dt = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return max(0.0, dt.timestamp() - time.time())


def _status_class(status: int) -> str:
    """Coarse status bucket for metric labels ("4xx"/"5xx")."""
    return f"{status // 100}xx"


def _note_retry(status_class: str, backoff_s: float) -> None:
    """One retry decision -> the dmlc_net_retry_* metric family."""
    if not telemetry.enabled():
        return
    telemetry.count("dmlc_net_retry_retries_total",
                    status_class=status_class)
    telemetry.count("dmlc_net_retry_backoff_seconds_total", backoff_s,
                    status_class=status_class)
