"""Connection-reestablishing retry shared by the remote-FS HTTP clients.

The reference re-connects on curl errors and short reads
(src/io/s3_filesys.cc:318-341, 703-733).  Every client here opens a fresh
connection per request, so a retry IS a re-connect; this module is the one
place the transport failure set, transient status set, and backoff policy
live, so the S3/GCS and Azure clients cannot drift.
"""

from __future__ import annotations

import http.client
import logging
import socket
import ssl
import time
from typing import Callable, Dict, Tuple

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.param import get_env

__all__ = ["RETRYABLE_EXC", "RETRYABLE_STATUS", "request_with_retries"]

logger = logging.getLogger("dmlc_core_tpu.io.net")

# transport-level failures worth re-establishing a connection for
RETRYABLE_EXC = (ConnectionError, socket.timeout, ssl.SSLError,
                 http.client.IncompleteRead, http.client.BadStatusLine,
                 http.client.CannotSendRequest, http.client.ResponseNotReady)
# server statuses that are transient by contract (503 SlowDown on S3,
# 429 rateLimitExceeded on the GCS interop API / Azure throttling,
# 5xx incl. 504 from front-end proxies)
RETRYABLE_STATUS = (429, 500, 502, 503, 504)

Response = Tuple[int, Dict[str, str], bytes]


def request_with_retries(perform: Callable[[], Response],
                         ok: Tuple[int, ...],
                         describe: str) -> Response:
    """Run ``perform`` (one full connect+send+read) with retry.

    Transport failures and transient statuses retry up to
    ``S3_MAX_ERROR_RETRY`` times (default 3) with 100 ms doubling backoff;
    ``perform`` is called fresh each attempt, so time-sensitive signatures
    re-sign.  Statuses in ``ok`` are returned immediately; non-ok final
    statuses are returned to the caller to report (not raised here).
    """
    max_retry = get_env("S3_MAX_ERROR_RETRY", int, 3)
    delay = 0.1
    for attempt in range(max_retry + 1):
        try:
            status, headers, data = perform()
        except RETRYABLE_EXC as exc:
            if attempt >= max_retry:
                telemetry.count("dmlc_net_retry_exhausted_total",
                                status_class="transport")
                raise
            logger.warning("re-establishing connection (%s, retry %d): %s",
                           describe, attempt + 1, exc)
            _note_retry("transport", delay)
            time.sleep(delay)
            delay *= 2
            continue
        if status in RETRYABLE_STATUS and status not in ok \
                and attempt < max_retry:
            logger.warning("%s returned %d; retry %d", describe, status,
                           attempt + 1)
            _note_retry(_status_class(status), delay)
            time.sleep(delay)
            delay *= 2
            continue
        if status in RETRYABLE_STATUS and attempt >= max_retry:
            telemetry.count("dmlc_net_retry_exhausted_total",
                            status_class=_status_class(status))
        return status, headers, data
    raise AssertionError("unreachable")


def _status_class(status: int) -> str:
    """Coarse status bucket for metric labels ("4xx"/"5xx")."""
    return f"{status // 100}xx"


def _note_retry(status_class: str, backoff_s: float) -> None:
    """One retry decision -> the dmlc_net_retry_* metric family."""
    if not telemetry.enabled():
        return
    telemetry.count("dmlc_net_retry_retries_total",
                    status_class=status_class)
    telemetry.count("dmlc_net_retry_backoff_seconds_total", backoff_s,
                    status_class=status_class)
