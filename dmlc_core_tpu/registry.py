"""Name -> factory registries with aliases.

Capability parity with the reference's ``dmlc::Registry<EntryType>``
(include/dmlc/registry.h:26-304): per-entry-type singleton registries, alias
registration (registry.h:62-72), and declarative registration macros — here a
decorator.  Registries underpin the parser/data factories (reference
src/data.cc:150-159) and our ops/model/filesystem factories.

Usage::

    parsers = Registry.get("parser")

    @parsers.register("libsvm", aliases=["svm"])
    def make_libsvm(source, nthread):
        ...

    entry = parsers.find("svm")
    parser = entry(source, nthread=2)
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Registry", "RegistryEntry"]


class RegistryEntry:
    """One registered factory (reference FunctionRegEntryBase, registry.h:146-222)."""

    def __init__(self, name: str, body: Callable[..., Any], description: str = ""):
        self.name = name
        self.body = body
        self.description = description
        self.aliases: List[str] = []

    def describe(self, description: str) -> "RegistryEntry":
        self.description = description
        return self

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.body(*args, **kwargs)

    def __repr__(self) -> str:
        return f"RegistryEntry({self.name!r})"


class Registry:
    """A singleton-per-name registry (reference Registry<E>::Get, registry.h:26-122)."""

    _registries: Dict[str, "Registry"] = {}
    _lock = threading.Lock()

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, RegistryEntry] = {}

    @classmethod
    def get(cls, kind: str) -> "Registry":
        """Return the global registry for ``kind``, creating it on first use."""
        with cls._lock:
            reg = cls._registries.get(kind)
            if reg is None:
                reg = cls._registries[kind] = Registry(kind)
            return reg

    # -- registration --------------------------------------------------------
    def register(
        self,
        name: str,
        aliases: Optional[List[str]] = None,
        description: str = "",
        override: bool = False,
    ) -> Callable[[Callable[..., Any]], Callable[..., Any]]:
        """Decorator registering a factory under ``name`` (+ aliases).

        Double registration of the same name raises unless ``override=True``
        (the reference fails a CHECK, registry.h:82-85).
        """

        def deco(body: Callable[..., Any]) -> Callable[..., Any]:
            self.add(name, body, aliases=aliases, description=description,
                     override=override)
            return body

        return deco

    def add(
        self,
        name: str,
        body: Callable[..., Any],
        aliases: Optional[List[str]] = None,
        description: str = "",
        override: bool = False,
    ) -> RegistryEntry:
        entry = RegistryEntry(name, body, description)
        with self._lock:
            if name in self._entries and not override:
                raise KeyError(f"{self.kind} registry: name {name!r} already registered")
            self._entries[name] = entry
            for alias in aliases or []:
                existing = self._entries.get(alias)
                if existing is not None and existing.name != name and not override:
                    raise KeyError(
                        f"{self.kind} registry: alias {alias!r} already bound to "
                        f"{existing.name!r}"
                    )
                self._entries[alias] = entry
                entry.aliases.append(alias)
        return entry

    # -- lookup ---------------------------------------------------------------
    def find(self, name: str) -> Optional[RegistryEntry]:
        """Find an entry by name or alias; None when absent (registry.h:48-56)."""
        return self._entries.get(name)

    def __getitem__(self, name: str) -> RegistryEntry:
        entry = self.find(name)
        if entry is None:
            raise KeyError(
                f"{self.kind} registry: unknown name {name!r}; "
                f"known: {sorted(self.list_names())}"
            )
        return entry

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def list_names(self) -> List[str]:
        """Canonical (non-alias) names (reference ListAllNames, registry.h:41-46)."""
        return sorted({e.name for e in self._entries.values()})

    def remove(self, name: str) -> None:
        with self._lock:
            entry = self._entries.pop(name, None)
            if entry is not None:
                for alias in entry.aliases:
                    self._entries.pop(alias, None)
