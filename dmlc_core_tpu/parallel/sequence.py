"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference predates LLMs (SURVEY.md §5.7): its only "long input" machinery
is streamed chunked reads.  The TPU-native framework makes long-context
first-class with the two standard sequence-parallel schemes, built on XLA
collectives over ICI:

- :func:`ring_attention` — blockwise attention with the KV shard rotating
  around the mesh-axis ring via ``lax.ppermute``, combined with the online
  (flash-style) softmax accumulator, so sequences scale with the number of
  devices while each device only ever holds its own Q shard and one KV block.
  Communication overlaps compute under XLA's scheduler (ppermute is async).
- :func:`ulysses_attention` — all-to-all resharding: sequence-sharded inputs
  are transposed to head-sharded via ``lax.all_to_all``, attention runs
  locally over full sequence length per head group, and the output transposes
  back.  Right when heads >= devices and full-sequence kernels are preferred.

Both are exact (match full attention to float tolerance) and jit-compiled via
shard_map over a named mesh axis.  Both are differentiable — jax autodiff
composes through the ppermute scan / all_to_all, and the gradients match
full-attention gradients (tests/test_sequence.py) — so long-context
TRAINING, not just inference, rides these paths.
"""

from __future__ import annotations

import functools
from typing import Optional

from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["ring_attention", "ulysses_attention", "reference_attention"]


def reference_attention(q, k, v, causal: bool = False, sm_scale: Optional[float] = None):
    """Plain full attention (the correctness oracle). Shapes [B, L, H, D]."""
    import jax.numpy as jnp

    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        L, Lk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(Lk)[None, :] > jnp.arange(L)[:, None]
        s = jnp.where(mask[None, None], -jnp.inf, s)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _ring_attention_local(q, k, v, axis: str, axis_size: int, causal: bool,
                          sm_scale: Optional[float]):
    """Per-shard kernel: local Q stays put, KV blocks rotate the ring."""
    import jax.lax as lax
    import jax.numpy as jnp

    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    scale = sm_scale if sm_scale is not None else D ** -0.5
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    neg_inf = jnp.finfo(jnp.float32).min

    def step(carry, t):
        o, m, l, k_cur, v_cur = carry
        # the block we hold at step t originated at rank (my - t) mod n
        src = (my - t) % axis_size
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = my * Lq + jnp.arange(Lq)
            k_pos = src * Lk + jnp.arange(Lk)
            mask = k_pos[None, :] > q_pos[:, None]
            s = jnp.where(mask[None, None], neg_inf, s)
        m_new = jnp.maximum(m, s.max(-1))
        # rows with no visible keys yet keep m at -inf; guard the exp
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        if causal:
            p = jnp.where(mask[None, None], 0.0, p)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * alpha + p.sum(-1)
        o_new = (o * alpha.transpose(0, 2, 1)[..., None]
                 + jnp.einsum("bhqk,bkhd->bqhd", p, v_cur,
                              preferred_element_type=jnp.float32))
        k_next = lax.ppermute(k_cur, axis, perm)
        v_next = lax.ppermute(v_cur, axis, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((B, Lq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Lq), neg_inf, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)
    # mark the fresh accumulators as device-varying over the ring axis so the
    # scan carry type matches the per-shard outputs (jax >= 0.6 vma).  pcast
    # is the current spelling; pvary its deprecated predecessor (probe pcast
    # FIRST — jax 0.9 fires the DeprecationWarning even on hasattr(pvary)).
    if hasattr(lax, "pcast"):
        o0, m0, l0 = (lax.pcast(x, (axis,), to="varying")
                      for x in (o0, m0, l0))
    elif hasattr(lax, "pvary"):
        o0, m0, l0 = (lax.pvary(x, (axis,)) for x in (o0, m0, l0))
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(axis_size))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


@functools.lru_cache(maxsize=None)
def _ring_fn(mesh, axis: str, causal: bool, sm_scale):
    import jax
    from jax.sharding import PartitionSpec as P

    from dmlc_core_tpu.parallel.compat import get_shard_map

    n = mesh.shape[axis]
    shard_map = get_shard_map()
    spec = P(None, axis, None, None)

    def kernel(q, k, v):
        return _ring_attention_local(q, k, v, axis, n, causal, sm_scale)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec))


def ring_attention(q, k, v, mesh, axis: str = "data", causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Exact attention over sequence-sharded [B, L, H, D] inputs.

    L must divide by the axis size; each device holds L/n of Q, K, V and peak
    memory is O(L/n * L/n) per step instead of O(L^2).
    """
    CHECK(q.shape[1] % mesh.shape[axis] == 0,
          "sequence length must divide the mesh axis size")
    return _ring_fn(mesh, axis, causal, sm_scale)(q, k, v)


@functools.lru_cache(maxsize=None)
def _ulysses_fn(mesh, axis: str, causal: bool, sm_scale):
    import jax
    import jax.lax as lax
    from jax.sharding import PartitionSpec as P

    from dmlc_core_tpu.parallel.compat import get_shard_map

    n = mesh.shape[axis]
    shard_map = get_shard_map()
    spec = P(None, axis, None, None)

    def kernel(q, k, v):
        # [B, L/n, H, D] -> [B, L, H/n, D]: split heads, gather sequence
        def to_heads(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        def to_seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        oh = reference_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale)
        return to_seq(oh)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec))


def ulysses_attention(q, k, v, mesh, axis: str = "data", causal: bool = False,
                      sm_scale: Optional[float] = None):
    """Exact attention via all-to-all head/sequence resharding.

    Requires H % axis_size == 0 and L % axis_size == 0.
    """
    n = mesh.shape[axis]
    CHECK(q.shape[2] % n == 0, "num heads must divide the mesh axis size")
    CHECK(q.shape[1] % n == 0, "sequence length must divide the mesh axis size")
    return _ulysses_fn(mesh, axis, causal, sm_scale)(q, k, v)
