"""Device-mesh construction and sharding helpers.

This layer replaces the reference tracker's tree/ring topology machinery
(tracker/dmlc_tracker/tracker.py:165-252): on TPU the torus topology is
hardware (ICI), so "topology awareness" surfaces as `jax.sharding.Mesh`
construction + NamedShardings, and the collectives ride ICI/DCN via XLA.
"""

from dmlc_core_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    make_hybrid_mesh,
    data_sharding,
    replicated_sharding,
    local_shard_info,
)
