"""Device-mesh construction, sharding helpers, and fleet-scale ingest.

This layer replaces the reference tracker's tree/ring topology machinery
(tracker/dmlc_tracker/tracker.py:165-252): on TPU the torus topology is
hardware (ICI), so "topology awareness" surfaces as `jax.sharding.Mesh`
construction + NamedShardings, and the collectives ride ICI/DCN via XLA.

:mod:`.fleet_ingest` is the host-side half of the fleet story: dynamic
work-stealing shard leases over the tracker control plane (see
docs/performance.md "Fleet ingest").  The mesh helpers import ``jax``;
``fleet_ingest`` is numpy-only — the names below resolve lazily (PEP 562)
so a spawned ingest worker importing this package never pays the jax
bring-up.
"""

_MESH_EXPORTS = (
    "make_mesh",
    "make_hybrid_mesh",
    "data_sharding",
    "replicated_sharding",
    "local_shard_info",
)

__all__ = list(_MESH_EXPORTS) + ["fleet_ingest"]


def __getattr__(name):
    if name in _MESH_EXPORTS:
        from dmlc_core_tpu.parallel import mesh

        return getattr(mesh, name)
    if name == "fleet_ingest":
        import importlib

        return importlib.import_module("dmlc_core_tpu.parallel.fleet_ingest")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
