"""Mesh + sharding helpers (the TPU-native "topology service").

The reference's rendezvous tracker computes a binary tree and a shared-node
ring over worker TCP sockets (tracker.py:185-252) for Rabit's allreduce.  On
TPU those topologies are obsolete: the ICI torus is physical, XLA chooses the
collective algorithm, and what remains of "topology" is *mesh shape* — how the
device grid is factored into named axes (data/model/...), and whether an axis
crosses slice boundaries (DCN) or stays inside a slice (ICI).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from dmlc_core_tpu.utils.logging import CHECK

__all__ = [
    "make_mesh",
    "make_hybrid_mesh",
    "data_sharding",
    "replicated_sharding",
    "local_shard_info",
]


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None):
    """Build a Mesh from named axis sizes, e.g. ``{"data": 4, "model": 2}``.

    One axis may be -1 (inferred).  Default: 1-D ``data`` mesh over all
    devices.  Uses ``mesh_utils.create_device_mesh`` so the assignment follows
    the physical ICI topology when running on TPU.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    ndev = len(devices)
    if not axes:
        axes = {"data": ndev}
    names = tuple(axes.keys())
    sizes = list(axes.values())
    n_infer = sum(1 for s in sizes if s == -1)
    CHECK(n_infer <= 1, "at most one mesh axis may be -1")
    if n_infer:
        known = int(np.prod([s for s in sizes if s != -1]))
        CHECK(ndev % known == 0, f"{ndev} devices not divisible by {known}")
        sizes = [ndev // known if s == -1 else s for s in sizes]
    CHECK(int(np.prod(sizes)) == ndev,
          f"mesh axes {dict(zip(names, sizes))} do not cover {ndev} devices")
    try:
        dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def make_hybrid_mesh(ici_axes: Dict[str, int], dcn_axes: Dict[str, int]):
    """Multi-slice mesh: ``dcn_axes`` cross slices (DCN), ``ici_axes`` stay
    within a slice (ICI) — e.g. ``make_hybrid_mesh({"model": 8}, {"data": 4})``
    for 4 slices of 8 chips.  This is how the reference's multi-host scale-out
    (tracker launching N hosts) maps onto TPU pods."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    dcn_shape = tuple(dcn_axes.values()) + tuple(1 for _ in ici_axes)
    ici_shape = tuple(1 for _ in dcn_axes) + tuple(ici_axes.values())
    import jax

    # virtual/CPU devices carry no usable slice_index, so the topology-aware
    # builder cannot run there; a plain reshape (dcn axes outermost) keeps
    # the axis semantics so the hybrid layout stays testable off-hardware.
    # On real sliced hardware a builder failure is a REAL error (a silent
    # reshape would put ICI-named axes across DCN links) and propagates.
    sliced_hw = any(getattr(d, "slice_index", None) is not None
                    for d in jax.devices())
    if sliced_hw:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, allow_split_physical_axes=True)
    else:
        shape = tuple(d * i for d, i in zip(dcn_shape, ici_shape))
        ndev = int(np.prod(shape))
        devices = np.asarray(jax.devices()[:ndev])
        CHECK(len(devices) == ndev,
              f"hybrid mesh {dict(zip(names, shape))} needs {ndev} devices")
        dev_array = devices.reshape(shape)
    return Mesh(dev_array, names)


def data_sharding(mesh, axis: str = "data", ndim: int = 1):
    """NamedSharding placing dim 0 on ``axis``, rest replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(axis, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def local_shard_info() -> Tuple[int, int]:
    """(part_index, num_parts) for this process — the InputSplit shard this
    host should read (SURVEY.md §7 stage 4: per-host shard = process index)."""
    import jax

    return jax.process_index(), jax.process_count()
