"""Small version-compat shims for jax API moves."""

from __future__ import annotations


def get_shard_map():
    """jax.shard_map (new) or jax.experimental.shard_map.shard_map (old)."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map  # type: ignore

    return shard_map
