"""Small version-compat shims for jax API moves."""

from __future__ import annotations


def get_shard_map():
    """jax.shard_map (new) or jax.experimental.shard_map.shard_map (old)."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map  # type: ignore

    return shard_map


def shard_map_unchecked(fn, mesh, in_specs, out_specs):
    """shard_map with the output-sharding check disabled, across the kwarg
    rename (``check_vma`` today, ``check_rep`` before jax 0.6)."""
    sm = get_shard_map()
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)
