"""Fleet-scale ingest: dynamic shard leasing with work-stealing workers.

The reference distributes input by deterministic byte-range sharding
(``InputSplit::ResetPartition``) with a static ``k % n`` assignment: one
slow rank gates the epoch, and a dead rank silently loses its slice.
This module is the data-plane half of the dynamic replacement
(docs/performance.md "Fleet ingest"); the control plane — the
authoritative unit ledger with heartbeat-renewed leases and expiry
reassignment — is
:class:`dmlc_core_tpu.tracker.rendezvous.ShardLeaseCoordinator`.

- :func:`plan_units` splits one input URI into many more **work units**
  than workers: each unit is an opaque JSON spec naming a
  ``(part, nparts)`` shard of the URI — byte-range shards for text
  formats (the ``reset_partition`` math), row-group/record-batch units
  for parquet/arrow (the columnar parsers shard ``k % n`` by unit);
- :class:`LeaseClient` speaks the framed lease protocol (one short
  conversation per op, so no lock ever spans a socket read);
- :func:`run_worker` is the worker loop: acquire -> drive the unit
  through the existing stack (``create_parser`` — the ``DMLC_PARSE_PROC``
  fan-out, remote page-cache fetch, and columnar ingest all engage
  exactly as they would single-host) -> densify to device-ready batches
  -> commit.  A commit rejected because the lease expired and moved means
  those rows are **discarded, not counted** — coverage stays exactly-once
  by construction.  A daemon heartbeat renews all held leases every
  ``lease_timeout / 3``; when the process dies, the heartbeat dies with
  it and the coordinator reassigns.

Observability: ``ingest.lease`` spans bracket waiting for a grant,
``ingest.unit`` spans bracket unit processing, and
``dmlc_fleet_worker_{rows,busy_seconds}_total{worker=...}`` give
per-worker rows/s (rows ÷ busy-seconds).  The ``io.fleet.lease`` fault
site fires before every wire op (``ctx: op=, worker=``) — chaos plans
kill workers mid-unit, stall stragglers, and reset the control link.

Cross-rank-consistent binning rides along: pass ``binner_bins=`` and the
worker accumulates fixed-size quantile summaries
(:func:`~dmlc_core_tpu.ops.histogram.local_quantile_summary`) over every
densified chunk it ingests; :func:`fleet_binner` then merges them through
:func:`~dmlc_core_tpu.bridge.binning.fit_binner_from_summaries` — with a
rabit-shaped ``comm`` every rank gets bitwise-identical bin edges even
though dynamic leasing gave each rank a different, non-deterministic
unit set.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.param import get_env
from dmlc_core_tpu.telemetry import clock
from dmlc_core_tpu.tracker.rendezvous import (DEFAULT_LEASE_TIMEOUT,
                                              LEASE_MAGIC, FramedSocket,
                                              ProtocolError, TrackerError)
from dmlc_core_tpu.utils.logging import log_warning

__all__ = ["plan_units", "LeaseClient", "WorkerResult", "run_worker",
           "default_unit_processor", "fleet_binner"]


def plan_units(uri: str, num_workers: int, *,
               units_per_worker: Optional[int] = None,
               num_units: Optional[int] = None,
               fmt: str = "auto", nthread: int = 1, threaded: bool = False,
               dense_features: Optional[int] = None,
               ledger_labels: bool = False) -> List[str]:
    """Split ``uri`` into work-unit specs (JSON strings) for the coordinator.

    The unit count defaults to ``num_workers * DMLC_FLEET_UNITS_PER_WORKER``
    (8): enough granularity that a straggler sheds load and a dead
    worker's loss re-spreads, without drowning the epoch in per-unit
    parser construction (sizing table in docs/performance.md).  Each unit
    is a ``(part, nparts)`` shard: exactly-once coverage of the input is
    the shard math's partition property plus the coordinator's
    exactly-once unit commits.

    ``dense_features`` makes workers densify every block to contiguous
    float32 ``[n, F]`` (the device-ready batch form);
    ``ledger_labels`` adds per-unit label id sum/xor to the commit payload
    (the chaos suite's every-row-exactly-once ground-truth check).
    """
    upw = (units_per_worker if units_per_worker is not None
           else get_env("DMLC_FLEET_UNITS_PER_WORKER", int, 8))
    n = num_units or max(1, num_workers) * max(1, upw)
    spec: Dict[str, Any] = {"uri": uri, "nparts": n, "format": fmt,
                            "nthread": nthread, "threaded": threaded}
    if dense_features:
        spec["dense_features"] = int(dense_features)
    if ledger_labels:
        spec["ledger_labels"] = True
    return [json.dumps(dict(spec, part=k)) for k in range(n)]


class LeaseClient:
    """Framed-protocol client for the shard-lease coordinator.

    One short TCP conversation per op — the heartbeat thread and the main
    loop never share a socket, so no lock spans a blocking read.
    Transient connection failures (including injected ``reset`` faults at
    ``io.fleet.lease``) retry with backoff; wire-protocol violations
    raise :class:`ProtocolError` immediately.
    """

    def __init__(self, host: str, port: int, worker_id: str, *,
                 timeout: float = 30.0, retries: int = 3):
        self.host = host
        self.port = int(port)
        self.worker_id = worker_id
        self.timeout = timeout
        self.retries = retries

    def _op(self, cmd: str, send_fn: Callable[[FramedSocket], None],
            recv_fn: Callable[[FramedSocket], Any]) -> Any:
        last: Optional[BaseException] = None
        for attempt in range(self.retries):
            try:
                # the fault site fires per ATTEMPT, before any byte moves:
                # an 'exit' rule kills this worker while it still holds
                # its leases, a 'reset' raises into the retry path below
                fault.inject("io.fleet.lease", op=cmd, worker=self.worker_id)
                sock = socket.create_connection((self.host, self.port),
                                                timeout=self.timeout)
            except OSError as err:
                last = err
                time.sleep(0.05 * (attempt + 1))
                continue
            try:
                sk = FramedSocket(sock, timeout=self.timeout)
                sk.sendint(LEASE_MAGIC)
                magic = sk.recvint()
                if magic != LEASE_MAGIC:
                    raise ProtocolError(
                        f"bad magic {magic:#x} from lease coordinator "
                        f"{self.host}:{self.port}")
                sk.sendstr(self.worker_id)
                sk.sendstr(cmd)
                send_fn(sk)
                return recv_fn(sk)
            except (ConnectionError, socket.timeout, OSError) as err:
                last = err
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
            time.sleep(0.05 * (attempt + 1))
        raise TrackerError(
            f"lease coordinator {self.host}:{self.port} unreachable for "
            f"{cmd!r} after {self.retries} attempts: {last!r} (a "
            "connection closed with no reply can also be the coordinator "
            "REJECTING the request — bad worker_index for a static-mode "
            "coordinator, malformed frame; check its log)")

    def acquire(self, worker_index: int = -1):
        """(unit_id, spec-json|None): ``-1`` = poll again, ``-2`` = done."""
        def recv(sk: FramedSocket):
            unit_id = sk.recvint()
            return unit_id, (sk.recvstr() if unit_id >= 0 else None)

        return self._op("acquire",
                        lambda sk: sk.sendint(worker_index), recv)

    def renew(self) -> int:
        """Heartbeat: renew every lease this worker holds; returns count."""
        return self._op("renew", lambda sk: None,
                        lambda sk: sk.recvint())

    def commit(self, unit_id: int, payload: Dict[str, Any]) -> bool:
        """True when the coordinator accepted this unit's commit."""
        def send(sk: FramedSocket) -> None:
            sk.sendint(unit_id)
            sk.sendstr(json.dumps(payload))

        return self._op("commit", send,
                        lambda sk: sk.recvint() == 1)


class _SummaryAccumulator:
    """Fixed-size quantile summaries over every densified chunk — the
    worker-local half of the cross-rank binner fit."""

    def __init__(self, num_bins: int):
        from dmlc_core_tpu.bridge.binning import default_summary_points

        self.num_bins = num_bins
        self.num_points = default_summary_points(num_bins)
        self._points: List[np.ndarray] = []
        self._counts: List[np.ndarray] = []

    def add(self, x: np.ndarray) -> None:
        from dmlc_core_tpu.ops.histogram import local_quantile_summary

        pts, cnt = local_quantile_summary(np.asarray(x, dtype=np.float32),
                                          self.num_points)
        self._points.append(pts)
        self._counts.append(cnt)

    def absorb(self, other: "_SummaryAccumulator") -> None:
        self._points.extend(other._points)
        self._counts.extend(other._counts)

    def stacked(self):
        if not self._points:
            return None, None
        return np.stack(self._points), np.stack(self._counts)


@dataclass
class WorkerResult:
    """One worker's view of its epoch (the coordinator ledger stays the
    authoritative exactly-once record)."""

    worker_id: str
    rows: int = 0
    units_committed: int = 0
    units_rejected: int = 0
    unit_ids: List[int] = field(default_factory=list)
    busy_seconds: float = 0.0
    summary_points: Optional[np.ndarray] = None   # [C, F, K] when binning
    summary_counts: Optional[np.ndarray] = None   # [C, F]
    binner_bins: Optional[int] = None


def default_unit_processor(spec: Dict[str, Any],
                           accum: Optional[_SummaryAccumulator] = None
                           ) -> Dict[str, Any]:
    """Drive one unit through the existing ingest stack.

    Builds a parser for the unit's ``(part, nparts)`` shard of the URI —
    every single-host capability engages unchanged underneath: the
    ``DMLC_PARSE_PROC`` process fan-out, the fleet-shared remote page
    cache, the columnar front door.  With ``dense_features`` each block
    is densified to a contiguous float32 ``[n, F]`` array (the
    device-ready form ``jax.device_put`` ships as-is) and fed to the
    binner accumulator when one is active.  Returns the commit payload
    (``rows`` + optional label-id ledger fields).
    """
    from dmlc_core_tpu.data.factory import create_parser

    parser = create_parser(spec["uri"], int(spec.get("part", 0)),
                           int(spec.get("nparts", 1)),
                           type=spec.get("format", "auto"),
                           nthread=int(spec.get("nthread", 1)),
                           threaded=bool(spec.get("threaded", False)))
    rows = 0
    batches = 0
    id_sum = 0
    id_xor = 0
    dense = int(spec.get("dense_features") or 0)
    ledger = bool(spec.get("ledger_labels"))
    try:
        for block in parser:
            rows += block.size
            if ledger and block.size:
                ids = np.asarray(block.label, dtype=np.int64)
                id_sum += int(ids.sum())
                id_xor ^= int(np.bitwise_xor.reduce(ids))
            if dense and block.size:
                from dmlc_core_tpu.bridge.batching import block_to_dense

                x = np.ascontiguousarray(
                    block_to_dense(block, dense).x, dtype=np.float32)
                batches += 1
                if accum is not None:
                    accum.add(x)
    finally:
        if hasattr(parser, "close"):
            parser.close()
    payload: Dict[str, Any] = {"rows": rows, "batches": batches}
    if ledger:
        payload["id_sum"] = id_sum
        payload["id_xor"] = id_xor
    return payload


def run_worker(worker_id: str, host: Optional[str] = None,
               port: Optional[int] = None, *,
               worker_index: int = -1,
               processor: Optional[Callable[..., Dict[str, Any]]] = None,
               binner_bins: Optional[int] = None,
               lease_timeout: Optional[float] = None,
               poll_seconds: float = 0.05) -> WorkerResult:
    """Worker loop: acquire -> process -> commit until the coordinator says
    done.  Spawn-safe (plain args), so it is the ``multiprocessing`` /
    launcher target for local fleets and the ``fleet-ab`` bench.

    ``host``/``port`` default to the coordinator's
    ``DMLC_FLEET_LEASE_URI`` / ``DMLC_FLEET_LEASE_PORT`` env contract
    (:meth:`ShardLeaseCoordinator.worker_envs`).  ``worker_index`` only
    matters under a static-mode coordinator (the ``k % n`` residue this
    worker owns).  ``lease_timeout`` must match the coordinator's
    (both default to ``DMLC_FLEET_LEASE_TIMEOUT``); the heartbeat renews
    at a third of it.
    """
    host = host or get_env("DMLC_FLEET_LEASE_URI", str, "127.0.0.1")
    if port is None:
        port = get_env("DMLC_FLEET_LEASE_PORT", int, 0)
    if not port:
        raise ValueError("run_worker needs the coordinator port "
                         "(arg or DMLC_FLEET_LEASE_PORT)")
    lease = (lease_timeout if lease_timeout is not None
             else get_env("DMLC_FLEET_LEASE_TIMEOUT", float,
                          DEFAULT_LEASE_TIMEOUT))
    client = LeaseClient(host, port, worker_id)
    accum = _SummaryAccumulator(binner_bins) if binner_bins else None
    process = processor or default_unit_processor
    result = WorkerResult(worker_id=worker_id, binner_bins=binner_bins)

    stop_hb = threading.Event()

    def _heartbeat() -> None:
        while not stop_hb.wait(lease / 3.0):
            try:
                client.renew()
            except Exception as exc:  # noqa: BLE001 — non-fatal by design
                # recorded, not ferried: a dead coordinator surfaces
                # loudly at the main loop's next wire op either way
                log_warning(f"worker {worker_id}: lease renew failed "
                            f"({exc!r}); leases may expire")

    hb = threading.Thread(target=_heartbeat, daemon=True,
                          name=f"fleet-hb-{worker_id}")
    hb.start()
    wait_start = clock.monotonic()
    try:
        while True:
            unit_id, spec_json = client.acquire(worker_index)
            if unit_id == -2:
                break
            if unit_id == -1:
                time.sleep(poll_seconds)
                continue
            telemetry.record_span("ingest.lease", wait_start,
                                  clock.monotonic(), worker=worker_id,
                                  unit=unit_id)
            spec = json.loads(spec_json)
            # summaries stage into a PER-UNIT accumulator and are absorbed
            # only on an accepted commit: a rejected unit's rows were (or
            # will be) ingested by the lease's new holder, and keeping its
            # summaries here would double that unit's mass in the fleet
            # binner edges
            unit_accum = (_SummaryAccumulator(binner_bins) if binner_bins
                          else None)
            t0 = clock.monotonic()
            with telemetry.span("ingest.unit", worker=worker_id,
                                unit=unit_id) as sp:
                payload = process(spec, unit_accum)
                sp.set(rows=payload.get("rows", 0))
            busy = clock.monotonic() - t0
            if client.commit(unit_id, payload):
                if accum is not None:
                    accum.absorb(unit_accum)
                result.rows += int(payload.get("rows", 0))
                result.units_committed += 1
                result.unit_ids.append(unit_id)
                result.busy_seconds += busy
                telemetry.count("dmlc_fleet_worker_rows_total",
                                int(payload.get("rows", 0)),
                                worker=worker_id)
                telemetry.count("dmlc_fleet_worker_busy_seconds_total",
                                busy, worker=worker_id)
            else:
                # the lease expired and moved while we processed: the unit
                # is (or will be) committed by its new holder — counting
                # these rows too would double them, so they are discarded
                result.units_rejected += 1
                log_warning(f"worker {worker_id}: commit of unit {unit_id} "
                            "rejected (lease moved); rows discarded")
            wait_start = clock.monotonic()
    finally:
        stop_hb.set()
        hb.join(timeout=2.0)
    if accum is not None:
        result.summary_points, result.summary_counts = accum.stacked()
    return result


def fleet_binner(result: WorkerResult, *, comm=None,
                 handle_missing: bool = False):
    """Fit this rank's :class:`HostBinner` from the summaries a
    ``binner_bins``-enabled :func:`run_worker` accumulated.

    With a rabit-shaped ``comm`` the merge is the
    :func:`fit_binner_from_summaries` allgather path: every rank returns
    bitwise-identical edges even though dynamic leasing gave each a
    different unit set (the cross-rank-consistency contract of
    ``fit_binner(comm=...)``, now multi-worker for real).

    Only committed units contribute (a rejected unit's summaries are
    dropped — its rows belong to the lease's new holder), and only the
    zero-fill densification is supported: ``handle_missing=True`` needs
    NaN-filled chunks (missing carries no rank mass), which the fleet
    processor does not produce — it raises rather than return
    silently-skewed edges.
    """
    from dmlc_core_tpu.bridge.binning import fit_binner_from_summaries

    if handle_missing:
        raise ValueError(
            "fleet_binner does not support handle_missing=True: the fleet "
            "processor densifies absent features to 0.0, so the "
            "accumulated summaries carry fabricated zero mass where the "
            "missing-bin contract needs NaN (zero mass); fit the missing-"
            "aware binner with bridge.binning.fit_binner over the source")
    if result.binner_bins is None or result.summary_points is None:
        raise ValueError(
            "fleet_binner needs a run_worker(binner_bins=...) result that "
            "ingested at least one dense chunk")
    return fit_binner_from_summaries(
        result.summary_points, result.summary_counts, result.binner_bins,
        comm=comm)
