"""The one monotonic clock for host-side metering.

Every hand-rolled ``time.perf_counter()`` idiom in utils/ (timer.get_time,
profiler.ThroughputMeter, profiler.device_timer) now routes through here, so
"what clock does telemetry use" has exactly one answer: ``perf_counter``,
monotonic, sub-microsecond resolution, meaningless across processes.

Span timestamps additionally need a per-process epoch so multiple ranks'
traces can be laid side by side in Perfetto: :func:`trace_time_us` is
microseconds since an arbitrary-but-fixed process start.  Wall-clock
(``time.time``) is only used to stamp exported snapshots, never to measure.
"""

from __future__ import annotations

import time

__all__ = ["monotonic", "elapsed", "trace_time_us", "to_trace_us"]

_PROCESS_EPOCH = time.perf_counter()


def monotonic() -> float:
    """Seconds on the process-wide monotonic clock."""
    return time.perf_counter()


def elapsed(start: float) -> float:
    """Seconds since ``start`` (a previous :func:`monotonic` reading)."""
    return time.perf_counter() - start


def trace_time_us() -> float:
    """Microseconds since process start — the Chrome-trace ``ts`` domain."""
    return (time.perf_counter() - _PROCESS_EPOCH) * 1e6


def to_trace_us(t: float) -> float:
    """Convert a :func:`monotonic` reading into the ``ts`` domain (for spans
    whose begin time was captured before the span was named)."""
    return (t - _PROCESS_EPOCH) * 1e6
