"""The one monotonic clock for host-side metering.

Every hand-rolled ``time.perf_counter()`` idiom in utils/ (timer.get_time,
profiler.ThroughputMeter, profiler.device_timer) now routes through here, so
"what clock does telemetry use" has exactly one answer: ``perf_counter``,
monotonic, sub-microsecond resolution, meaningless across processes.

Span timestamps additionally need a per-process epoch so multiple ranks'
traces can be laid side by side in Perfetto: :func:`trace_time_us` is
microseconds since an arbitrary-but-fixed process start.  Wall-clock
(``time.time``) is only used to stamp exported snapshots, never to measure
— with one deliberate exception: :func:`wall_epoch` records, once at
import, the wall-clock time corresponding to trace timestamp 0.  The
cross-process trace assembler (``telemetry trace``) uses it to shift each
process' monotonic timestamps onto one shared axis; NTP-grade skew (ms)
is fine for eyeballing a merged timeline, and no *measurement* ever reads
the wall clock.
"""

from __future__ import annotations

import time

__all__ = ["monotonic", "elapsed", "trace_time_us", "to_trace_us",
           "wall_epoch"]

_PROCESS_EPOCH = time.perf_counter()
# captured back-to-back with _PROCESS_EPOCH: the wall time of trace ts 0
_WALL_EPOCH = time.time()


def monotonic() -> float:
    """Seconds on the process-wide monotonic clock."""
    return time.perf_counter()


def elapsed(start: float) -> float:
    """Seconds since ``start`` (a previous :func:`monotonic` reading)."""
    return time.perf_counter() - start


def trace_time_us() -> float:
    """Microseconds since process start — the Chrome-trace ``ts`` domain."""
    return (time.perf_counter() - _PROCESS_EPOCH) * 1e6


def to_trace_us(t: float) -> float:
    """Convert a :func:`monotonic` reading into the ``ts`` domain (for spans
    whose begin time was captured before the span was named)."""
    return (t - _PROCESS_EPOCH) * 1e6


def wall_epoch() -> float:
    """``time.time()`` at trace timestamp 0 — the per-process anchor the
    trace assembler uses to align processes on one time axis."""
    return _WALL_EPOCH
