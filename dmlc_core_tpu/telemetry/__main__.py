"""CLI: ``python -m dmlc_core_tpu.telemetry {report,trace} <dir> [...]``."""

from __future__ import annotations

import argparse
import sys

from dmlc_core_tpu.telemetry import report, traceview


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m dmlc_core_tpu.telemetry",
        description="telemetry snapshot tooling")
    sub = parser.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser(
        "report", help="aggregate rank snapshots from a DMLC_TELEMETRY_DIR")
    rep.add_argument("dir", help="directory holding metrics-*.json snapshots")
    rep.add_argument("--json", action="store_true",
                     help="emit the merged result as JSON instead of a table")
    tr = sub.add_parser(
        "trace", help="assemble per-process span files + flight dumps into "
                      "one merged trace; critical path per trace_id")
    tr.add_argument("dir", help="directory holding trace-*.trace.json / "
                                "flight-*.json files")
    tr.add_argument("--out", default=None,
                    help="write the merged Perfetto trace JSON here")
    tr.add_argument("--json", action="store_true",
                    help="emit the assembly report as JSON")
    tr.add_argument("--top", type=int, default=10,
                    help="slowest-traces table length (default 10)")
    tr.add_argument("--fail-on-orphans", action="store_true",
                    help="exit 2 when any span's recorded parent is missing "
                         "from the merged set (the CI propagation gate)")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "report":
        return report.main(args.dir, as_json=args.json)
    if args.cmd == "trace":
        return traceview.main(args.dir, out=args.out, as_json=args.json,
                              top=args.top,
                              fail_on_orphans=args.fail_on_orphans)
    return 2


if __name__ == "__main__":
    sys.exit(main())
