"""CLI: ``python -m dmlc_core_tpu.telemetry report <dir> [--json]``."""

from __future__ import annotations

import argparse
import sys

from dmlc_core_tpu.telemetry import report


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m dmlc_core_tpu.telemetry",
        description="telemetry snapshot tooling")
    sub = parser.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser(
        "report", help="aggregate rank snapshots from a DMLC_TELEMETRY_DIR")
    rep.add_argument("dir", help="directory holding metrics-*.json snapshots")
    rep.add_argument("--json", action="store_true",
                     help="emit the merged result as JSON instead of a table")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "report":
        return report.main(args.dir, as_json=args.json)
    return 2


if __name__ == "__main__":
    sys.exit(main())
