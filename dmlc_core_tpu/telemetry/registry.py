"""Thread-safe process-wide metrics registry: counters, gauges, histograms.

The shape is Prometheus' data model cut down to what the pipeline needs:

- a **family** is a named metric of one kind (``counter``/``gauge``/
  ``histogram``) with optional help text;
- each distinct label set under a family is one **child** holding the actual
  value; the no-label child is keyed by the empty tuple;
- histograms use **fixed upper-bound buckets** chosen at registration
  (defaults suit request latencies in seconds) — observation is a bisect
  plus two adds, no allocation.

Everything mutating takes the child's own lock, so N writer threads produce
exact final counts (the GIL does not make ``+=`` on an attribute atomic).
Family creation takes the registry lock once; hot-path increments never do.

This module has no idea whether telemetry is enabled — the near-zero-overhead
disabled path lives in :mod:`dmlc_core_tpu.telemetry` (the module-level flag
is checked before any registry call or allocation happens).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricFamily", "MetricRegistry",
           "DEFAULT_BUCKETS"]

# request/op latencies in seconds; the +Inf bucket is implicit
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing float."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time float that can move both ways."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram: per-bucket counts + sum + count.

    ``buckets`` are inclusive upper bounds in ascending order; one extra
    +Inf bucket is always appended, so every observation lands somewhere.
    """

    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: Iterable[float] = DEFAULT_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(nxt <= prev
                             for nxt, prev in zip(bounds[1:], bounds)):
            raise ValueError(f"buckets must be ascending and non-empty: {bounds}")
        self._lock = threading.Lock()
        self.buckets = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        # upper bounds are inclusive (Prometheus le semantics): the index of
        # the first bound >= v
        idx = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count

    @property
    def bucket_counts(self) -> List[int]:
        """Per-bucket (non-cumulative) counts; last entry is the +Inf bucket."""
        with self._lock:
            return list(self._counts)

    def cumulative(self) -> List[int]:
        """Cumulative counts per upper bound, Prometheus ``le`` style."""
        out, running = [], 0
        for c in self.bucket_counts:
            running += c
            out.append(running)
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """One named metric with children per label set."""

    __slots__ = ("name", "kind", "help", "buckets", "_children", "_lock")

    def __init__(self, name: str, kind: str, help: str = "",
                 buckets: Optional[Iterable[float]] = None):
        if kind not in _KINDS:
            raise ValueError(f"unknown metric kind {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = tuple(buckets) if buckets is not None else DEFAULT_BUCKETS
        self._children: Dict[LabelKey, object] = {}
        self._lock = threading.Lock()

    def child(self, labels: Dict[str, object]):
        key = _label_key(labels)
        got = self._children.get(key)
        if got is None:
            with self._lock:
                got = self._children.get(key)
                if got is None:
                    got = (Histogram(self.buckets) if self.kind == "histogram"
                           else _KINDS[self.kind]())
                    self._children[key] = got
        return got

    def samples(self) -> List[Tuple[LabelKey, object]]:
        with self._lock:
            return sorted(self._children.items())


class MetricRegistry:
    """Process-wide family store.  All lookups are by (name, kind)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}

    def _family(self, name: str, kind: str, help: str = "",
                buckets: Optional[Iterable[float]] = None) -> MetricFamily:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = MetricFamily(name, kind, help, buckets)
                    self._families[name] = fam
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, not {kind}")
        if (kind == "histogram" and buckets is not None
                and tuple(float(b) for b in buckets) != fam.buckets):
            # same rigor as the kind clash: observations silently landing in
            # bounds the caller never asked for would be invisible until
            # someone reads the exported le= labels
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{fam.buckets}, not {tuple(buckets)}")
        return fam

    def counter(self, name: str, help: str = "", /, **labels) -> Counter:
        return self._family(name, "counter", help).child(labels)

    def gauge(self, name: str, help: str = "", /, **labels) -> Gauge:
        return self._family(name, "gauge", help).child(labels)

    def histogram(self, name: str, help: str = "", /, *,
                  buckets: Optional[Iterable[float]] = None,
                  **labels) -> Histogram:
        return self._family(name, "histogram", help, buckets).child(labels)

    def families(self) -> List[MetricFamily]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def reset(self) -> None:
        with self._lock:
            self._families.clear()
