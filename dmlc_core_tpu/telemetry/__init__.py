"""Unified metrics + span tracing for the io/tracker/collective hot paths.

The reference's observability was ``GetTime()`` plus inline "N MB, X MB/sec"
prints (src/data/basic_row_iter.h:70-75).  This package is the structured
replacement: a process-wide metrics registry (counters / gauges / fixed-
bucket histograms with labels), a span tracer exporting Chrome-trace JSON
(chrome://tracing / Perfetto) and JSONL, and Prometheus-text / JSON-snapshot
exporters with an atexit flush.  The per-subsystem metric catalog lives in
``docs/observability.md``.

Usage (instrumentation sites)::

    from dmlc_core_tpu import telemetry

    telemetry.count("dmlc_parser_rows_total", n, format="libsvm")
    telemetry.gauge_set("dmlc_threadediter_queue_depth", depth)
    telemetry.observe("dmlc_filesystem_request_seconds", dt, fs="s3")
    with telemetry.span("parser.parse_chunk", nbytes=len(chunk)):
        ...

**Disabled is the default and costs (almost) nothing**: every helper checks
the module-level ``_enabled`` flag before touching the registry, allocating,
or reading a clock; :func:`span` returns a shared no-op context manager.
Enable explicitly via :func:`enable`, or by environment:

- ``DMLC_TELEMETRY=1``     — enable collection;
- ``DMLC_TELEMETRY_DIR=d`` — enable collection AND flush every export form
  into ``d`` at interpreter exit (rank/pid-keyed filenames, aggregatable
  across ranks with ``python -m dmlc_core_tpu.telemetry report d``), and
  arm the flight recorder's abnormal-exit dumps (:mod:`.flight`).

Spans carry **distributed trace identity** when a trace context is active
(:mod:`.tracecontext`: W3C ``traceparent`` over HTTP headers /
``DMLC_TRACEPARENT`` env / explicit argument); assemble per-process span
files + crash dumps into one merged Perfetto trace with per-trace critical
paths via ``python -m dmlc_core_tpu.telemetry trace d``.

Telemetry helpers are **host-side only**: calling them inside jit/pallas-
traced code would bake one trace-time measurement into the compiled function
(at best) — the analysis purity pass flags exactly that
(``purity-telemetry-call``, see docs/analysis.md).
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Any, Dict, Iterable, Optional

from dmlc_core_tpu.telemetry import clock  # noqa: F401  (re-export)
from dmlc_core_tpu.telemetry import flight  # noqa: F401  (re-export)
from dmlc_core_tpu.telemetry import tracecontext  # noqa: F401  (re-export)
from dmlc_core_tpu.telemetry.registry import (DEFAULT_BUCKETS, Counter, Gauge,
                                              Histogram, MetricRegistry)
from dmlc_core_tpu.telemetry.spans import Span, SpanTracer

__all__ = [
    "enabled", "enable", "disable", "reset",
    "count", "gauge_set", "gauge_add", "observe", "span", "record_span",
    "event",
    "get_registry", "get_tracer",
    "snapshot", "prometheus_text", "flush",
    "Counter", "Gauge", "Histogram", "MetricRegistry", "SpanTracer", "Span",
    "DEFAULT_BUCKETS", "clock", "flight", "tracecontext",
]

_enabled = False
_flush_dir: Optional[str] = None
_registry = MetricRegistry()
_tracer = SpanTracer()
_lock = threading.Lock()
_atexit_registered = False


class _NullSpan:
    """Shared no-op span: the whole disabled-mode cost of ``with span(...)``."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> None:
        return None

    def set(self, **attrs: Any) -> None:
        return None


_NULL_SPAN = _NullSpan()


# -- switch ------------------------------------------------------------------

def enabled() -> bool:
    return _enabled


def enable(flush_dir: Optional[str] = None) -> None:
    """Turn collection on; with ``flush_dir``, also flush at interpreter exit
    and arm the flight recorder's abnormal-exit dumps into the same dir."""
    global _enabled, _flush_dir, _atexit_registered
    with _lock:
        _enabled = True
        if flush_dir:
            _flush_dir = flush_dir
            if not _atexit_registered:
                atexit.register(_atexit_flush)
                _atexit_registered = True
            flight.install(flush_dir)


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


def reset() -> None:
    """Drop all collected state (metrics + spans).  Collection stays as-is."""
    _registry.reset()
    _tracer.reset()


def _atexit_flush() -> None:
    if _enabled and _flush_dir:
        try:
            flush(_flush_dir)
        except Exception:
            pass  # nothing at interpreter exit may turn into a traceback


# -- hot-path helpers (flag checked before anything else) --------------------

def count(name: str, n: float = 1, /, **labels: Any) -> None:
    """Increment counter ``name`` (no-op when disabled)."""
    if not _enabled:
        return
    _registry.counter(name, **labels).inc(n)


def gauge_set(name: str, value: float, /, **labels: Any) -> None:
    if not _enabled:
        return
    _registry.gauge(name, **labels).set(value)


def gauge_add(name: str, delta: float, /, **labels: Any) -> None:
    if not _enabled:
        return
    _registry.gauge(name, **labels).inc(delta)


def observe(name: str, value: float, /, *,
            buckets: Optional[Iterable[float]] = None, **labels: Any) -> None:
    """Record ``value`` into histogram ``name`` (no-op when disabled)."""
    if not _enabled:
        return
    _registry.histogram(name, buckets=buckets, **labels).observe(value)


def span(name: str, /, **attrs: Any):
    """Context manager tracing one span (shared no-op when disabled)."""
    if not _enabled:
        return _NULL_SPAN
    return _tracer.span(name, **attrs)


def record_span(name: str, start: float, end: float, /, *,
                trace=None, **attrs: Any) -> None:
    """Record a span bracketed by two :func:`clock.monotonic` readings.

    ``trace`` optionally pins explicit ``(trace_id, span_id, parent_id)``
    identity (cross-thread attribution); without it, the recording thread's
    active trace context applies as usual."""
    if not _enabled:
        return
    _tracer.record_complete(name, start, end, trace=trace, **attrs)


def event(name: str, /, *, trace=None, **attrs: Any) -> None:
    """Record an instant event on the current span/context (no-op when
    disabled) — how point-in-time facts like fault-site fires land *on*
    the span that was running when they happened."""
    if not _enabled:
        return
    _tracer.record_instant(name, trace=trace, **attrs)


# -- access / export ---------------------------------------------------------

def get_registry() -> MetricRegistry:
    return _registry


def get_tracer() -> SpanTracer:
    return _tracer


def snapshot() -> Dict[str, Any]:
    from dmlc_core_tpu.telemetry import export

    return export.json_snapshot(_registry, _tracer)


def prometheus_text() -> str:
    from dmlc_core_tpu.telemetry import export

    return export.prometheus_text(_registry)


def flush(dirpath: Optional[str] = None) -> Dict[str, str]:
    """Write snapshot/prom/trace/events into ``dirpath`` (or the env dir)."""
    from dmlc_core_tpu.telemetry import export

    target = dirpath or _flush_dir or os.environ.get("DMLC_TELEMETRY_DIR")
    if not target:
        raise ValueError("no telemetry directory: pass dirpath or set "
                         "DMLC_TELEMETRY_DIR")
    return export.flush(target, _registry, _tracer)


# -- env-driven bring-up -----------------------------------------------------

def _init_from_env() -> None:
    env_dir = os.environ.get("DMLC_TELEMETRY_DIR", "").strip()
    flag = os.environ.get("DMLC_TELEMETRY", "").strip().lower()
    if env_dir:
        enable(env_dir)
    elif flag not in ("", "0", "false", "off"):
        enable()


_init_from_env()
