"""Span tracer: begin/end events per thread, Chrome-trace + JSONL export.

Spans are *complete* events (Chrome trace ``"ph": "X"``): one record per
span carrying its start timestamp and duration, appended at span end — no
begin/end pairing pass is needed at export time and a crashed span simply
never appears.  Timestamps are microseconds on the process-monotonic clock
(:func:`dmlc_core_tpu.telemetry.clock.trace_time_us`), so traces from
several ranks laid side by side in Perfetto share a plausible-if-not-
synchronized time axis.

The buffer is bounded (``max_events``, default 200k): past the cap new
spans are counted as dropped rather than grown without limit — a telemetry
subsystem that OOMs the pipeline it observes would be worse than none.

The enabled/disabled fast path lives in the package ``__init__``; this
module always records when called.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional

from dmlc_core_tpu.telemetry import clock

__all__ = ["SpanTracer", "Span"]


class Span:
    """Context manager recording one complete event on exit."""

    __slots__ = ("_tracer", "_name", "_attrs", "_start")

    def __init__(self, tracer: "SpanTracer", name: str,
                 attrs: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._start = 0.0

    def __enter__(self) -> "Span":
        self._start = clock.trace_time_us()
        return self

    def set(self, **attrs: Any) -> None:
        """Attach attributes discovered mid-span (e.g. bytes handled)."""
        if self._attrs is None:
            self._attrs = {}
        self._attrs.update(attrs)

    def __exit__(self, exc_type, exc, tb) -> None:
        end = clock.trace_time_us()
        if exc_type is not None:
            self.set(error=exc_type.__name__)
        self._tracer.record(self._name, self._start, end - self._start,
                            self._attrs)


class SpanTracer:
    """Process-wide span sink with per-thread identity."""

    def __init__(self, max_events: int = 200_000):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._thread_meta: Dict[int, str] = {}
        self._max = max_events
        self.dropped = 0

    def span(self, name: str, /, **attrs: Any) -> Span:
        return Span(self, name, attrs or None)

    def record(self, name: str, start_us: float, dur_us: float,
               attrs: Optional[Dict[str, Any]] = None) -> None:
        """Append one complete event (``ph: X``)."""
        tid = threading.get_ident()
        event: Dict[str, Any] = {
            "name": name, "ph": "X", "ts": round(start_us, 3),
            "dur": round(max(dur_us, 0.0), 3),
            "pid": os.getpid(), "tid": tid,
        }
        if attrs:
            event["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            if len(self._events) >= self._max:
                self.dropped += 1
                return
            if tid not in self._thread_meta:
                self._thread_meta[tid] = threading.current_thread().name
            self._events.append(event)

    def record_complete(self, name: str, start: float, end: float,
                        /, **attrs: Any) -> None:
        """Record a span bracketed by explicit :func:`clock.monotonic`
        readings — for phases whose begin predates knowing their name
        (e.g. tracker rendezvous: connect time is only attributable once
        the rank is assigned)."""
        self.record(name, clock.to_trace_us(start),
                    (end - start) * 1e6, attrs or None)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def chrome_trace(self) -> Dict[str, Any]:
        """chrome://tracing / Perfetto loadable JSON object."""
        with self._lock:
            events = list(self._events)
            meta = [{"name": "thread_name", "ph": "M", "pid": os.getpid(),
                     "tid": tid, "args": {"name": tname}}
                    for tid, tname in sorted(self._thread_meta.items())]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def jsonl(self) -> Iterator[str]:
        """One JSON object per line — the appendable event-log form."""
        for event in self.events():
            yield json.dumps(event, sort_keys=True)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._thread_meta.clear()
            self.dropped = 0


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)
