"""Span tracer: begin/end events per thread, Chrome-trace + JSONL export.

Spans are *complete* events (Chrome trace ``"ph": "X"``): one record per
span carrying its start timestamp and duration, appended at span end — no
begin/end pairing pass is needed at export time and a crashed span simply
never appears.  Timestamps are microseconds on the process-monotonic clock
(:func:`dmlc_core_tpu.telemetry.clock.trace_time_us`), so traces from
several ranks laid side by side in Perfetto share a plausible-if-not-
synchronized time axis.

**Trace identity** (:mod:`.tracecontext`): when a trace context is active
on the recording thread (an HTTP ``traceparent`` continued by the server,
a ``DMLC_TRACEPARENT`` process root, an enclosing span), every recorded
event additionally carries ``trace_id`` / ``span_id`` / ``parent_id`` —
the keys the cross-process assembler (``telemetry trace``) joins on.  A
context-managed :class:`Span` also *installs itself* as the active context
for its dynamic extent, so nested spans parent automatically.  With no
active context, events record exactly as before: untraced, never dropped
for it.

Every recorded event is also fed to the flight recorder's bounded ring
(:mod:`.flight`) — including events the main buffer drops — so a crashed
or SIGTERMed process still leaves its last N spans behind.

The buffer is bounded (``max_events``, default 200k): past the cap new
spans are counted as dropped rather than grown without limit — a telemetry
subsystem that OOMs the pipeline it observes would be worse than none.
Drops are exported as ``dmlc_telemetry_spans_dropped_total`` so an
assembled-but-incomplete trace is attributable to them.

The enabled/disabled fast path lives in the package ``__init__``; this
module always records when called.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

from dmlc_core_tpu.telemetry import clock, flight, tracecontext

__all__ = ["SpanTracer", "Span"]

# (trace_id, span_id, parent_id-or-None) as carried on one event
TraceIds = Tuple[str, str, Optional[str]]


class Span:
    """Context manager recording one complete event on exit."""

    __slots__ = ("_tracer", "_name", "_attrs", "_start", "_trace", "_token")

    def __init__(self, tracer: "SpanTracer", name: str,
                 attrs: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._start = 0.0
        self._trace: Optional[TraceIds] = None
        self._token: Optional[tracecontext.TraceContext] = None

    def __enter__(self) -> "Span":
        self._start = clock.trace_time_us()
        ctx = tracecontext.current()
        if ctx is not None:
            span_id = tracecontext.new_span_id()
            self._trace = (ctx.trace_id, span_id, ctx.span_id)
            # children opened inside this span's extent parent to it
            self._token = tracecontext._push(
                tracecontext.TraceContext(ctx.trace_id, span_id))
        return self

    @property
    def trace_id(self) -> Optional[str]:
        return self._trace[0] if self._trace else None

    @property
    def span_id(self) -> Optional[str]:
        return self._trace[1] if self._trace else None

    def set(self, **attrs: Any) -> None:
        """Attach attributes discovered mid-span (e.g. bytes handled)."""
        if self._attrs is None:
            self._attrs = {}
        self._attrs.update(attrs)

    def __exit__(self, exc_type, exc, tb) -> None:
        end = clock.trace_time_us()
        if self._trace is not None:
            tracecontext._pop(self._token)
        if exc_type is not None:
            self.set(error=exc_type.__name__)
        self._tracer.record(self._name, self._start, end - self._start,
                            self._attrs, trace=self._trace)


class SpanTracer:
    """Process-wide span sink with per-thread identity."""

    def __init__(self, max_events: int = 200_000):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._thread_meta: Dict[int, str] = {}
        self._max = max_events
        self.dropped = 0

    def span(self, name: str, /, **attrs: Any) -> Span:
        return Span(self, name, attrs or None)

    def record(self, name: str, start_us: float, dur_us: float,
               attrs: Optional[Dict[str, Any]] = None, *,
               trace: Optional[TraceIds] = None, ph: str = "X") -> None:
        """Append one complete event (``ph: X``; ``ph: i`` for instants).

        ``trace`` pins explicit trace identity; when omitted, the recording
        thread's active context (if any) supplies it — the event becomes a
        child of the current span/context.
        """
        tid = threading.get_ident()
        event: Dict[str, Any] = {
            "name": name, "ph": ph, "ts": round(start_us, 3),
            "pid": os.getpid(), "tid": tid,
        }
        if ph == "X":
            event["dur"] = round(max(dur_us, 0.0), 3)
        else:
            event["s"] = "t"  # instant events scope to their thread
        if trace is None:
            ctx = tracecontext.current()
            if ctx is not None:
                trace = (ctx.trace_id, tracecontext.new_span_id(),
                         ctx.span_id)
        if trace is not None:
            event["trace_id"], event["span_id"] = trace[0], trace[1]
            if trace[2]:
                event["parent_id"] = trace[2]
        if attrs:
            event["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        overflow = False
        with self._lock:
            if len(self._events) >= self._max:
                self.dropped += 1
                overflow = True
            else:
                if tid not in self._thread_meta:
                    self._thread_meta[tid] = threading.current_thread().name
                self._events.append(event)
        # the flight ring keeps the most recent tail even past overflow:
        # that tail is exactly what a crash dump needs
        flight.note_event(event)
        if overflow:
            try:  # lazy: the package imports this module at its own load
                from dmlc_core_tpu import telemetry

                telemetry.count("dmlc_telemetry_spans_dropped_total")
            except Exception:
                pass

    def record_complete(self, name: str, start: float, end: float,
                        /, *, trace: Optional[TraceIds] = None,
                        **attrs: Any) -> None:
        """Record a span bracketed by explicit :func:`clock.monotonic`
        readings — for phases whose begin predates knowing their name
        (e.g. tracker rendezvous: connect time is only attributable once
        the rank is assigned).  ``trace`` optionally pins identity for
        cross-thread attribution (e.g. the batcher crediting a request's
        queue wait to the request's own trace)."""
        self.record(name, clock.to_trace_us(start),
                    (end - start) * 1e6, attrs or None, trace=trace)

    def record_instant(self, name: str, /, *,
                       trace: Optional[TraceIds] = None,
                       **attrs: Any) -> None:
        """Record an instant event (``ph: i``) at now — fault fires and
        other point-in-time marks that belong *on* the enclosing span."""
        self.record(name, clock.trace_time_us(), 0.0, attrs or None,
                    trace=trace, ph="i")

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def chrome_trace(self) -> Dict[str, Any]:
        """chrome://tracing / Perfetto loadable JSON object."""
        with self._lock:
            events = list(self._events)
            meta = [{"name": "thread_name", "ph": "M", "pid": os.getpid(),
                     "tid": tid, "args": {"name": tname}}
                    for tid, tname in sorted(self._thread_meta.items())]
        # the per-process wall anchor the cross-process assembler aligns on
        meta.append({"name": "clock_sync", "ph": "M", "pid": os.getpid(),
                     "tid": 0, "args": {"wall_epoch_s": clock.wall_epoch()}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def jsonl(self) -> Iterator[str]:
        """One JSON object per line — the appendable event-log form."""
        for event in self.events():
            yield json.dumps(event, sort_keys=True)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._thread_meta.clear()
            self.dropped = 0


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)
