"""Cross-process trace assembly + critical-path analysis.

``python -m dmlc_core_tpu.telemetry trace <dir>`` takes the per-process
span files a run left in its ``DMLC_TELEMETRY_DIR`` — ``trace-*.trace.json``
flushes plus ``flight-*.json`` crash dumps — and produces:

- **one merged Perfetto trace** (``--out``): every process' events on a
  shared time axis, aligned via each file's ``clock_sync`` wall-epoch
  anchor (per-process monotonic clocks mean nothing to each other; the
  wall clock is only used for this shift, never for measurement);
- **trace assembly**: events grouped by ``trace_id``, spans joined into
  parent/child trees across process boundaries; spans whose recorded
  parent is nowhere in the merged set are counted as **orphans** (the
  smoking gun for a process that never flushed, or buffer drops — the
  report says which);
- **critical-path analysis** per trace: each span's *exclusive* time
  (duration minus its children's), aggregated by span name — "which stage
  dominated this request" as a number, not a guess — and a slowest-traces
  table the serving SLO report's worst-p99 trace ids can be looked up in.

Flight dumps are merged like regular span files (overlapping events
deduplicated) and mark their process as **crashed** with the dump's
reason: a chaos-killed worker or a watchdog-SIGTERMed bench child shows
up in the merged timeline with its last recorded spans, not as silence.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["load_sources", "assemble", "critical_path", "render_report",
           "main"]

# cap on how many stages the per-trace critical-path column names
_PATH_STAGES = 3


def _read_json(path: str) -> Optional[Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None


def load_sources(dirpath: str) -> Dict[str, Any]:
    """Everything assembly needs from one telemetry dir.

    Returns ``{"files": [...], "flights": [...], "drops": [...]}`` where
    each ``files`` entry is a flushed trace file (events + wall anchor),
    each ``flights`` entry a crash dump, and ``drops`` the per-process
    span-drop counts reported by metrics snapshots (an assembled trace
    missing spans is attributable, not mysterious).
    """
    files: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(dirpath, "trace-*.trace.json"))):
        obj = _read_json(path)
        if not isinstance(obj, dict) or "traceEvents" not in obj:
            continue
        events = [e for e in obj["traceEvents"] if isinstance(e, dict)]
        wall = None
        meta: List[Dict[str, Any]] = []
        body: List[Dict[str, Any]] = []
        for ev in events:
            if ev.get("ph") == "M":
                if ev.get("name") == "clock_sync":
                    wall = ev.get("args", {}).get("wall_epoch_s")
                else:
                    meta.append(ev)
            else:
                body.append(ev)
        files.append({"path": path, "events": body, "meta": meta,
                      "wall_epoch_s": wall, "reason": None})
    flights: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(dirpath, "flight-*.json"))):
        obj = _read_json(path)
        if not isinstance(obj, dict) or "entries" not in obj:
            continue
        flights.append({"path": path,
                        "events": [e for e in obj["entries"]
                                   if isinstance(e, dict)],
                        "meta": [],
                        "wall_epoch_s": obj.get("wall_epoch_s"),
                        "reason": obj.get("reason", "unknown"),
                        "pid": obj.get("pid"), "rank": obj.get("rank"),
                        "spans_dropped": obj.get("spans_dropped", 0)})
    drops: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(dirpath, "metrics-*.json"))):
        snap = _read_json(path)
        if not isinstance(snap, dict):
            continue
        n = (snap.get("spans") or {}).get("dropped", 0)
        if n:
            drops.append({"rank": snap.get("rank", 0),
                          "pid": snap.get("pid"), "dropped": n})
    return {"files": files, "flights": flights, "drops": drops}


def _dedup_key(ev: Dict[str, Any]) -> Tuple:
    return (ev.get("pid"), ev.get("tid"), ev.get("name"), ev.get("ph"),
            ev.get("ts"), ev.get("span_id"))


def assemble(dirpath: str) -> Dict[str, Any]:
    """Merge every source under ``dirpath`` and analyze the traces.

    Returns a dict with ``events`` (time-aligned, deduplicated),
    ``meta`` (process/thread names for the merged Perfetto file),
    ``traces`` (per-trace stats incl. critical path), plus the global
    ``orphans`` / ``untraced`` / ``drops`` / ``crashed`` accounting.
    """
    src = load_sources(dirpath)
    sources = src["files"] + src["flights"]
    # pids that reached a final flush: a flight dump from one of these is
    # ring residue (e.g. the periodic interval writer, or a SIGTERM that
    # still unwound through atexit) — its events merge, but the process
    # did not die silently and must not be reported as crashed
    flushed_pids = set()
    for s in src["files"]:
        m = re.search(r"-p(\d+)\.trace\.json$", s["path"])
        if m:
            flushed_pids.add(int(m.group(1)))
        for ev in s["events"]:
            if isinstance(ev.get("pid"), int):
                flushed_pids.add(ev["pid"])
                break
    anchors = [s["wall_epoch_s"] for s in sources
               if isinstance(s.get("wall_epoch_s"), (int, float))]
    base = min(anchors) if anchors else None
    unaligned = 0
    merged: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []
    seen: set = set()
    seen_meta: set = set()
    crashed: List[Dict[str, Any]] = []
    for s in sources:
        wall = s.get("wall_epoch_s")
        if base is not None and isinstance(wall, (int, float)):
            offset = (wall - base) * 1e6
        else:
            offset = 0.0
            if base is not None:
                unaligned += 1
        recovered = 0
        for ev in s["events"]:
            key = _dedup_key(ev)
            if key in seen:
                continue
            seen.add(key)
            out = dict(ev)
            try:
                out["ts"] = round(float(ev.get("ts", 0.0)) + offset, 3)
            except (TypeError, ValueError):
                continue
            if s["reason"] is not None:
                out.setdefault("args", {})
                recovered += 1
            merged.append(out)
        for mv in s["meta"]:
            mkey = (mv.get("pid"), mv.get("tid"), mv.get("name"),
                    json.dumps(mv.get("args", {}), sort_keys=True))
            if mkey not in seen_meta:
                seen_meta.add(mkey)
                meta.append(mv)
        if s["reason"] is not None:
            crashed.append({"pid": s.get("pid"), "rank": s.get("rank"),
                            "reason": s["reason"],
                            "events_recovered": recovered,
                            "spans_dropped": s.get("spans_dropped", 0),
                            "final_flush": s.get("pid") in flushed_pids,
                            "path": s["path"]})
    merged.sort(key=lambda e: e.get("ts", 0.0))

    spans = [e for e in merged if e.get("ph") == "X"]
    instants = [e for e in merged if e.get("ph") == "i"]
    traced = [e for e in spans if e.get("trace_id")]
    span_ids = {(e["trace_id"], e.get("span_id")) for e in traced}
    traces: Dict[str, Dict[str, Any]] = {}
    orphan_total = 0
    for ev in traced:
        t = traces.setdefault(ev["trace_id"], {
            "spans": [], "pids": set(), "orphans": 0, "events": 0})
        t["spans"].append(ev)
        t["pids"].add(ev.get("pid"))
        parent = ev.get("parent_id")
        if parent and (ev["trace_id"], parent) not in span_ids:
            t["orphans"] += 1
            orphan_total += 1
    for ev in instants:
        if ev.get("trace_id") in traces:
            traces[ev["trace_id"]]["events"] += 1

    summaries: List[Dict[str, Any]] = []
    for trace_id, t in traces.items():
        ts0 = min(e["ts"] for e in t["spans"])
        ts1 = max(e["ts"] + e.get("dur", 0.0) for e in t["spans"])
        roots = [e for e in t["spans"] if not e.get("parent_id")]
        root = min(roots or t["spans"], key=lambda e: e["ts"])
        path = critical_path(t["spans"])
        summaries.append({
            "trace_id": trace_id,
            "root": root.get("name", "?"),
            "total_ms": round((ts1 - ts0) / 1e3, 3),
            "spans": len(t["spans"]),
            "instants": t["events"],
            "pids": sorted(p for p in t["pids"] if p is not None),
            "orphans": t["orphans"],
            "critical_path": path,
        })
    summaries.sort(key=lambda s: -s["total_ms"])

    return {
        "dir": dirpath,
        "events": merged,
        "meta": meta,
        "sources": len(src["files"]),
        "flights": crashed,
        "unaligned_sources": unaligned,
        "spans": len(spans),
        "instants": len(instants),
        "untraced": len(spans) - len(traced),
        "traces": summaries,
        "orphans": orphan_total,
        "drops": src["drops"] + [
            {"rank": c.get("rank"), "pid": c.get("pid"),
             "dropped": c["spans_dropped"]}
            for c in crashed if c.get("spans_dropped")],
    }


def critical_path(spans: List[Dict[str, Any]]) \
        -> List[Dict[str, Any]]:
    """Exclusive time per span name, largest first.

    A span's exclusive time is its duration minus the summed durations of
    its direct children (floored at 0 — children from other processes can
    overhang their parent by clock-alignment skew).  Aggregated by name,
    this answers "which stage actually spent the time": a request whose
    ``serve.request`` span is 100 ms with a 90 ms ``serve.predict`` child
    charges 90 ms to predict, 10 ms to the handler — not 100 to each.
    """
    children: Dict[Optional[str], float] = {}
    for ev in spans:
        parent = ev.get("parent_id")
        if parent:
            children[parent] = children.get(parent, 0.0) \
                + float(ev.get("dur", 0.0))
    by_name: Dict[str, float] = {}
    for ev in spans:
        dur = float(ev.get("dur", 0.0))
        exclusive = max(0.0, dur - children.get(ev.get("span_id"), 0.0))
        name = ev.get("name", "?")
        by_name[name] = by_name.get(name, 0.0) + exclusive
    total = sum(by_name.values()) or 1.0
    out = [{"stage": name, "exclusive_ms": round(us / 1e3, 3),
            "share": round(us / total, 3)}
           for name, us in sorted(by_name.items(), key=lambda kv: -kv[1])]
    return out


def _fmt_path(path: List[Dict[str, Any]]) -> str:
    return " > ".join(f"{p['stage']} {p['share'] * 100:.0f}%"
                      for p in path[:_PATH_STAGES])


def render_report(asm: Dict[str, Any], top: int) -> str:
    lines: List[str] = []
    lines.append(
        f"merged {asm['spans']} span(s) + {asm['instants']} instant "
        f"event(s) from {asm['sources']} trace file(s) + "
        f"{len(asm['flights'])} flight dump(s) under {asm['dir']}")
    for c in asm["flights"]:
        who = f"p{c['pid']}" if c.get("pid") else os.path.basename(c["path"])
        if c.get("final_flush"):
            # ring residue next to a completed flush (interval writer, or
            # a SIGTERM that still unwound through atexit) — not a crash
            lines.append(f"  flight dump from {who} (reason={c['reason']}; "
                         "final flush present, process did not die "
                         "silently)")
        else:
            lines.append(f"  crashed process {who}: reason={c['reason']} "
                         f"({c['events_recovered']} event(s) recovered "
                         "from the flight ring)")
    if asm["unaligned_sources"]:
        lines.append(f"  note: {asm['unaligned_sources']} source(s) carry "
                     "no clock_sync anchor — their timestamps are NOT "
                     "aligned to the shared axis")
    for d in asm["drops"]:
        lines.append(
            f"WARNING: r{d.get('rank', 0)}-p{d.get('pid')} dropped "
            f"{d['dropped']} span(s) (buffer overflow) — assembled traces "
            "may be incomplete")
    lines.append(
        f"{len(asm['traces'])} trace(s) assembled; "
        f"{asm['untraced']} untraced span(s); "
        f"{asm['orphans']} orphan span(s)")
    if asm["traces"]:
        rows = [("trace_id", "root", "total_ms", "spans", "procs",
                 "critical path")]
        for t in asm["traces"][:top]:
            rows.append((t["trace_id"], t["root"], f"{t['total_ms']:.3f}",
                         str(t["spans"]), str(len(t["pids"])),
                         _fmt_path(t["critical_path"])))
        widths = [max(len(r[i]) for r in rows) for i in range(5)]
        lines.append("")
        lines.append(f"slowest {min(top, len(asm['traces']))} of "
                     f"{len(asm['traces'])} trace(s):")
        for i, row in enumerate(rows):
            lines.append("  ".join(
                [row[j].ljust(widths[j]) for j in range(5)] + [row[5]])
                .rstrip())
            if i == 0:
                lines.append("  ".join("-" * w for w in widths) + "  -----")
    return "\n".join(lines)


def main(dirpath: str, out: Optional[str] = None, as_json: bool = False,
         top: int = 10, fail_on_orphans: bool = False) -> int:
    asm = assemble(dirpath)
    if not asm["spans"] and not asm["instants"]:
        print(f"no trace-*.trace.json / flight-*.json events under "
              f"{dirpath!r}")
        return 1
    if out:
        payload = {"traceEvents": asm["meta"] + asm["events"],
                   "displayTimeUnit": "ms"}
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, out)
    if as_json:
        report = {k: v for k, v in asm.items()
                  if k not in ("events", "meta")}
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(render_report(asm, top))
        if out:
            print(f"\nmerged Perfetto trace written to {out} "
                  "(load at ui.perfetto.dev)")
    if fail_on_orphans and asm["orphans"]:
        # stderr, not stdout: `--json > report.json` must stay parseable
        # JSON even (especially) when the gate trips
        print(f"FAIL: {asm['orphans']} orphan span(s) — a recorded parent "
              "is missing from the merged set (unflushed process, or "
              "buffer drops; see warnings above)", file=sys.stderr)
        return 2
    return 0
