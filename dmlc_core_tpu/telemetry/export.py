"""Exporters: Prometheus text format, JSON snapshot, directory flush.

Three interchange forms, one source of truth (the registry + tracer):

- :func:`prometheus_text` — the text exposition format, scrapeable or
  greppable (``# TYPE``/``# HELP`` headers, ``le``-cumulative histograms);
- :func:`json_snapshot` — a structured dict for programmatic use (this is
  what ``bench.py`` attaches to a BENCH round's ``detail``);
- :func:`flush` — write snapshot + Prometheus dump + Chrome trace + JSONL
  event log into a directory, filenames keyed by rank and pid so N ranks
  flushing into one shared ``DMLC_TELEMETRY_DIR`` never collide.  The
  multi-rank ``report`` CLI (:mod:`.report`) aggregates these back.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from dmlc_core_tpu.telemetry import clock
from dmlc_core_tpu.telemetry.registry import Histogram, MetricRegistry
from dmlc_core_tpu.telemetry.spans import SpanTracer

__all__ = ["prometheus_text", "json_snapshot", "flush", "rank_from_env"]


def rank_from_env() -> int:
    """This process' rank for snapshot filenames — the launcher env contract
    (same precedence as collective.api's task-id resolution; duplicated here
    because telemetry must import nothing heavier than the stdlib)."""
    for key in ("DMLC_TASK_ID", "OMPI_COMM_WORLD_RANK", "PMIX_RANK",
                "PMI_RANK", "SLURM_PROCID"):
        value = os.environ.get(key, "").strip()
        if value:
            try:
                return int(value)
            except ValueError:
                continue
    return 0


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"  # the text format's literals; int(v) would raise
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    """Prometheus text-format label-value escaping (backslash first)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(label_key) -> str:
    if not label_key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in label_key)
    return "{" + inner + "}"


def _merge_labels(label_key, extra: str) -> str:
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in label_key)
    joined = ",".join(x for x in (inner, extra) if x)
    return "{" + joined + "}"


def prometheus_text(registry: MetricRegistry) -> str:
    """Render the registry in the Prometheus text exposition format."""
    out = []
    for fam in registry.families():
        if fam.help:
            out.append(f"# HELP {fam.name} {fam.help}")
        out.append(f"# TYPE {fam.name} {fam.kind}")
        for label_key, child in fam.samples():
            if isinstance(child, Histogram):
                cumulative = child.cumulative()
                bounds = [str(b) for b in fam.buckets] + ["+Inf"]
                for bound, count in zip(bounds, cumulative):
                    le = 'le="%s"' % bound
                    out.append(f"{fam.name}_bucket"
                               f"{_merge_labels(label_key, le)} {count}")
                out.append(f"{fam.name}_sum{_fmt_labels(label_key)} "
                           f"{_fmt_value(child.sum)}")
                out.append(f"{fam.name}_count{_fmt_labels(label_key)} "
                           f"{child.count}")
            else:
                out.append(f"{fam.name}{_fmt_labels(label_key)} "
                           f"{_fmt_value(child.value)}")
    return "\n".join(out) + ("\n" if out else "")


def json_snapshot(registry: MetricRegistry,
                  tracer: Optional[SpanTracer] = None) -> Dict[str, Any]:
    """Structured snapshot of every family (and span stats when given)."""
    families: Dict[str, Any] = {}
    for fam in registry.families():
        samples = []
        for label_key, child in fam.samples():
            entry: Dict[str, Any] = {"labels": dict(label_key)}
            if isinstance(child, Histogram):
                entry["buckets"] = list(fam.buckets)
                entry["counts"] = child.bucket_counts
                entry["sum"] = child.sum
                entry["count"] = child.count
            else:
                entry["value"] = child.value
            samples.append(entry)
        families[fam.name] = {"kind": fam.kind, "help": fam.help,
                              "samples": samples}
    snap: Dict[str, Any] = {
        "time": time.time(),
        "pid": os.getpid(),
        "rank": rank_from_env(),
        "wall_epoch_s": clock.wall_epoch(),
        "metrics": families,
    }
    if tracer is not None:
        snap["spans"] = {"recorded": len(tracer.events()),
                         "dropped": tracer.dropped}
    return snap


def flush(dirpath: str, registry: MetricRegistry,
          tracer: SpanTracer) -> Dict[str, str]:
    """Write all export forms into ``dirpath``; returns {kind: path}.

    Every file is written to a temp name and renamed, so a reader (or the
    ``report`` aggregator) never sees a half-written snapshot.
    """
    os.makedirs(dirpath, exist_ok=True)
    tag = f"r{rank_from_env()}-p{os.getpid()}"
    written: Dict[str, str] = {}

    def _write(name: str, text: str) -> None:
        path = os.path.join(dirpath, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
        written[name.split(".", 1)[1]] = path

    _write(f"metrics-{tag}.json",
           json.dumps(json_snapshot(registry, tracer), indent=1, sort_keys=True))
    _write(f"metrics-{tag}.prom", prometheus_text(registry))
    _write(f"trace-{tag}.trace.json", json.dumps(tracer.chrome_trace()))
    _write(f"events-{tag}.jsonl",
           "".join(line + "\n" for line in tracer.jsonl()))
    return written
