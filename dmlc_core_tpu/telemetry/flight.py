"""Flight recorder: the last N span completions, kept where a crash can't
eat them.

The span buffer and the atexit flush cover the happy path; the failures
worth diagnosing are exactly the ones that skip it — a bench child SIGTERMed
by its parent's watchdog after 300 silent seconds, a worker ``os._exit``'d
by a chaos plan, an unhandled exception past the last flush.  This module
keeps a bounded ring of recent span events (and fault-site fires), fed on
every record, and **dumps it** to ``DMLC_TELEMETRY_DIR`` when the process
dies abnormally:

- unhandled exception (a chained ``sys.excepthook``);
- ``SIGTERM`` (a chained handler — installed only from the main thread, and
  any pre-existing handler still runs after the dump);
- explicitly, from watchdog/soft-deadline paths (``bench.py``) and from the
  fault injector's ``exit`` kind before ``os._exit``;
- optionally every ``DMLC_FLIGHT_INTERVAL_S`` seconds from a daemon thread,
  so even ``SIGKILL`` leaves a dump at most one interval stale (``bench.py``
  arms this for its children; default off — most processes don't need a
  background writer).

The dump is one small JSON file, ``flight-r<rank>-p<pid>.json``, written
atomically; the trace assembler (``telemetry trace``) merges its events
with the regular per-process span files (deduplicating overlap) and marks
the process as crashed with the dump's ``reason``.

Knobs: ``DMLC_FLIGHT=0`` disables handler installation entirely;
``DMLC_FLIGHT_MAX`` sizes the ring (default 512 entries);
``DMLC_FLIGHT_INTERVAL_S`` arms the periodic writer.  Feeding the ring
costs one deque append per recorded span — and recording only happens when
telemetry is enabled, so disabled-mode cost stays zero.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from dmlc_core_tpu.telemetry import clock

__all__ = ["note_event", "note", "snapshot", "dump", "install", "reset",
           "installed", "DEFAULT_MAX_ENTRIES"]

DEFAULT_MAX_ENTRIES = 512


def _ring_size() -> int:
    raw = os.environ.get("DMLC_FLIGHT_MAX", "").strip()
    try:
        return max(16, int(raw)) if raw else DEFAULT_MAX_ENTRIES
    except ValueError:
        return DEFAULT_MAX_ENTRIES


# deque.append with maxlen is atomic under the GIL: the ring needs no lock
# on the hot path (snapshot() copies via list(), also atomic)
_ring: "deque[Dict[str, Any]]" = deque(maxlen=_ring_size())
_dump_dir: Optional[str] = None
_installed = False
_prev_excepthook = None
_prev_sigterm = None
_interval_thread: Optional[threading.Thread] = None
# reentrant: the SIGTERM handler runs ON the main thread and calls dump();
# a plain Lock would deadlock it against a dump already in progress there
# (bench's soft-deadline dump racing the parent watchdog's terminate())
_dump_lock = threading.RLock()


def note_event(event: Dict[str, Any]) -> None:
    """Feed one span/instant event dict into the ring (called by the span
    tracer on every record — including ones the bounded span buffer
    dropped: the flight ring always keeps the most recent tail)."""
    _ring.append(event)


def note(name: str, /, **payload: Any) -> None:
    """Feed a non-span marker (e.g. a fault fire outside any span).

    ``name`` is positional-only so payload keys named ``name`` (or any
    other identifier — fault fires carry ``kind=``) can never collide."""
    entry: Dict[str, Any] = {"ph": "i", "name": name,
                             "ts": round(clock.trace_time_us(), 3),
                             "pid": os.getpid(),
                             "tid": threading.get_ident()}
    if payload:
        entry["args"] = payload
    _ring.append(entry)


def snapshot() -> List[Dict[str, Any]]:
    return list(_ring)


def reset() -> None:
    """Drop ring contents (test isolation; handlers stay installed)."""
    _ring.clear()


def installed() -> bool:
    return _installed


def dump(reason: str, dirpath: Optional[str] = None) -> Optional[str]:
    """Write the ring to ``flight-r<rank>-p<pid>.json``; returns the path.

    Never raises (a failing dump on a dying process must not replace the
    original failure); returns None with nothing written when no directory
    is known or the write fails.
    """
    target = dirpath or _dump_dir or os.environ.get("DMLC_TELEMETRY_DIR")
    if not target:
        return None
    try:
        # cold path: the lazy import avoids a spans->flight->export->spans
        # import cycle at module load
        from dmlc_core_tpu.telemetry.export import rank_from_env

        with _dump_lock:
            os.makedirs(target, exist_ok=True)
            path = os.path.join(
                target, f"flight-r{rank_from_env()}-p{os.getpid()}.json")
            payload = {
                "reason": reason,
                "time": time.time(),
                "wall_epoch_s": clock.wall_epoch(),
                "pid": os.getpid(),
                "rank": rank_from_env(),
                "entries": snapshot(),
            }
            try:
                from dmlc_core_tpu import telemetry

                payload["spans_dropped"] = telemetry.get_tracer().dropped
            except Exception:
                pass
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            return path
    except Exception:
        return None


# -- abnormal-exit handlers ---------------------------------------------------

def _on_uncaught(exc_type, exc, tb) -> None:
    dump(f"unhandled_exception:{getattr(exc_type, '__name__', exc_type)}")
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def _on_sigterm(signum, frame) -> None:
    dump("sigterm")
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    elif prev != signal.SIG_IGN:
        # SIG_DFL — or None, a handler installed by non-Python code that
        # we cannot call: restore the default and re-raise so the process
        # still DIES on SIGTERM (swallowing it would strand supervisors
        # into SIGKILL, losing the clean shutdown the chain preserves)
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


_logger = logging.getLogger("dmlc_core_tpu.telemetry.flight")


def _interval_loop(interval_s: float) -> None:
    # daemon loop, whole body guarded: a failing periodic dump must never
    # take anything down (the thread dies with the process), but the
    # failure itself is ferried to the log rather than lost
    try:
        while True:
            time.sleep(interval_s)
            dump("interval")
    except Exception as exc:  # noqa: BLE001 — ferried, not swallowed
        _logger.warning("flight interval writer stopped: %r", exc)


def install(dirpath: str) -> None:
    """Arm the abnormal-exit dumps into ``dirpath`` (idempotent).

    Called by ``telemetry.enable(flush_dir)`` — i.e. whenever
    ``DMLC_TELEMETRY_DIR`` is set — unless ``DMLC_FLIGHT=0``.  Signal
    installation is skipped off the main thread (CPython restriction) and
    never clobbers an existing handler: the previous one is chained after
    the dump.
    """
    global _installed, _dump_dir, _prev_excepthook, _prev_sigterm
    global _interval_thread, _ring
    _dump_dir = dirpath
    if _ring.maxlen != _ring_size():
        # the ring was sized at import; honor a DMLC_FLIGHT_MAX set after
        # that but before enable() — same late-binding the interval knob
        # gets — keeping whatever tail was already recorded
        _ring = deque(_ring, maxlen=_ring_size())
    if _installed:
        return
    if os.environ.get("DMLC_FLIGHT", "").strip() == "0":
        return
    _installed = True
    _prev_excepthook = sys.excepthook
    sys.excepthook = _on_uncaught
    if threading.current_thread() is threading.main_thread():
        try:
            _prev_sigterm = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            _prev_sigterm = None
    raw = os.environ.get("DMLC_FLIGHT_INTERVAL_S", "").strip()
    try:
        interval = float(raw) if raw else 0.0
    except ValueError:
        interval = 0.0
    if interval > 0 and _interval_thread is None:
        _interval_thread = threading.Thread(
            target=_interval_loop, args=(interval,),
            name="flight-recorder", daemon=True)
        _interval_thread.start()
