"""Multi-rank snapshot aggregation: ``python -m dmlc_core_tpu.telemetry report``.

Each rank flushing into a shared ``DMLC_TELEMETRY_DIR`` leaves one
``metrics-r<rank>-p<pid>.json`` snapshot.  This module folds them back into
one table: counters and histograms sum across ranks; gauges keep per-rank
spread (min/max) because summing queue depths across ranks is meaningless.

Histograms additionally get **quantile estimates** (p50/p95/p99) derived
from the merged fixed-bucket counts (:func:`estimate_quantiles`): serving
SLOs are stated as latency quantiles, and a report that only shows bucket
counts makes every reader redo the interpolation by hand.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["load_snapshots", "aggregate", "estimate_quantiles",
           "render_table", "main"]

# the quantiles every aggregated histogram reports (SLO vocabulary)
REPORT_QUANTILES: Tuple[Tuple[str, float], ...] = (
    ("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


def estimate_quantiles(buckets: Sequence[float], counts: Sequence[int],
                       qs: Sequence[float]) -> List[Optional[float]]:
    """Quantile estimates from fixed-bucket histogram counts.

    ``buckets`` are the finite inclusive upper bounds (ascending);
    ``counts`` are **non-cumulative** per-bucket counts with one extra
    trailing entry for the implicit +Inf bucket (the registry's
    ``bucket_counts`` layout).  Returns one estimate per ``q`` in ``qs``:

    - linear interpolation inside the bucket the quantile rank lands in,
      taking the previous bound (or 0.0 for the first bucket — observations
      here are non-negative latencies/sizes) as the lower edge;
    - a rank landing in the +Inf bucket reports the highest finite bound
      (the Prometheus ``histogram_quantile`` convention: the estimate is a
      floor, not an extrapolation past what the buckets can resolve);
    - ``None`` per quantile when the histogram is empty or the counts
      don't line up with the bounds (a cross-rank bucket clash).
    """
    bounds = [float(b) for b in buckets]
    if len(counts) != len(bounds) + 1 or not bounds:
        return [None] * len(qs)
    total = sum(counts)
    if total <= 0:
        return [None] * len(qs)
    out: List[Optional[float]] = []
    for q in qs:
        if not 0.0 <= q <= 1.0:
            out.append(None)
            continue
        rank = q * total
        running = 0.0
        est: Optional[float] = bounds[-1]  # +Inf bucket floors here
        for i, c in enumerate(counts[:-1]):
            if running + c >= rank:
                lo = 0.0 if i == 0 else bounds[i - 1]
                hi = bounds[i]
                # position within this bucket's count mass
                est = lo + (hi - lo) * ((rank - running) / c) if c else hi
                break
            running += c
        out.append(est)
    return out


def load_snapshots(dirpath: str) -> List[Dict[str, Any]]:
    """All rank snapshots in ``dirpath``, oldest first; bad files skipped."""
    out = []
    for path in sorted(glob.glob(os.path.join(dirpath, "metrics-*.json"))):
        try:
            with open(path) as f:
                snap = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(snap, dict) and isinstance(snap.get("metrics"), dict):
            snap["_path"] = path
            out.append(snap)
    return sorted(out, key=lambda s: (s.get("rank", 0), s.get("time", 0)))


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())) + "}"


def aggregate(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge rank snapshots; returns {series_name: merged_entry}.

    One series per (family, label set).  Entry fields:
    ``kind``, ``ranks`` (contributing rank list) and, by kind:
    counter -> ``total``; gauge -> ``min``/``max``/``last``;
    histogram -> ``count``/``sum``/``mean`` (+ merged ``buckets``/``counts``).
    """
    merged: Dict[str, Dict[str, Any]] = {}
    for snap in snapshots:
        rank = snap.get("rank", 0)
        for fam_name, fam in sorted(snap["metrics"].items()):
            kind = fam.get("kind", "counter")
            for sample in fam.get("samples", []):
                series = fam_name + _label_str(sample.get("labels", {}))
                entry = merged.setdefault(series, {
                    "kind": kind, "ranks": [],
                })
                if entry["kind"] != kind:
                    # same series name, different kind across ranks: keep the
                    # first and note the clash rather than corrupting the fold
                    entry["kind_clash"] = True
                    continue
                entry["ranks"].append(rank)
                if kind == "counter":
                    entry["total"] = entry.get("total", 0.0) + sample.get("value", 0.0)
                elif kind == "gauge":
                    v = sample.get("value", 0.0)
                    entry["min"] = min(entry.get("min", v), v)
                    entry["max"] = max(entry.get("max", v), v)
                    entry["last"] = v
                else:  # histogram
                    entry["count"] = entry.get("count", 0) + sample.get("count", 0)
                    entry["sum"] = entry.get("sum", 0.0) + sample.get("sum", 0.0)
                    counts = sample.get("counts")
                    if counts is not None:
                        prev = entry.get("counts")
                        if prev is None:
                            entry["counts"] = list(counts)
                            entry["buckets"] = sample.get("buckets")
                        elif len(prev) != len(counts):
                            # ranks registered different bucket lists: keep
                            # the first fold and mark the clash instead of
                            # silently dropping accumulated counts (the sum
                            # and count above still cover every rank)
                            entry["bucket_clash"] = True
                        else:
                            entry["counts"] = [a + b for a, b in zip(prev, counts)]
    # finalize histograms once per merged series, not once per folded
    # snapshot: mean + quantile estimates only make sense on the final fold
    for entry in merged.values():
        if entry["kind"] != "histogram":
            continue
        if entry.get("count"):
            entry["mean"] = entry["sum"] / entry["count"]
        if entry.get("counts") and entry.get("buckets"):
            # quantiles follow the merged bucket counts (on a bucket
            # clash they cover the folded ranks only — the clash marker
            # above says so)
            ests = estimate_quantiles(entry["buckets"], entry["counts"],
                                      [q for _, q in REPORT_QUANTILES])
            for (name, _), est in zip(REPORT_QUANTILES, ests):
                if est is not None:
                    entry[name] = est
    return merged


def _value_column(entry: Dict[str, Any], series: str = "") -> str:
    kind = entry["kind"]
    if kind == "counter":
        total = entry.get("total", 0.0)
        return str(int(total)) if total == int(total) else f"{total:.6g}"
    if kind == "gauge":
        lo, hi = entry.get("min", 0.0), entry.get("max", 0.0)
        if lo == hi:
            return f"{lo:.6g}"
        return f"min={lo:.6g} max={hi:.6g}"
    # the "s" unit suffix follows the catalog convention: only *_seconds
    # histograms measure durations (dmlc_serve_batch_rows is a count)
    unit = "s" if series.split("{", 1)[0].endswith("_seconds") else ""
    mean = entry.get("mean")
    mean_s = f" mean={mean:.6g}{unit}" if mean is not None else ""
    q_s = "".join(f" {name}={entry[name]:.6g}{unit}"
                  for name, _ in REPORT_QUANTILES if name in entry)
    # a clash fold is partial: say so next to the numbers it limits
    # (count/sum still cover every rank; counts-derived quantiles don't)
    flag = (" [bucket-clash: quantiles cover first-fold ranks only]"
            if entry.get("bucket_clash") else "")
    return (f"n={entry.get('count', 0)} sum={entry.get('sum', 0.0):.6g}"
            f"{mean_s}{q_s}{flag}")


def render_table(merged: Dict[str, Any]) -> str:
    rows: List[Tuple[str, str, str, str]] = [
        ("series", "kind", "ranks", "value")]
    for series in sorted(merged):
        entry = merged[series]
        ranks = sorted(set(entry.get("ranks", [])))
        rank_s = ",".join(map(str, ranks)) if len(ranks) <= 6 \
            else f"{len(ranks)} ranks"
        rows.append((series, entry["kind"], rank_s,
                     _value_column(entry, series)))
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    lines = []
    for i, row in enumerate(rows):
        lines.append("  ".join([row[0].ljust(widths[0]),
                                row[1].ljust(widths[1]),
                                row[2].ljust(widths[2]), row[3]]).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths) + "  " + "-" * 5)
    return "\n".join(lines)


def main(dirpath: str, as_json: bool = False) -> int:
    snapshots = load_snapshots(dirpath)
    if not snapshots:
        print(f"no metrics-*.json snapshots under {dirpath!r}")
        return 1
    merged = aggregate(snapshots)
    if as_json:
        print(json.dumps(merged, indent=1, sort_keys=True))
    else:
        ranks = sorted({s.get("rank", 0) for s in snapshots})
        print(f"{len(snapshots)} snapshot(s) from rank(s) "
              f"{','.join(map(str, ranks))} under {dirpath}")
        dup_ranks = sorted({r for r in ranks
                            if sum(1 for s in snapshots
                                   if s.get("rank", 0) == r) > 1})
        if dup_ranks:
            # pid-keyed filenames mean a re-used dir accumulates snapshots
            # across runs; the fold sums them all, so say so rather than
            # silently reporting inflated totals
            print(f"note: rank(s) {','.join(map(str, dup_ranks))} have "
                  "multiple snapshots (multi-process rank, or a re-used "
                  "telemetry dir) — counters/histograms sum across all")
        print(render_table(merged))
    return 0
