"""Trace context: the identity that makes spans joinable across processes.

A **trace** is one causal story — a scored request from the loadgen client
through the HTTP handler, the micro-batcher and predict; a probe run from
``bench.py`` down into its re-exec'd children; a rendezvous from the
tracker's accept loop into every worker.  Each story gets one ``trace_id``;
every span recorded while a :class:`TraceContext` is active carries that id
plus its own ``span_id`` and its parent's, so the offline assembler
(``python -m dmlc_core_tpu.telemetry trace``) can stitch per-process span
files back into one tree however many processes the story crossed.

Propagation forms (all carry the same W3C ``traceparent`` string,
``00-<32 hex trace_id>-<16 hex span_id>-01``):

- **HTTP header** ``traceparent`` — the serving path
  (client attaches, ``serve/server.py`` continues);
- **environment** ``DMLC_TRACEPARENT`` — a parent process roots every span
  of a child it launches (``bench.py`` children, tracker-launched workers
  via ``DMLC_TRACKER_TRACEPARENT``); read once at import into the
  *process root* context, which applies to every thread;
- **explicit argument** — same-process boundaries that cross threads or
  executors (``data/parse_proc.py`` ships it to pool workers next to the
  parse spec).

In-process the active context is **thread-local**: ``with activate(ctx):``
installs it, every ``telemetry.span(...)`` opened inside becomes a child
and re-installs itself for its own dynamic extent, so nesting is automatic.
A thread with no activated context falls back to the process root (or no
context at all — spans then record exactly as they did before tracing
existed: untraced, but never lost).

Cost discipline: this module is consulted only when telemetry is enabled
and a span is actually recorded — one thread-local read.  Disabled
telemetry never touches it.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

__all__ = [
    "TraceContext", "new_trace_id", "new_span_id",
    "format_traceparent", "from_traceparent",
    "current", "activate", "set_process_root", "get_process_root",
    "current_traceparent", "child_env",
    "TRACEPARENT_ENV", "TRACKER_TRACEPARENT_ENV",
]

TRACEPARENT_ENV = "DMLC_TRACEPARENT"
# the tracker's own env contract (tracker/rendezvous.py worker_envs): kept
# distinct from DMLC_TRACEPARENT so a job-level trace (bench) and a
# tracker-level one can coexist; DMLC_TRACEPARENT wins when both are set
TRACKER_TRACEPARENT_ENV = "DMLC_TRACKER_TRACEPARENT"

_VERSION = "00"
_FLAGS_SAMPLED = "01"
_HEX = set("0123456789abcdef")


class TraceContext:
    """One point in a trace: the trace and the span new children parent to.

    ``span_id`` may be ``None`` for a *fresh root*: the first span opened
    under it becomes the trace's root span (no parent) — this is how a
    client starts a story without inventing a parent span nobody recorded.
    """

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)


def new_trace_id() -> str:
    """Fresh 32-hex (128-bit) trace id."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """Fresh 16-hex (64-bit) span id."""
    return os.urandom(8).hex()


def new_root() -> TraceContext:
    """A fresh root context (new trace, no parent span yet)."""
    return TraceContext(new_trace_id(), None)


def format_traceparent(ctx: TraceContext) -> str:
    """W3C ``traceparent`` for ``ctx`` (requires a concrete ``span_id`` —
    the wire format has no way to say "trace but no span yet")."""
    if not ctx.span_id:
        raise ValueError("cannot encode a traceparent without a span_id "
                         "(open a span first, or generate one explicitly)")
    return f"{_VERSION}-{ctx.trace_id}-{ctx.span_id}-{_FLAGS_SAMPLED}"


def _hexfield(s: str, n: int) -> bool:
    return len(s) == n and set(s) <= _HEX and set(s) != {"0"}


def from_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Decode a ``traceparent``; ``None`` on anything malformed.

    Lenient by design (the W3C rule: an invalid header is *ignored*, the
    receiver starts its own trace) — a hostile or buggy client must not be
    able to 500 the scoring path with a weird header.  Future versions
    (``version != 00``) are accepted as long as the two id fields parse;
    version ``ff`` is explicitly invalid per spec.
    """
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or set(version) - _HEX or version == "ff":
        return None
    if version == "00" and len(parts) != 4:
        # the spec: version 00 has exactly four fields; extra fields are
        # only tolerated from FUTURE versions (forward compatibility)
        return None
    if not _hexfield(trace_id, 32) or not _hexfield(span_id, 16):
        return None
    return TraceContext(trace_id, span_id)


# -- the active context -------------------------------------------------------

_tls = threading.local()
_process_root: Optional[TraceContext] = None


def current() -> Optional[TraceContext]:
    """The context spans opened on this thread join (thread-local first,
    then the process root, else None)."""
    ctx = getattr(_tls, "ctx", None)
    return ctx if ctx is not None else _process_root


class _Activation:
    """``with activate(ctx):`` — install (or, for None, change nothing)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._prev: Optional[TraceContext] = None

    def __enter__(self) -> Optional[TraceContext]:
        if self._ctx is not None:
            self._prev = getattr(_tls, "ctx", None)
            _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc: Any) -> None:
        if self._ctx is not None:
            _tls.ctx = self._prev


def activate(ctx: Optional[TraceContext]) -> _Activation:
    """Context manager installing ``ctx`` as this thread's active context.

    ``activate(None)`` is a transparent no-op, so call sites can write
    ``with activate(from_traceparent(header)):`` without branching.
    """
    return _Activation(ctx)


def _push(ctx: TraceContext) -> Optional[TraceContext]:
    """Install ``ctx`` (a span making itself current); returns the token
    :func:`_pop` restores.  Internal — spans.py only."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


def _pop(token: Optional[TraceContext]) -> None:
    _tls.ctx = token


def set_process_root(ctx: Optional[TraceContext]) -> None:
    """Install the process-wide fallback context (None clears it).

    This is what env propagation sets: every thread with no explicitly
    activated context parents its spans here, so a whole child process'
    telemetry joins the launcher's trace.
    """
    global _process_root
    _process_root = ctx


def get_process_root() -> Optional[TraceContext]:
    return _process_root


def current_traceparent() -> Optional[str]:
    """The active context as a traceparent string (None when there is no
    context or it has no span yet)."""
    ctx = current()
    if ctx is None or not ctx.span_id:
        return None
    return format_traceparent(ctx)


def child_env(environ: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Env-var propagation: ``environ`` (or a fresh dict) with
    ``DMLC_TRACEPARENT`` set from the active context when there is one."""
    env: Dict[str, str] = dict(environ) if environ is not None else {}
    tp = current_traceparent()
    if tp:
        env[TRACEPARENT_ENV] = tp
    return env


# -- env-driven bring-up ------------------------------------------------------

def _init_from_env() -> None:
    header = (os.environ.get(TRACEPARENT_ENV, "").strip()
              or os.environ.get(TRACKER_TRACEPARENT_ENV, "").strip())
    ctx = from_traceparent(header)
    if ctx is not None:
        set_process_root(ctx)


_init_from_env()
