"""Continuous trainer daemon: crash-tolerant GBDT refresh, end to end.

This is the connector the reference ecosystem never had (ROADMAP item 2):
fleet-ingested batches in, hot-swappable serving checkpoints out.
:class:`TrainerDaemon` consumes dense batches from a
:mod:`~dmlc_core_tpu.train.source` (spool directory or the PR 12
shard-lease fleet), appends incremental boosting rounds
(:meth:`~dmlc_core_tpu.models.gbdt.GBDT.append_rounds` — binner edges
frozen, uint8 serving wire bitwise skew-free), and publishes
manifest-first checkpoints the PR 13
:class:`~dmlc_core_tpu.serve.lifecycle.CheckpointWatcher` validates and
swaps with zero dropped requests.

Crash tolerance is by construction, not by cleanup:

resume
    startup scans for the last *valid* manifest
    (:meth:`CheckpointManager.latest_valid` — the same fallback-past-bad-
    steps scan the serving watcher runs, plus a byte re-hash), restores
    trees + frozen edges + the ingest cursor from it, and retrains only
    the rounds published state never saw.  A manifest-less newest step
    (the previous incarnation died mid-publish) is skipped AND its step
    number is reused: the interrupted publish completes idempotently.
publish
    temp + verify + manifest-last: the blob lands via atomic
    temp+rename, is re-hashed against its own digest, and only then gets
    a manifest.  A kill at ANY point mid-publish leaves a step the
    manifest-first watcher never even opens — a torn publish cannot
    become a swap candidate.  A verify failure (torn/bit-rotted blob)
    rejects the publish, counts it, and the same step is re-published on
    the next cadence.
poison
    a batch that fails to parse, has the wrong feature arity, a
    non-finite label, or non-finite features outside the
    ``handle_missing`` contract is quarantined and counted
    (``dmlc_train_quarantined_total``), never fatal; the cursor advances
    past it.

Fault sites ``train.ingest`` / ``train.round`` / ``train.publish`` ride
the :mod:`~dmlc_core_tpu.fault` plan machinery (the continuous chaos
drill kills the daemon mid-round and tears a publish); every stage is a
``train.*`` span and the odometers flush as ``dmlc_train_*`` metrics.

Knobs: ``DMLC_TRAIN_PUBLISH_EVERY_S`` (wall-clock publish cadence, 0 =
off — a daemon thread snapshots and publishes even while ingest idles),
``DMLC_TRAIN_PUBLISH_ROUNDS`` (publish every N boosting rounds, default
8), ``DMLC_TRAIN_POLL_S`` (idle source poll, default 0.5).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import numpy as np

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.bridge.checkpoint import (CheckpointManager,
                                             load_checkpoint,
                                             save_checkpoint,
                                             verify_checkpoint)
from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam, TreeEnsemble
from dmlc_core_tpu.param import get_env
from dmlc_core_tpu.train.source import Batch
from dmlc_core_tpu.utils.logging import CHECK, log_info, log_warning

__all__ = ["TrainerDaemon", "CURSOR_KEY", "ROUND_KEY"]

# serving_state extra leaves: the ingest cursor and round odometer ride
# the same atomic blob as the trees they produced — resume state and
# model state can never diverge
CURSOR_KEY = "train_cursor"
ROUND_KEY = "train_round"

DEFAULT_PUBLISH_ROUNDS = 8
DEFAULT_POLL_S = 0.5


def _strip_local(uri: str) -> str:
    return uri[7:] if uri.startswith("file://") else uri


class TrainerDaemon:
    """The continuous training loop: ingest → boost → publish, survivable
    at every instruction boundary.

    ``source`` is any object with ``next_batch(cursor) -> Batch | None``
    and ``exhausted(cursor) -> bool`` (:class:`~dmlc_core_tpu.train.
    source.DirectorySource` / :class:`~.source.FleetSource`).  ``param``
    carries the boosting hyperparameters; on resume its structural fields
    must match the restored checkpoint (:meth:`GBDT.resume` refuses a
    mismatch — the serving wire contract is frozen by the checkpoint).
    """

    def __init__(self, directory: str, source: Any, num_feature: int, *,
                 param: Optional[GBDTParam] = None,
                 manager: Optional[CheckpointManager] = None,
                 rounds_per_batch: int = 1,
                 publish_every_rounds: Optional[int] = None,
                 publish_every_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 keep: int = 8,
                 incarnation: int = 0,
                 state_file: Optional[str] = None):
        CHECK(rounds_per_batch >= 1, "rounds_per_batch must be >= 1")
        self.source = source
        self.num_feature = num_feature
        self.incarnation = incarnation
        self.state_file = state_file
        self.rounds_per_batch = rounds_per_batch
        self.publish_every_rounds = (
            publish_every_rounds if publish_every_rounds is not None
            else get_env("DMLC_TRAIN_PUBLISH_ROUNDS", int,
                         DEFAULT_PUBLISH_ROUNDS))
        self.publish_every_s = (
            publish_every_s if publish_every_s is not None
            else get_env("DMLC_TRAIN_PUBLISH_EVERY_S", float, 0.0))
        self.poll_s = (poll_s if poll_s is not None
                       else get_env("DMLC_TRAIN_POLL_S", float,
                                    DEFAULT_POLL_S))
        CHECK(self.poll_s > 0, "poll_s must be > 0")
        self.manager = manager or CheckpointManager(directory, keep=keep)
        param = param or GBDTParam()
        # guards every piece of mutable training state: the publish clock
        # thread snapshots model+cursor while the ingest loop trains, and
        # both sides bump the progress odometers
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._clock: Optional[threading.Thread] = None
        #: public progress odometers (lock-guarded; mirrored to
        #: ``dmlc_train_*`` counters and the ``state_file`` snapshot)
        self.rounds_completed = 0
        self.publishes_completed = 0
        self.publish_rejections = 0
        self.quarantined = 0
        self.ingest_failures = 0
        #: the step resume restored from (None = cold start)
        self.resumed_from: Optional[int] = None
        self._ensemble: Optional[TreeEnsemble] = None
        self._cursor = 0
        self._published_rounds = 0
        self._next_step = 1
        self._gbdt = GBDT(param, num_feature)
        self._resume(param)
        self._write_state_file()

    # -- resume ---------------------------------------------------------------

    def _resume(self, param: GBDTParam) -> None:
        """Restore from the last *valid* manifest, exactly like the
        serving watcher scans (shared ``latest_valid``), plus a byte
        re-hash: corrupt or torn steps are fallen past, a manifest-less
        newest step (a dead incarnation's interrupted publish) is skipped
        — and its step number reused, so the publish completes
        idempotently on the next cadence."""
        with telemetry.span("train.resume", incarnation=self.incarnation):
            step, manifest = self.manager.latest_valid(
                verify=True, skip_unpublished=True)
            steps = self.manager.all_steps()
            if step is None:
                # cold start: boundaries are fit from the first healthy
                # batch; any abandoned blobs still claim their numbers.
                # __init__ runs before the clock thread exists, but every
                # write to the shared state rides the lock anyway — one
                # lockset per field, no special cases
                with self._lock:
                    self._next_step = (steps[-1] + 1) if steps else 1
                    next_step = self._next_step
                log_info("train: cold start (no valid checkpoint); "
                         f"first publish will be step {next_step}")
                return
            flat = load_checkpoint(self.manager.step_uri(step))
            gbdt, ensemble = GBDT.resume(flat, param=param)
            CHECK(gbdt.num_feature == self.num_feature,
                  f"checkpoint serves {gbdt.num_feature} features; "
                  f"this trainer ingests {self.num_feature}")
            cursor = flat.get(f"['{CURSOR_KEY}']")
            rounds = flat.get(f"['{ROUND_KEY}']")
            restored_cursor = int(np.asarray(cursor).reshape(-1)[0]) \
                if cursor is not None else 0
            restored_rounds = int(np.asarray(rounds).reshape(-1)[0]) \
                if rounds is not None else ensemble.num_trees
            # abandoned manifest-LESS steps above the restored one get
            # overwritten, not leapfrogged: re-publish is idempotent.  A
            # manifested-but-corrupt step keeps its number retired — it
            # was once published, so a serving slot may carry it as a
            # live version; rewriting it with different trees would make
            # that version ambiguous.  Fresh work goes above it.
            newest = steps[-1] if steps else step
            orphans = [s for s in steps if s > step
                       and self.manager.read_manifest(s) is None]
            with self._lock:
                self._gbdt = gbdt
                self._ensemble = ensemble
                self._cursor = restored_cursor
                self.rounds_completed = restored_rounds
                self._published_rounds = restored_rounds
                self.resumed_from = step
                self._next_step = min(orphans) if orphans else newest + 1
                next_step = self._next_step
            telemetry.gauge_set("dmlc_train_resumed_step", step)
            log_info(f"train: resumed from step {step} "
                     f"(rounds={restored_rounds}, "
                     f"cursor={restored_cursor}, next step "
                     f"{next_step})")

    # -- lifecycle ------------------------------------------------------------

    def start_clock(self) -> "TrainerDaemon":
        """Start the wall-clock publish thread (``publish_every_s``);
        no-op when the cadence is 0/off."""
        if self.publish_every_s and self.publish_every_s > 0:
            CHECK(self._clock is None or not self._clock.is_alive(),
                  "publish clock already running")
            self._clock = threading.Thread(
                target=self._publish_clock,
                name=f"train-publish-{self.incarnation}", daemon=False)
            self._clock.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        self._stop.set()
        clock, self._clock = self._clock, None
        if clock is not None:
            clock.join(timeout)
            if clock.is_alive():
                log_warning("train: publish clock did not stop within "
                            f"{timeout}s; abandoning it")

    def __enter__(self) -> "TrainerDaemon":
        return self.start_clock()

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _publish_clock(self) -> None:
        while not self._stop.wait(self.publish_every_s):
            try:
                self.publish_now()
            except Exception as exc:  # noqa: BLE001 — ferried, not fatal
                log_warning(f"train: cadence publish failed: {exc!r}")

    # -- the loop -------------------------------------------------------------

    def run(self, *, max_batches: int = 0,
            exit_when_idle: bool = False) -> None:
        """Ingest→boost→publish until stopped.  ``max_batches`` bounds
        consumed batches (0 = unbounded); ``exit_when_idle`` returns once
        the source reports itself exhausted (batch-job drain) — with a
        final publish so nothing trained is left unpublished."""
        consumed = 0
        self.start_clock()
        try:
            while not self._stop.is_set():
                progressed = self.step_once()
                if progressed:
                    consumed += 1
                    if max_batches and consumed >= max_batches:
                        break
                    continue
                if exit_when_idle and self.source.exhausted(self._cursor):
                    break
                self._stop.wait(self.poll_s)
        finally:
            self.close()
            self.publish_now()   # drain: publish whatever trained last
            self._write_state_file()

    def step_once(self) -> bool:
        """One ingest+train step; True when a batch was consumed (healthy
        or quarantined), False when the source had nothing new."""
        batch = self._ingest_once()
        if batch is None:
            return False
        if not self._healthy(batch):
            return True
        self._train_on(batch)
        due = False
        with self._lock:
            if (self.publish_every_rounds and
                    self.rounds_completed - self._published_rounds
                    >= self.publish_every_rounds):
                due = True
        if due:
            self.publish_now()
        self._write_state_file()
        return True

    def _ingest_once(self) -> Optional[Batch]:
        with telemetry.span("train.ingest", cursor=self._cursor):
            try:
                fault.inject("train.ingest", cursor=self._cursor,
                             incarnation=self.incarnation)
                return self.source.next_batch(self._cursor)
            except Exception as exc:  # noqa: BLE001 — retried next tick
                with self._lock:
                    self.ingest_failures += 1
                telemetry.count("dmlc_train_ingest_failures_total")
                log_warning(f"train: ingest at cursor {self._cursor} "
                            f"failed ({exc!r}); retrying next tick")
                return None

    def _healthy(self, batch: Batch) -> bool:
        """Poison gate: quarantine-and-count, never fatal.  The cursor
        advances past the batch either way — a poisoned file must not
        wedge the ring."""
        reason = batch.error
        if reason is None:
            x, label = batch.x, batch.label
            if x.ndim != 2 or x.shape[1] != self.num_feature:
                reason = (f"feature arity {x.shape} != "
                          f"[n, {self.num_feature}] (schema drift)")
            elif x.dtype != np.float32:
                reason = f"dtype drift: {x.dtype} is not float32"
            elif label is None or not np.all(np.isfinite(label)):
                reason = "non-finite label"
            elif (not self._gbdt.param.handle_missing
                  and not np.all(np.isfinite(x))):
                reason = ("non-finite features without handle_missing "
                          "(NaN would poison binning)")
            elif np.any(np.isinf(x)):
                reason = "infinite feature values"
        if reason is None:
            return True
        with self._lock:
            self.quarantined += 1
            self._cursor = batch.cursor
        telemetry.count("dmlc_train_quarantined_total")
        telemetry.event("train.quarantined", origin=batch.origin,
                        reason=reason)
        log_warning(f"train: quarantined batch {batch.origin!r}: {reason}")
        return False

    def _train_on(self, batch: Batch) -> None:
        """Append ``rounds_per_batch`` boosting rounds on one batch.  The
        ensemble is replaced wholesale under the lock (never mutated), so
        the publish clock can snapshot mid-training safely."""
        if self._gbdt.boundaries is None:
            # cold start: quantile edges fit once, frozen forever after —
            # every later batch and every serving binner sees these exact
            # edges (the bitwise skew-free wire contract).  The fit rides
            # the lock: the publish clock reads boundaries through
            # serving_state, and this is the one write after threads start
            with self._lock:
                self._gbdt.make_bins(batch.x)
            log_info(f"train: fit {self.num_feature}-feature bin edges "
                     f"from first batch {batch.origin!r}")
        bins = self._gbdt.bin_features(batch.x)
        ensemble, margin = self._ensemble, None
        start = self.rounds_completed
        for r in range(self.rounds_per_batch):
            with telemetry.span("train.round", round=start + r):
                fault.inject("train.round", round=start + r,
                             incarnation=self.incarnation)
                ensemble, margin = self._gbdt.append_rounds(
                    ensemble, bins, batch.label, num_rounds=1,
                    margin=margin, start_round=start + r)
            telemetry.count("dmlc_train_rounds_total")
        with self._lock:
            self._ensemble = ensemble
            self._cursor = batch.cursor
            self.rounds_completed += self.rounds_per_batch
        telemetry.gauge_set("dmlc_train_cursor", batch.cursor)
        telemetry.gauge_set("dmlc_train_trees", ensemble.num_trees)

    # -- publish --------------------------------------------------------------

    def publish_now(self) -> Optional[int]:
        """Publish the current model if it has trained past the last
        published state; returns the published step or ``None`` (nothing
        new, or the publish was rejected by its own verify).

        Runs on the ingest loop (every-N-rounds cadence) AND the publish
        clock thread — the snapshot and the odometers are lock-guarded;
        the store IO runs outside the lock (training never stalls on a
        slow store)."""
        with self._lock:
            if (self._ensemble is None
                    or self.rounds_completed <= self._published_rounds):
                return None
            ensemble = self._ensemble
            cursor = self._cursor
            rounds = self.rounds_completed
            step = self._next_step
        state = self._gbdt.serving_state(ensemble, extra={
            CURSOR_KEY: np.array([cursor], np.int64),
            ROUND_KEY: np.array([rounds], np.int64)})
        try:
            with telemetry.span("train.publish", step=step):
                self._write_step(step, state)
        except Exception as exc:  # noqa: BLE001 — rejected, retried
            with self._lock:
                self.publish_rejections += 1
            telemetry.count("dmlc_train_publish_total", outcome="rejected")
            log_warning(f"train: publish of step {step} rejected "
                        f"({exc!r}); will re-publish the same step")
            return None
        with self._lock:
            self.publishes_completed += 1
            self._published_rounds = rounds
            self._next_step = step + 1
        telemetry.count("dmlc_train_publish_total", outcome="ok")
        log_info(f"train: published step {step} (rounds={rounds}, "
                 f"cursor={cursor})")
        self._write_state_file()
        return step

    def _write_step(self, step: int, state: Dict[str, Any]) -> None:
        """temp + verify + manifest-last.  A kill before the manifest
        write leaves an unpublished step no manifest-first reader opens;
        an injected (or real) torn write fails the verify and the step is
        re-published from scratch next cadence."""
        uri = self.manager.prepare_step(step)
        fault.inject("train.publish", step=step, phase="begin",
                     incarnation=self.incarnation)
        summary = save_checkpoint(uri, state)
        fault.inject("train.publish", step=step, phase="durable",
                     incarnation=self.incarnation)
        keep = fault.truncate("train.publish", summary["nbytes"],
                              step=step, phase="durable",
                              incarnation=self.incarnation)
        if keep < summary["nbytes"]:
            # chaos only: tear the durable blob the way a dying disk or a
            # non-atomic remote store would, BEFORE the verify
            with open(_strip_local(uri), "r+b") as f:
                f.truncate(keep)
        verify_checkpoint(uri, summary)
        self.manager.publish(step, summary)

    # -- introspection --------------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "incarnation": self.incarnation,
                "resumed_from": self.resumed_from,
                "cursor": self._cursor,
                "rounds_completed": self.rounds_completed,
                "publishes_completed": self.publishes_completed,
                "publish_rejections": self.publish_rejections,
                "quarantined": self.quarantined,
                "ingest_failures": self.ingest_failures,
                "next_step": self._next_step,
                "trees": (self._ensemble.num_trees
                          if self._ensemble is not None else 0),
            }

    def _write_state_file(self) -> None:
        """Atomic progress snapshot for supervisors (the chaos drill
        asserts resume provenance from it after every kill)."""
        if not self.state_file:
            return
        tmp = f"{self.state_file}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.describe(), f, indent=1, sort_keys=True)
        os.replace(tmp, self.state_file)
