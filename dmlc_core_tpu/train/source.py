"""Batch sources for the continuous trainer (docs/training.md).

A source turns "where training data comes from" into one cursor-addressed
call: ``next_batch(cursor)`` returns the next dense host batch after
``cursor`` or ``None`` when nothing new has landed yet.  The cursor is the
source's own resume token — the daemon persists it inside every published
checkpoint (the ``train_cursor`` leaf rides the same atomic blob as the
trees), so a crashed trainer restarts exactly where its last *published*
state left off and retrains only the rounds that were lost with it.

Two sources close the PR 12 → PR 13 ring:

:class:`DirectorySource`
    single-host spool: a directory of data files (libsvm/CSV/columnar —
    anything :func:`~dmlc_core_tpu.data.factory.create_parser` speaks),
    consumed once each in name order.  New files appearing later are
    picked up on the next poll; a ``_DONE`` sentinel marks the spool
    finished so batch jobs can drain and exit.  A file that fails to
    parse is returned as a *poison* batch (``error`` set) — the daemon
    quarantines and counts it, the cursor advances, training continues.

:class:`FleetSource`
    the PR 12 fleet-ingest path: drives :func:`~dmlc_core_tpu.parallel.
    fleet_ingest.run_worker` against a ``ShardLeaseCoordinator`` on a
    background thread, ferrying each densified unit into a bounded queue.
    Lease bookkeeping stays coordinator-side (exactly-once *coverage*);
    the training feed itself is at-least-once — a unit whose commit is
    rejected was already handed to the boosting loop.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from dmlc_core_tpu.utils.logging import CHECK, log_warning

__all__ = ["Batch", "DirectorySource", "FleetSource", "DONE_SENTINEL"]

# an empty file of this name in a spool directory = no more data is coming
DONE_SENTINEL = "_DONE"


class Batch(NamedTuple):
    """One dense host batch, or a poison marker when ``error`` is set."""

    x: Optional[np.ndarray]        # [n, F] float32 (None on poison)
    label: Optional[np.ndarray]    # [n] float32 (None on poison)
    origin: str                    # file / unit the rows came from
    cursor: int                    # source position AFTER this batch
    error: Optional[str] = None    # parse failure → poison, not fatal


class DirectorySource:
    """Spool-directory source: files consumed once each, in name order.

    ``cursor`` counts consumed files over the name-sorted listing — files
    must land with monotonically increasing names (timestamps, sequence
    numbers) and never be renamed, the usual spool contract.  ``nan_fill``
    densifies absent libsvm features as NaN instead of 0.0 (the
    sparsity-aware ``handle_missing`` training mode).
    """

    def __init__(self, directory: str, num_feature: int, *,
                 nan_fill: bool = False):
        CHECK(num_feature >= 1, "num_feature must be >= 1")
        self.directory = directory
        self.num_feature = num_feature
        self.nan_fill = nan_fill

    def _files(self) -> List[str]:
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        return sorted(n for n in names
                      if not n.startswith((".", "_")))

    def next_batch(self, cursor: int) -> Optional[Batch]:
        files = self._files()
        if cursor >= len(files):
            return None
        name = files[cursor]
        path = os.path.join(self.directory, name)
        try:
            x, label = self._parse(path)
        except Exception as exc:  # noqa: BLE001 — poison, not fatal
            return Batch(None, None, path, cursor + 1, error=repr(exc))
        return Batch(x, label, path, cursor + 1)

    def exhausted(self, cursor: int) -> bool:
        """True when every spooled file is consumed AND the ``_DONE``
        sentinel says no more are coming (batch-job drain)."""
        if not os.path.exists(os.path.join(self.directory, DONE_SENTINEL)):
            return False
        return cursor >= len(self._files())

    def _parse(self, path: str):
        from dmlc_core_tpu.bridge.batching import block_to_dense
        from dmlc_core_tpu.data.factory import create_parser

        fill = np.nan if self.nan_fill else 0.0
        xs: List[np.ndarray] = []
        ys: List[np.ndarray] = []
        parser = create_parser(path, threaded=False)
        try:
            for block in parser:
                if not block.size:
                    continue
                dense = block_to_dense(block, self.num_feature,
                                       fill_value=fill)
                xs.append(np.ascontiguousarray(dense.x[:block.size],
                                               dtype=np.float32))
                ys.append(np.asarray(dense.label[:block.size],
                                     dtype=np.float32))
        finally:
            if hasattr(parser, "close"):
                parser.close()
        CHECK(bool(xs), f"{path!r} parsed to zero rows")
        return np.concatenate(xs, axis=0), np.concatenate(ys, axis=0)


class FleetSource:
    """Trainer feed over the PR 12 shard-lease fleet (one worker's view).

    Runs :func:`~dmlc_core_tpu.parallel.fleet_ingest.run_worker` on a
    background thread with a processor that densifies each leased unit
    and ferries it here through a bounded queue; ``next_batch`` drains the
    queue.  The coordinator's ledger keeps unit *coverage* exactly-once;
    the feed is at-least-once (a rejected commit's rows were already
    yielded).  The cursor counts delivered units — it resumes the queue
    position after a trainer restart within one coordinator epoch, but a
    restarted epoch re-leases every unit (the coordinator owns coverage,
    not this adapter).
    """

    def __init__(self, worker_id: str, num_feature: int, *,
                 host: Optional[str] = None, port: Optional[int] = None,
                 nan_fill: bool = False, max_queued: int = 8):
        self.worker_id = worker_id
        self.num_feature = num_feature
        self.nan_fill = nan_fill
        self._queue: "queue.Queue[Batch]" = queue.Queue(maxsize=max_queued)
        self._done = threading.Event()
        self._delivered = 0
        self._host = host
        self._port = port
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetSource":
        CHECK(self._thread is None, "FleetSource already started")
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name=f"train-fleet-{self.worker_id}")
        self._thread.start()
        return self

    def _pump(self) -> None:
        from dmlc_core_tpu.parallel.fleet_ingest import run_worker

        try:
            run_worker(self.worker_id, self._host, self._port,
                       processor=self._process_unit)
        except Exception as exc:  # noqa: BLE001 — surfaced as exhaustion
            log_warning(f"train: fleet source worker {self.worker_id!r} "
                        f"failed: {exc!r}")
        finally:
            self._done.set()

    def _process_unit(self, spec: Dict[str, Any],
                      accum: Any = None) -> Dict[str, Any]:
        from dmlc_core_tpu.bridge.batching import block_to_dense
        from dmlc_core_tpu.data.factory import create_parser

        fill = np.nan if self.nan_fill else 0.0
        parser = create_parser(spec["uri"], int(spec.get("part", 0)),
                               int(spec.get("nparts", 1)),
                               type=spec.get("format", "auto"),
                               threaded=False)
        xs: List[np.ndarray] = []
        ys: List[np.ndarray] = []
        rows = 0
        try:
            for block in parser:
                if not block.size:
                    continue
                rows += block.size
                dense = block_to_dense(block, self.num_feature,
                                       fill_value=fill)
                xs.append(np.ascontiguousarray(dense.x[:block.size],
                                               dtype=np.float32))
                ys.append(np.asarray(dense.label[:block.size],
                                     dtype=np.float32))
                if accum is not None:
                    accum.add(xs[-1])
        finally:
            if hasattr(parser, "close"):
                parser.close()
        if xs:
            self._delivered += 1
            origin = f"{spec.get('uri')}#{spec.get('part', 0)}"
            self._queue.put(Batch(np.concatenate(xs, axis=0),
                                  np.concatenate(ys, axis=0),
                                  origin, self._delivered))
        return {"rows": rows, "batches": 1 if xs else 0}

    def next_batch(self, cursor: int) -> Optional[Batch]:
        try:
            return self._queue.get(timeout=0.05)
        except queue.Empty:
            return None

    def exhausted(self, cursor: int) -> bool:
        return self._done.is_set() and self._queue.empty()
