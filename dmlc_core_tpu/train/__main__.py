"""CLI entry for the continuous trainer daemon (docs/training.md).

Single-host spool::

    python -m dmlc_core_tpu.train --data /spool --ckpt /ckpts \\
        --num-feature 16 --rounds-per-batch 2 --publish-every-rounds 4 \\
        --exit-when-idle

Fleet-fed (PR 12 shard leases; coordinator address via
``DMLC_FLEET_LEASE_URI``/``DMLC_FLEET_LEASE_PORT`` or flags)::

    python -m dmlc_core_tpu.train --fleet-worker w0 --ckpt /ckpts \\
        --num-feature 16

Telemetry rides the usual env bring-up (``DMLC_TELEMETRY_DIR``), chaos
the usual ``DMLC_FAULT_PLAN`` — both are read at import.  The process is
designed to be killed: a supervisor restarting it with ``--incarnation``
bumped gets a daemon that resumes from the last valid manifest and
re-publishes anything torn (the chaos drill in benchmarks/bench_serving.py
``continuous`` does exactly this).
"""

from __future__ import annotations

import argparse
import sys

from dmlc_core_tpu.models.gbdt import GBDTParam
from dmlc_core_tpu.train.daemon import TrainerDaemon
from dmlc_core_tpu.train.source import DirectorySource, FleetSource
from dmlc_core_tpu.utils.logging import log_info


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dmlc_core_tpu.train",
        description="continuous GBDT trainer daemon: ingest -> boost -> "
                    "publish manifest-first checkpoints for hot swap")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--data", help="spool directory of data files "
                     "(consumed once each, in name order)")
    src.add_argument("--fleet-worker", metavar="ID",
                     help="feed from the fleet shard-lease coordinator "
                          "as this worker id")
    ap.add_argument("--ckpt", required=True,
                    help="checkpoint directory (URI or local path)")
    ap.add_argument("--num-feature", type=int, required=True)
    ap.add_argument("--fleet-host", default=None)
    ap.add_argument("--fleet-port", type=int, default=None)
    ap.add_argument("--rounds-per-batch", type=int, default=1)
    ap.add_argument("--publish-every-rounds", type=int, default=None,
                    help="publish cadence in boosting rounds "
                         "(DMLC_TRAIN_PUBLISH_ROUNDS, default 8)")
    ap.add_argument("--publish-every-s", type=float, default=None,
                    help="wall-clock publish cadence, 0=off "
                         "(DMLC_TRAIN_PUBLISH_EVERY_S)")
    ap.add_argument("--poll-s", type=float, default=None,
                    help="idle source poll (DMLC_TRAIN_POLL_S, default 0.5)")
    ap.add_argument("--keep", type=int, default=8,
                    help="checkpoint retention (local steps kept)")
    ap.add_argument("--max-batches", type=int, default=0,
                    help="stop after N consumed batches (0 = unbounded)")
    ap.add_argument("--exit-when-idle", action="store_true",
                    help="return once the source reports exhausted "
                         "(spool _DONE sentinel / fleet drained)")
    ap.add_argument("--incarnation", type=int, default=0,
                    help="supervisor restart counter; rides every train.* "
                         "fault context so chaos plans can target one life")
    ap.add_argument("--state-file", default=None,
                    help="atomic JSON progress snapshot for supervisors")
    ap.add_argument("--nan-fill", action="store_true",
                    help="densify absent features as NaN (handle_missing)")
    ap.add_argument("--learning-rate", type=float, default=0.1)
    ap.add_argument("--max-depth", type=int, default=4)
    ap.add_argument("--num-bins", type=int, default=64)
    ap.add_argument("--objective", default="logistic",
                    choices=["logistic", "squared", "softmax"])
    args = ap.parse_args(argv)

    param = GBDTParam()
    param.update({"learning_rate": args.learning_rate,
                  "max_depth": args.max_depth,
                  "num_bins": args.num_bins,
                  "objective": args.objective,
                  "handle_missing": args.nan_fill})
    if args.data:
        source = DirectorySource(args.data, args.num_feature,
                                 nan_fill=args.nan_fill)
    else:
        source = FleetSource(args.fleet_worker, args.num_feature,
                             host=args.fleet_host, port=args.fleet_port,
                             nan_fill=args.nan_fill).start()
    daemon = TrainerDaemon(
        args.ckpt, source, args.num_feature, param=param,
        rounds_per_batch=args.rounds_per_batch,
        publish_every_rounds=args.publish_every_rounds,
        publish_every_s=args.publish_every_s, poll_s=args.poll_s,
        keep=args.keep, incarnation=args.incarnation,
        state_file=args.state_file)
    daemon.run(max_batches=args.max_batches,
               exit_when_idle=args.exit_when_idle)
    final = daemon.describe()
    log_info(f"train: daemon done: {final}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
