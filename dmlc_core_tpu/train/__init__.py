"""Continuous training ring: crash-tolerant trainer daemon (docs/training.md).

``python -m dmlc_core_tpu.train`` runs :class:`~.daemon.TrainerDaemon`
against a spool directory (:class:`~.source.DirectorySource`) or the
PR 12 shard-lease fleet (:class:`~.source.FleetSource`), publishing
manifest-first checkpoints the PR 13 serving watcher hot-swaps live.
"""

from dmlc_core_tpu.train.daemon import CURSOR_KEY, ROUND_KEY, TrainerDaemon
from dmlc_core_tpu.train.source import (Batch, DirectorySource, DONE_SENTINEL,
                                        FleetSource)

__all__ = ["TrainerDaemon", "DirectorySource", "FleetSource", "Batch",
           "DONE_SENTINEL", "CURSOR_KEY", "ROUND_KEY"]
