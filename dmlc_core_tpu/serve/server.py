"""The scoring transport: stdlib HTTP in front of the model registry.

Endpoints (all JSON):

- ``POST /v1/score`` / ``POST /v1/score/<model>`` — ``{"instances":
  [row, ...]}`` where a row is either a dense ``[f0, f1, ...]`` list of
  ``num_feature`` numbers or a sparse ``{"index": [...], "value": [...]}``
  pair (feature ids in ``[0, num_feature)``); the bare path routes to the
  registry's default slot, the suffixed form to the named slot (unknown
  names are a structured 404).  Answers ``{"predictions": [...],
  "model": <slot>, "version": <checkpoint step>, "num_rows": n}`` or a
  structured error envelope (:mod:`.errors`) — the version field is how a
  client (and the hot-swap chaos drill) pins which model build answered;
- ``GET /healthz`` — liveness + per-slot model identity/version;
- ``GET /metrics`` — the telemetry registry in Prometheus text form;
- ``GET /stats`` — the serving SLO snapshot: per-histogram count/mean and
  p50/p95/p99 derived via :func:`dmlc_core_tpu.telemetry.report.
  estimate_quantiles` (the same math the offline report uses), plus each
  slot's identity block.

Every request runs inside a ``serve.request`` telemetry span and lands in
``dmlc_serve_request_seconds{status=...}``; the ``serve.request`` fault
site fires before parsing (``http_status`` rules *replace* the response —
the chaos 503 storm — act rules model slow/broken connections).

The server is ``ThreadingHTTPServer``: one handler thread per connection,
all funneling into the single batcher thread — concurrency without a
thread-per-request predict path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.serve.errors import (BadRequest, RequestTimeout,
                                        ServeError)
from dmlc_core_tpu.serve.model_runtime import ModelRuntime
from dmlc_core_tpu.serve.registry import ModelRegistry, ModelSlot
from dmlc_core_tpu.telemetry import clock, tracecontext
from dmlc_core_tpu.telemetry.report import (REPORT_QUANTILES, _label_str,
                                            estimate_quantiles)
from dmlc_core_tpu.utils.logging import log_debug, log_info, log_warning

__all__ = ["ScoringServer", "parse_instances", "healthz_payload",
           "route_slot"]

MAX_BODY_BYTES = 8 << 20  # one request, not a bulk upload

# the two transports behind DMLC_SERVE_TRANSPORT: "threaded" is the
# original ThreadingHTTPServer (one handler thread per connection),
# "evloop" is the selectors-based non-blocking front end
# (serve/eventloop.py) that holds 10k+ keep-alive connections on a
# couple of event-loop threads
TRANSPORTS = ("threaded", "evloop")


def parse_instances(obj: Any, num_feature: int) -> np.ndarray:
    """Validate + densify a ``{"instances": [...]}`` body to [n, F] f32.

    Every malformed shape raises :class:`BadRequest` naming the offending
    row — a scoring client debugging a 400 should never need server logs.
    """
    if not isinstance(obj, dict):
        raise BadRequest("body must be a JSON object")
    instances = obj.get("instances")
    if not isinstance(instances, list) or not instances:
        raise BadRequest("'instances' must be a non-empty list")
    out = np.zeros((len(instances), num_feature), np.float32)
    for i, row in enumerate(instances):
        if isinstance(row, list):
            if len(row) != num_feature:
                raise BadRequest(
                    f"instances[{i}]: expected {num_feature} features, "
                    f"got {len(row)}")
            try:
                out[i] = np.asarray(row, dtype=np.float32)
            except (TypeError, ValueError):
                raise BadRequest(
                    f"instances[{i}]: non-numeric feature value") from None
            if not np.isfinite(out[i]).all():
                # json.loads admits 1e400/NaN; letting them through would
                # end in a 200 whose body strict JSON parsers reject
                raise BadRequest(
                    f"instances[{i}]: non-finite feature value")
        elif isinstance(row, dict):
            idx, val = row.get("index"), row.get("value")
            if not isinstance(idx, list) or not isinstance(val, list) \
                    or len(idx) != len(val):
                raise BadRequest(
                    f"instances[{i}]: sparse rows need equal-length "
                    "'index' and 'value' lists")
            try:
                ids = np.asarray(idx, dtype=np.int64)
                vals = np.asarray(val, dtype=np.float32)
                # np.asarray silently truncates 1.7 -> 1: a float feature
                # id is a client bug that must 400, not mis-route a value
                if not np.array_equal(np.asarray(idx, dtype=np.float64),
                                      ids):
                    raise BadRequest(
                        f"instances[{i}]: non-integer feature index")
            except (TypeError, ValueError):
                raise BadRequest(
                    f"instances[{i}]: non-numeric index/value") from None
            if vals.size and not np.isfinite(vals).all():
                raise BadRequest(
                    f"instances[{i}]: non-finite feature value")
            if ids.size and (ids.min() < 0 or ids.max() >= num_feature):
                raise BadRequest(
                    f"instances[{i}]: feature index out of "
                    f"[0, {num_feature})")
            out[i, ids] = vals
        else:
            raise BadRequest(
                f"instances[{i}]: each row must be a list of "
                f"{num_feature} numbers or a sparse index/value object")
    return out


def healthz_payload(app: "ScoringServer") -> Dict[str, Any]:
    """The enriched ``/healthz`` body both transports serve: "status"
    keeps the plain ok/draining probe semantics existing checks rely on,
    "admission" adds the per-model load state the router routes on
    (queue-bytes, budget, shed EWMA)."""
    default = app.registry.get()
    return {
        "status": "draining" if app.draining else "ok",
        "model": default.family,
        "version": default.version,
        "num_feature": default.num_feature,
        "max_batch": default.batcher.max_batch,
        "models": app.registry.describe(),
        "admission": {
            name: app.registry.get(name).admission.describe()
            for name in app.registry.names()},
        "in_flight": app.in_flight,
        "uptime_s": round(clock.monotonic() - app.started_at, 3)}


def route_slot(app: "ScoringServer", path: str) -> ModelSlot:
    """``/v1/score`` -> default slot; ``/v1/score/<model>`` -> named
    slot (structured 404 for unknown names, 400 for other paths)."""
    if path == "/v1/score":
        return app.registry.get()
    if path.startswith("/v1/score/"):
        return app.registry.get(path[len("/v1/score/"):])
    raise BadRequest(f"no such path {path!r}")


class _Handler(BaseHTTPRequestHandler):
    server_version = "dmlc-serve/0.1"
    protocol_version = "HTTP/1.1"
    # per-socket deadline: a client announcing more body bytes than it
    # sends (or idling mid-request) must not pin a handler thread forever
    # — the same discipline as DMLC_TRACKER_SOCK_TIMEOUT on the tracker
    timeout = 30.0

    # the app (ScoringServer) rides on the HTTPServer instance
    @property
    def app(self) -> "ScoringServer":
        return self.server.app  # type: ignore[attr-defined]

    def log_message(self, fmt: str, *args: Any) -> None:
        # BaseHTTPRequestHandler prints to stderr; route through the
        # repo's logging (and keep per-request lines at debug verbosity)
        log_debug(2, f"serve: {self.address_string()} {fmt % args}")

    # -- plumbing -------------------------------------------------------------

    def _respond(self, status: int, body: bytes,
                 headers: Optional[Dict[str, str]] = None,
                 content_type: str = "application/json") -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            if k.lower() not in ("content-type", "content-length"):
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _respond_json(self, status: int, payload: Dict[str, Any],
                      headers: Optional[Dict[str, str]] = None) -> None:
        self._respond(status, json.dumps(payload, sort_keys=True).encode(),
                      headers)

    def _respond_error(self, exc: ServeError) -> None:
        self._respond(exc.status, exc.body(), exc.headers())

    # -- routes ---------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        app = self.app
        try:
            if self.path == "/healthz":
                self._respond_json(200, healthz_payload(app))
            elif self.path == "/metrics":
                self._respond(200, telemetry.prometheus_text().encode(),
                              content_type="text/plain; version=0.0.4")
            elif self.path == "/stats":
                self._respond_json(200, app.stats())
            else:
                self._respond_error(BadRequest(f"no such path "
                                               f"{self.path!r}"))
        except ServeError as exc:
            # e.g. /healthz or /stats on a registry with no slots: the
            # probe must read a structured error, not a dropped connection
            self._respond_error(exc)

    def do_POST(self) -> None:  # noqa: N802 (http.server contract)
        # the in-flight odometer brackets the WHOLE request so a drain
        # (SIGTERM rolling restart) only exits once every admitted
        # request has been answered — including its error envelope
        app = self.app
        app._request_begin()
        try:
            self._handle_post(app)
        finally:
            app._request_end()

    def _handle_post(self, app: "ScoringServer") -> None:
        t0 = clock.monotonic()
        status = 500
        # route first: the per-model label every request-path metric
        # carries must name the slot, and an unroutable request must not
        # invent unbounded label values out of hostile paths
        model_label = "_unrouted"
        try:
            slot = self._route(app)
            model_label = slot.name
        except ServeError as exc:
            # the body was never read: keeping this keep-alive connection
            # would parse it as the next request line (same discipline as
            # every other early-response path)
            self.close_connection = True
            self._respond_error(exc)
            telemetry.count("dmlc_serve_requests_total", model=model_label,
                            status=exc.status)
            telemetry.observe("dmlc_serve_request_seconds",
                              clock.monotonic() - t0, model=model_label,
                              status=exc.status)
            return
        # continue the caller's W3C trace when one is announced: the
        # serve.request span (and everything the handler does under it —
        # batcher wait, predict share) joins the client's trace_id, which
        # is what lets the offline assembler resolve a scored request to
        # exactly one cross-process trace.  A malformed header decodes to
        # None and the request simply runs untraced (W3C: ignore, never 500)
        ctx = tracecontext.from_traceparent(self.headers.get("traceparent"))
        try:
            with tracecontext.activate(ctx), \
                    telemetry.span("serve.request", model=model_label):
                injected = fault.http_response("serve.request")
                if injected is not None:
                    i_status, i_headers, i_body = injected
                    status = i_status
                    if status == 503:
                        telemetry.count("dmlc_serve_shed_total",
                                        model=model_label,
                                        reason="injected_503")
                    # the request body was never read: keeping this
                    # keep-alive connection would parse it as the next
                    # request line
                    self.close_connection = True
                    self._respond(status, i_body or b'{"error": '
                                  b'{"code": "injected"}}', i_headers)
                    return
                # act kinds: delay/stall = a slow server thread; reset =
                # the connection dying mid-request (the one outcome a
                # client counts as crashed)
                fault.inject("serve.request")
                status, payload, headers = self._score(app, slot)
                self._respond_json(status, payload, headers)
        except ServeError as exc:
            status = exc.status
            self._respond_error(exc)
        except (BrokenPipeError, ConnectionResetError):
            # client (or an injected reset) tore the socket down: there is
            # no one left to answer — close, count, survive
            status = 0
            telemetry.count("dmlc_serve_connection_aborts_total")
            self.close_connection = True
        except Exception as exc:  # noqa: BLE001 — the 500 of last resort
            status = 500
            log_warning(f"serve: unexpected error handling request: {exc!r}")
            # the body may be partially read or unread here: keeping the
            # keep-alive connection would desync its framing (same reason
            # every early-response path above closes)
            self.close_connection = True
            try:
                self._respond_error(ServeError(f"internal error: {exc}"))
            except OSError:
                pass
        finally:
            telemetry.count("dmlc_serve_requests_total", model=model_label,
                            status=status)
            telemetry.observe("dmlc_serve_request_seconds",
                              clock.monotonic() - t0, model=model_label,
                              status=status)

    def _route(self, app: "ScoringServer") -> ModelSlot:
        return route_slot(app, self.path)

    def _score(self, app: "ScoringServer", slot: ModelSlot) \
            -> Tuple[int, Dict[str, Any], Optional[Dict[str, str]]]:
        try:
            length = int(self.headers.get("Content-Length", ""))
        except ValueError:
            self.close_connection = True  # unread body would desync keep-alive
            raise BadRequest("Content-Length required") from None
        if length < 0:
            # rfile.read(-1) would block until client EOF — a hostile
            # header must not pin a handler thread
            self.close_connection = True
            raise BadRequest(f"invalid Content-Length {length}")
        if length > MAX_BODY_BYTES:
            # responding without draining would desync this keep-alive
            # connection; the body is too big to drain, so drop the link
            self.close_connection = True
            exc = BadRequest(
                f"body of {length} bytes exceeds {MAX_BODY_BYTES}")
            exc.status = 413
            exc.code = "payload_too_large"
            raise exc
        raw = self.rfile.read(length)
        try:
            obj = json.loads(raw)
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise BadRequest(f"body is not valid JSON: {e}") from None
        rows = parse_instances(obj, slot.num_feature)
        future = slot.batcher.submit(rows)
        try:
            preds = future.result(timeout=app.request_timeout_s)
        except FutureTimeout:
            telemetry.count("dmlc_serve_shed_total", model=slot.name,
                            reason="timeout")
            raise RequestTimeout(
                f"not answered within {app.request_timeout_s}s "
                "(queue + predict)", details={
                    "timeout_s": app.request_timeout_s}) from None
        preds = np.asarray(preds)
        if not np.isfinite(preds).all():
            # finite inputs produced a non-finite score (model overflow):
            # a structured 500 beats a 200 body of RFC-invalid Infinity
            raise ServeError("model produced a non-finite prediction")
        # the version of the runtime that actually computed these
        # predictions (the batcher annotates it from its per-batch
        # runtime snapshot) — NOT the slot's current version, which a
        # swap landing mid-request could have moved past the scoring one.
        # The hot-swap drill asserts predictions match this exact version.
        version = getattr(future, "dmlc_served_version", None)
        return 200, {"predictions": preds.tolist(),
                     "model": slot.name,
                     "version": version if version is not None
                     else slot.version,
                     "num_rows": int(rows.shape[0])}, None


class _Server(ThreadingHTTPServer):
    daemon_threads = True       # handler threads must not block shutdown
    allow_reuse_address = True
    # socketserver's default listen backlog is 5: an open-loop burst (every
    # client is a fresh connection) overflows it and the kernel RSTs the
    # excess — which a client can only read as a crash.  Deep backlog +
    # admission control is the correct order: shed with a structured 503,
    # never with a refused connection.
    request_queue_size = 128

    def handle_error(self, request, client_address) -> None:
        # default prints a traceback to stderr per dropped connection —
        # under an injected reset storm that is pure noise
        log_debug(1, f"serve: connection error from {client_address}")


class ScoringServer:
    """The assembled service: model registry + transport.

    Construct with either a single :class:`~.model_runtime.ModelRuntime`
    (wrapped into a one-slot registry named after the runtime family —
    the pre-lifecycle API, unchanged for existing callers) or a
    pre-populated :class:`~.registry.ModelRegistry` whose slots carry
    their own batch/budget knobs (the knob arguments here then apply to
    nothing and must be left at their defaults).
    """

    def __init__(self, model: "ModelRuntime | ModelRegistry", *,
                 host: str = "127.0.0.1",
                 port: int = 0, max_batch: int = 64,
                 max_delay_ms: float = 2.0,
                 max_queue_bytes: Optional[int] = None,
                 request_timeout_s: float = 10.0, warmup: bool = True,
                 transport: Optional[str] = None):
        if isinstance(model, ModelRegistry):
            # slots already carry their own knobs: a knob passed HERE
            # would be silently dropped — make the misuse loud instead
            if (max_batch, max_delay_ms, max_queue_bytes) != (64, 2.0,
                                                              None):
                raise ValueError(
                    "max_batch/max_delay_ms/max_queue_bytes are per-slot "
                    "knobs: set them on registry.add(...), not on "
                    "ScoringServer when passing a ModelRegistry")
            self.registry = model
        else:
            self.registry = ModelRegistry()
            self.registry.add(model.name, model, max_batch=max_batch,
                              max_delay_ms=max_delay_ms,
                              max_queue_bytes=max_queue_bytes,
                              default=True)
        self.request_timeout_s = float(request_timeout_s)
        self._warmup = warmup
        # transport selection: the argument wins, then DMLC_SERVE_TRANSPORT,
        # then the threaded default — the env form is what lets the parity
        # test rig (and a fleet of replica subprocesses) flip every server
        # in a process tree without touching call sites
        if transport is None:
            transport = os.environ.get("DMLC_SERVE_TRANSPORT", "threaded")
        transport = (transport or "threaded").strip().lower()
        if transport not in TRANSPORTS:
            raise ValueError(
                f"unknown serve transport {transport!r}: expected one of "
                f"{TRANSPORTS} (DMLC_SERVE_TRANSPORT)")
        self.transport = transport
        if transport == "evloop":
            # imported lazily: eventloop imports this module for the
            # shared request plumbing (parse_instances, healthz_payload)
            from dmlc_core_tpu.serve.eventloop import EventLoopServer
            self._httpd = EventLoopServer((host, port), app=self)
        else:
            self._httpd = _Server((host, port), _Handler)
            self._httpd.app = self  # type: ignore[attr-defined]
        self._serve_thread: Optional[threading.Thread] = None
        # drain/lifecycle state: handler threads bump the in-flight
        # odometer, the drain path and /healthz read it
        self._state_lock = threading.Lock()
        self._in_flight = 0
        self._draining = False
        self._closed = False
        self.started_at = clock.monotonic()

    # -- drain bookkeeping (handler threads + the SIGTERM path) ---------------

    def _request_begin(self) -> None:
        with self._state_lock:
            self._in_flight += 1

    def _request_end(self) -> None:
        with self._state_lock:
            self._in_flight -= 1

    @property
    def in_flight(self) -> int:
        with self._state_lock:
            return self._in_flight

    @property
    def draining(self) -> bool:
        with self._state_lock:
            return self._draining

    # -- single-model compatibility views (the default slot's pieces) ---------

    @property
    def runtime(self) -> ModelRuntime:
        return self.registry.get().runtime

    @property
    def batcher(self):
        return self.registry.get().batcher

    @property
    def admission(self):
        return self.registry.get().admission

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "ScoringServer":
        self.registry.start(warmup=self._warmup)
        self.started_at = clock.monotonic()
        self._serve_thread = threading.Thread(
            target=self._serve, name="serve-http", daemon=False)
        self._serve_thread.start()
        names = self.registry.names()
        if names:
            default = self.registry.get()
            log_info(f"serve: listening on {self.url} "
                     f"(transport={self.transport}, models={names}, "
                     f"default={default.name}:{default.family}, "
                     f"max_batch={default.batcher.max_batch}, "
                     f"max_delay_ms={default.batcher.max_delay_s * 1e3:g}, "
                     f"max_queue_bytes={default.admission.max_queue_bytes})")
        else:
            # an empty registry can still serve /metrics and structured
            # 404s — a deploy that adds slots before routing traffic
            log_info(f"serve: listening on {self.url} (no models "
                     "registered yet)")
        return self

    def _serve(self) -> None:
        try:
            self._httpd.serve_forever(poll_interval=0.1)
        except Exception as exc:  # noqa: BLE001 — ferried, not swallowed
            log_warning(f"serve: listener exited abnormally: {exc!r}")

    def drain(self, timeout_s: Optional[float] = None,
              settle_s: float = 0.5) -> None:
        """Zero-downtime shutdown: stop being a routing target, finish
        every in-flight request, then close.

        Flips ``/healthz`` to ``"draining"`` immediately (the router's
        prober stops routing here within one probe interval), keeps
        serving for at least ``settle_s`` (covering that notice window —
        requests already routed our way must land, not crash), waits for
        the in-flight odometer to hit zero, then closes.  Bounded by
        ``timeout_s`` (default ``DMLC_SERVE_DRAIN_S``, 10s): a wedged
        request must not turn a rolling restart into a hung deploy.
        """
        with self._state_lock:
            already = self._draining
            self._draining = True
        if not already:
            log_info(f"serve: draining {self.url} "
                     f"(in_flight={self.in_flight})")
        if timeout_s is None:
            timeout_s = float(os.environ.get("DMLC_SERVE_DRAIN_S", "10"))
        start = clock.monotonic()
        deadline = start + max(float(timeout_s), 0.0)
        while clock.monotonic() < deadline:
            if self.in_flight == 0 \
                    and clock.monotonic() - start >= settle_s:
                break
            time.sleep(0.05)
        leftover = self.in_flight
        if leftover:
            log_warning(f"serve: drain deadline ({timeout_s:g}s) hit with "
                        f"{leftover} request(s) still in flight")
        else:
            log_info(f"serve: drained in "
                     f"{clock.monotonic() - start:.2f}s, shutting down")
        self.close()

    def close(self) -> None:
        with self._state_lock:
            if self._closed:
                return  # drain() already closed us; __exit__ is a no-op
            self._closed = True
        self._httpd.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(10.0)
            self._serve_thread = None
        self._httpd.server_close()
        self.registry.close()

    def __enter__(self) -> "ScoringServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- the SLO snapshot -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Live serving stats: counters + histogram quantiles, the same
        estimates the offline ``telemetry report`` prints."""
        default = self.registry.get()
        out: Dict[str, Any] = {
            "model": default.family,
            "version": default.version,
            "models": {
                name: dict(info,
                           queue_bytes=self.registry.get(name)
                           .admission.queued_bytes)
                for name, info in self.registry.describe().items()},
            "queue_bytes": default.admission.queued_bytes,
            "max_queue_bytes": default.admission.max_queue_bytes,
            "uptime_s": round(clock.monotonic() - self.started_at, 3),
            "metrics": {},
        }
        for fam in telemetry.get_registry().families():
            if not fam.name.startswith("dmlc_serve_"):
                continue
            for key, child in fam.samples():
                # the same renderer the offline report uses, so /stats
                # series names join 1:1 against the aggregated table
                series = fam.name + _label_str(dict(key))
                if fam.kind == "counter":
                    out["metrics"][series] = child.value
                elif fam.kind == "gauge":
                    out["metrics"][series] = child.value
                else:
                    counts = child.bucket_counts
                    ests = estimate_quantiles(
                        child.buckets, counts,
                        [q for _, q in REPORT_QUANTILES])
                    entry: Dict[str, Any] = {
                        "count": child.count,
                        "mean": (child.sum / child.count
                                 if child.count else None)}
                    for (name, _), est in zip(REPORT_QUANTILES, ests):
                        entry[name] = est
                    out["metrics"][series] = entry
        return out
