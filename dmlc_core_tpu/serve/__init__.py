"""Online scoring: the "millions of users" workload over the training stack.

The reference dmlc-core stops at training-side plumbing; this package is
the serving path the ROADMAP's north star demands, built entirely out of
subsystems earlier PRs shipped:

- **micro-batching** (:mod:`.scheduler`) — concurrent requests coalesce
  into one padded batch per predict call, batch shapes drawn from the
  ``bridge.batching`` bucket ladder so jitted predict functions compile
  O(log max_batch) shapes (warmed at load, :mod:`.model_runtime`);
- **admission control** (:mod:`.admission`) — PR 4's byte-bounded
  backpressure at the front door: queue-bytes reservations, structured
  503 + Retry-After sheds, never OOM;
- **transport** (:mod:`.server`, ``python -m dmlc_core_tpu.serve``) —
  stdlib threading HTTP with every stage in the PR 2 telemetry registry
  (request/queue/batch/predict spans, latency histograms with live
  p50/p95/p99 on ``/stats``) and PR 3 fault sites ``serve.request`` /
  ``serve.queue`` / ``serve.predict`` wired through the hot path;
- **proof** (:mod:`.loadgen`, ``benchmarks/bench_serving.py``) — an
  open-loop load harness that drives fault plans through the service and
  emits a JSON SLO report; the CI ``serve`` job fails unless every
  request under an active fault plan completes or sheds structurally;
- **model lifecycle** (:mod:`.registry`, :mod:`.lifecycle`) — named model
  slots with per-model admission budgets behind ``/v1/score/<model>``
  routing, and a checkpoint watcher that validates each new training
  checkpoint off-path (manifest-first, CRC-checked, bucket-ladder
  pre-warmed) and hot-swaps it behind the scheduler with zero dropped
  requests — the closed train→serve loop, scoring through the same uint8
  binned wire + ``HostBinner`` edges the model trained on.

- **multi-replica tier** (:mod:`.router`, :mod:`.fleet`,
  ``python -m dmlc_core_tpu.serve --replicas N``) — N replica processes
  on fixed ports behind a health-checked router: passive+active health
  state machine with half-open recovery, connect-level-only failover
  retries, p95-tracked request hedging, shared admission state from the
  enriched ``/healthz``, and zero-downtime rolling restarts via replica
  drain (docs/serving.md "Multi-replica tier").

See docs/serving.md for the architecture, the knee-curve methodology, and
every knob.
"""

from dmlc_core_tpu.serve.admission import AdmissionController  # noqa: F401
from dmlc_core_tpu.serve.errors import (BadRequest, ClientTimeout,  # noqa: F401
                                        Overloaded, PredictFailed,
                                        RequestTimeout, ServeError,
                                        UnknownModel, UpstreamFailed)
from dmlc_core_tpu.serve.fleet import ReplicaFleet  # noqa: F401
from dmlc_core_tpu.serve.lifecycle import (CheckpointWatcher,  # noqa: F401
                                           runtime_builder)
from dmlc_core_tpu.serve.model_runtime import (GBDTRuntime,  # noqa: F401
                                               LinearRuntime, MLPRuntime,
                                               ModelRuntime, build_runtime)
from dmlc_core_tpu.serve.registry import ModelRegistry, ModelSlot  # noqa: F401
from dmlc_core_tpu.serve.router import Replica, RouterServer  # noqa: F401
from dmlc_core_tpu.serve.scheduler import MicroBatcher, batch_buckets  # noqa: F401
from dmlc_core_tpu.serve.server import ScoringServer  # noqa: F401
# after .server: the event loop imports the shared request plumbing
# (parse_instances, healthz_payload, route_slot) from there
from dmlc_core_tpu.serve.eventloop import EventLoopServer  # noqa: F401
