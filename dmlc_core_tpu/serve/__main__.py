"""``python -m dmlc_core_tpu.serve`` — run the scoring service.

Examples::

    # a synthetic linear scorer on :8080 with the default knee knobs
    python -m dmlc_core_tpu.serve --model linear --num-feature 28 --port 8080

    # tighter latency knee, explicit byte bound, telemetry flushing
    DMLC_TELEMETRY_DIR=/tmp/t python -m dmlc_core_tpu.serve \
        --model mlp --num-feature 28 --max-batch 32 --max-delay-ms 1 \
        --max-queue-bytes 33554432

The process serves until SIGINT/SIGTERM; ``/healthz``, ``/metrics`` and
``/stats`` are live immediately after the warmup line prints.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.serve.model_runtime import build_runtime
from dmlc_core_tpu.serve.registry import ModelRegistry
from dmlc_core_tpu.serve.server import ScoringServer


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dmlc_core_tpu.serve",
        description="low-latency scoring service (micro-batching + "
                    "admission control; docs/serving.md)")
    p.add_argument("--model", default="linear",
                   choices=["linear", "mlp", "gbdt"],
                   help="model family (seeded synthetic params unless "
                        "--checkpoint)")
    p.add_argument("--num-feature", type=int, default=28)
    p.add_argument("--checkpoint", default=None,
                   help="bridge/checkpoint.py URI with trained params "
                        "(linear/mlp)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--max-batch", type=int, default=64,
                   help="rows per predict call (throughput knob)")
    p.add_argument("--max-delay-ms", type=float, default=2.0,
                   help="batch assembly wait (latency knob)")
    p.add_argument("--max-queue-bytes", type=int, default=None,
                   help="admission bound (default: DMLC_SERVE_QUEUE_BYTES "
                        "or 64 MiB)")
    p.add_argument("--request-timeout-s", type=float, default=10.0)
    p.add_argument("--no-warmup", action="store_true",
                   help="skip compile-ahead warmup (first requests of each "
                        "batch shape will pay XLA compilation)")
    p.add_argument("--model-name", default=None,
                   help="slot name for routing/metrics (default: the "
                        "model family)")
    p.add_argument("--watch-dir", default=None,
                   help="CheckpointManager directory URI to watch: new "
                        "steps are validated off-path and hot-swapped in "
                        "with zero downtime (docs/serving.md \"Model "
                        "lifecycle\")")
    p.add_argument("--watch-interval-s", type=float, default=None,
                   help="watcher poll interval (default: "
                        "DMLC_SERVE_WATCH_S or 2.0)")
    p.add_argument("--replicas", type=int, default=1,
                   help="run N replica processes behind a health-checked "
                        "router with failover + hedging (--port binds the "
                        "ROUTER; replicas take ephemeral ports — "
                        "docs/serving.md \"Multi-replica tier\")")
    p.add_argument("--no-hedge", action="store_true",
                   help="disable request hedging in the router "
                        "(--replicas > 1 only)")
    p.add_argument("--transport", default=None,
                   choices=["threaded", "evloop"],
                   help="HTTP transport: 'threaded' (thread per "
                        "connection) or 'evloop' (selectors event loop, "
                        "10k+ keep-alive connections — docs/serving.md "
                        "\"Transport\"; default: DMLC_SERVE_TRANSPORT "
                        "or threaded)")
    return p


def _run_replicated(args: argparse.Namespace) -> int:
    """--replicas N: a ReplicaFleet of scoring processes behind a
    RouterServer; SIGTERM rolls everything down cleanly (router first —
    stop routing, then drain the replicas)."""
    from dmlc_core_tpu.serve.fleet import ReplicaFleet
    from dmlc_core_tpu.serve.router import RouterServer

    telemetry.enable()
    name = args.model_name or args.model
    extra_args: List[str] = []
    if args.no_warmup:
        extra_args.append("--no-warmup")
    if args.transport:
        # env propagates to replica subprocesses automatically; the
        # explicit flag must reach them the same way
        extra_args += ["--transport", args.transport]
    if args.watch_dir:
        extra_args += ["--watch-dir", args.watch_dir]
        if args.watch_interval_s is not None:
            extra_args += ["--watch-interval-s",
                           str(args.watch_interval_s)]
    fleet = ReplicaFleet(
        args.replicas, model=args.model, num_feature=args.num_feature,
        seed=args.seed, host=args.host, max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        max_queue_bytes=args.max_queue_bytes,
        request_timeout_s=args.request_timeout_s,
        checkpoint=args.checkpoint, model_name=args.model_name,
        warmup=not args.no_warmup, extra_args=extra_args)
    stop = threading.Event()

    def _signal(signum, frame):  # noqa: ARG001 (signal contract)
        stop.set()

    signal.signal(signal.SIGINT, _signal)
    signal.signal(signal.SIGTERM, _signal)
    fleet.start()
    try:
        router = RouterServer(
            fleet.urls, host=args.host, port=args.port,
            hedge=False if args.no_hedge else None,
            # the router outlives one replica try-chain: per-try deadline
            # + retries must fit inside its own request deadline
            request_timeout_s=args.request_timeout_s + 5.0)
        router.start()
    except Exception:
        fleet.close()
        raise
    try:
        # same stable prefix as single-process mode: headless launchers
        # scrape "serving <name> on <url>" for the bound URL
        print(f"serving {name} on {router.url} "
              f"(replicas={args.replicas}, ctrl-c to stop)")
        stop.wait()
    finally:
        router.close()
        fleet.close()
    print("serve: shut down cleanly")
    return 0


def _raise_nofile_limit() -> None:
    """Best-effort soft→hard RLIMIT_NOFILE bump: a 10k-connection event
    loop cannot live inside the usual 1024 soft cap, and raising to the
    hard limit is always allowed."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if hard > soft:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except Exception:
        pass


def main(argv: Optional[List[str]] = None) -> int:
    args = make_parser().parse_args(argv)
    # honor an explicit JAX_PLATFORMS request even under plugin-pinning
    # images (the same discipline the examples follow)
    from dmlc_core_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()
    _raise_nofile_limit()
    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")
    if args.replicas > 1:
        return _run_replicated(args)
    # a server without metrics cannot state its SLOs: collection on
    # unconditionally (flushing still needs DMLC_TELEMETRY_DIR)
    telemetry.enable()
    runtime = build_runtime(args.model, args.num_feature, seed=args.seed,
                            checkpoint=args.checkpoint)
    name = args.model_name or runtime.name
    registry = ModelRegistry()
    registry.add(name, runtime, max_batch=args.max_batch,
                 max_delay_ms=args.max_delay_ms,
                 max_queue_bytes=args.max_queue_bytes, default=True)
    server = ScoringServer(
        registry, host=args.host, port=args.port,
        request_timeout_s=args.request_timeout_s,
        warmup=not args.no_warmup, transport=args.transport)
    watcher = None
    if args.watch_dir:
        from dmlc_core_tpu.serve.lifecycle import (CheckpointWatcher,
                                                   runtime_builder)

        watcher = CheckpointWatcher(
            registry, name, args.watch_dir,
            runtime_builder(args.model, args.num_feature, seed=args.seed),
            poll_s=args.watch_interval_s)
    stop = threading.Event()

    def _signal(signum, frame):  # noqa: ARG001 (signal contract)
        stop.set()

    signal.signal(signal.SIGINT, _signal)
    signal.signal(signal.SIGTERM, _signal)
    server.start()
    if watcher is not None:
        watcher.start()
    try:
        # keep "serving <name> on <url>" as the stable prefix: headless
        # launchers (tests/test_trace_e2e.py) scrape this line for the
        # bound URL
        print(f"serving {name} on {server.url} "
              f"(model={runtime.name}, ctrl-c to stop)")
        stop.wait()
    finally:
        if watcher is not None:
            watcher.close()
        # graceful drain (the rolling-restart contract): /healthz flips
        # to "draining", in-flight requests finish, THEN the listener
        # closes — a SIGTERM mid-storm must record zero client crashes
        server.drain()
    print("serve: shut down cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
