"""Structured serving errors: every failure a client can see has a shape.

The SLO contract (docs/serving.md) is that a request **completes, is shed,
or is rejected** — never dropped on the floor with a bare traceback.  Each
error here maps to one HTTP status and renders as one JSON envelope::

    {"error": {"code": "overloaded", "message": "...", "retry_after": 2}}

so load generators, retry layers, and humans all parse the same thing.
``Overloaded`` / ``PredictFailed`` are the two *shed* forms (503 + a
Retry-After the client is expected to honor — the same header discipline
:mod:`dmlc_core_tpu.io.net_retry` honors on the client side); ``BadRequest``
is the caller's bug (400, retrying is pointless); ``RequestTimeout`` (504)
means the request was admitted but its deadline elapsed in the queue or in
predict.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

__all__ = ["ServeError", "BadRequest", "ClientTimeout", "Overloaded",
           "PredictFailed", "RequestTimeout", "UnknownModel",
           "UpstreamFailed"]


class ServeError(Exception):
    """Base: carries the HTTP status, a stable machine code, and details."""

    status = 500
    code = "internal"

    def __init__(self, message: str, *,
                 retry_after: Optional[float] = None,
                 details: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.message = message
        self.retry_after = retry_after
        self.details = details or {}

    def payload(self) -> Dict[str, Any]:
        err: Dict[str, Any] = {"code": self.code, "message": self.message}
        if self.retry_after is not None:
            # integer seconds: the delta-seconds form every Retry-After
            # parser accepts (net_retry._retry_after included)
            err["retry_after"] = max(1, int(round(self.retry_after)))
        if self.details:
            err["details"] = self.details
        return {"error": err}

    def body(self) -> bytes:
        return json.dumps(self.payload(), sort_keys=True).encode("utf-8")

    def headers(self) -> Dict[str, str]:
        hdrs = {"Content-Type": "application/json"}
        if self.retry_after is not None:
            hdrs["Retry-After"] = str(max(1, int(round(self.retry_after))))
        return hdrs


class BadRequest(ServeError):
    """The request body cannot mean what its author intended (400)."""

    status = 400
    code = "bad_request"


class Overloaded(ServeError):
    """Admission control shed this request before queueing it (503)."""

    status = 503
    code = "overloaded"

    def __init__(self, message: str, *, retry_after: float = 1.0,
                 details: Optional[Dict[str, Any]] = None):
        super().__init__(message, retry_after=retry_after, details=details)


class PredictFailed(ServeError):
    """The batch this request rode in failed in predict; the request was
    not computed and the client should retry (503 — a shed, not a crash:
    the server is alive and the next batch is expected to succeed)."""

    status = 503
    code = "predict_failed"

    def __init__(self, message: str, *, retry_after: float = 1.0,
                 details: Optional[Dict[str, Any]] = None):
        super().__init__(message, retry_after=retry_after, details=details)


class RequestTimeout(ServeError):
    """Admitted but not answered within the request deadline (504)."""

    status = 504
    code = "timeout"


class ClientTimeout(ServeError):
    """The client failed to deliver its request bytes within the
    transport's assembly deadline (408) — the event-loop transport's
    slowloris / stalled-body defense (``DMLC_SERVE_HEADER_S``).  The
    connection is closed after this envelope is written: a half-delivered
    request leaves no framing to recover.  The threaded transport's
    equivalent is a silent per-socket timeout close; a structured 408 is
    strictly more diagnosable."""

    status = 408
    code = "client_timeout"


class UpstreamFailed(ServeError):
    """The router forwarded this request to a replica that failed after
    response bytes were read (or after the no-replay point) — the body is
    never replayed, so the client gets a structured 503 shed and retries
    itself (scoring is idempotent end-to-end, the router just refuses to
    guess whether a half-answered request was scored)."""

    status = 503
    code = "replica_failed"

    def __init__(self, message: str, *, retry_after: float = 1.0,
                 details: Optional[Dict[str, Any]] = None):
        super().__init__(message, retry_after=retry_after, details=details)


class UnknownModel(ServeError):
    """``/v1/score/<model>`` named a model no slot serves (404 — the
    details list what IS registered, so a mis-deployed client can see
    its routing bug without server logs)."""

    status = 404
    code = "unknown_model"
