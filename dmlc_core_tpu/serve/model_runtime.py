"""Model runtimes: the three model families behind one predict surface.

A :class:`ModelRuntime` owns everything the scoring path needs from a
model: the compiled predict function, the feature-dimension contract, and
**warmup** — compiling every batch-bucket shape at load time so the first
request of each shape pays queueing, not XLA compilation.  The scheduler
(:mod:`.scheduler`) only ever sees ``predict(x[B, F]) -> y[B] | y[B, K]``
with ``B`` drawn from the bucket ladder it warmed up.

Runtimes wrap the existing model families unchanged:

- :class:`LinearRuntime` — :class:`~dmlc_core_tpu.models.linear.LinearModel`
  params (margin / sigmoid);
- :class:`MLPRuntime` — :class:`~dmlc_core_tpu.models.mlp.MLP` params
  (softmax probabilities, or the regression head);
- :class:`GBDTRuntime` — a trained
  :class:`~dmlc_core_tpu.models.gbdt.TreeEnsemble` plus the binning
  boundaries (``bin_features`` then the ensemble's jitted predict).

:func:`build_runtime` constructs seeded synthetic instances for the CLI,
the load bench, and tests — real deployments construct runtimes from
checkpointed params (``bridge/checkpoint.py``) the same way.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.telemetry import tracecontext
from dmlc_core_tpu.utils.logging import CHECK, log_info

__all__ = ["ModelRuntime", "LinearRuntime", "MLPRuntime", "GBDTRuntime",
           "build_runtime"]


class ModelRuntime:
    """Base: a named predict function with a fixed feature contract."""

    #: model-family tag carried into metrics labels and /healthz
    name: str = "base"

    def __init__(self, num_feature: int):
        CHECK(num_feature >= 1, "num_feature must be >= 1")
        self.num_feature = int(num_feature)
        #: checkpoint step this runtime was built from; stamped by the
        #: model registry before the runtime can serve (None = unmanaged)
        self.version: Optional[int] = None

    def predict(self, x: np.ndarray) -> np.ndarray:
        """``[B, F] float32 -> [B]`` scores or ``[B, K]`` probabilities.

        ``B`` is a padded bucket size; padding rows produce garbage scores
        the scheduler slices off — runtimes must tolerate all-zero rows.
        Returns a **host** ndarray (the device sync happens here, inside
        the scheduler's predict span).  Subclasses override exactly one of
        ``predict`` (numpy stubs) or ``predict_async`` (jax runtimes).
        """
        if type(self).predict_async is ModelRuntime.predict_async:
            raise NotImplementedError(
                "runtimes must override predict or predict_async")
        return np.asarray(self.predict_async(x))

    def predict_async(self, x: np.ndarray):
        """Dispatch predict without waiting for the result.

        jax-backed runtimes override this to return the **un-synced**
        device array the jit call handed back — the transfer and compute
        are already queued on the device, and ``np.asarray`` on the handle
        is the sync point.  The scheduler's double-buffered loop dispatches
        batch N+1 (host binning + device transfer + compute, all queued
        behind N) before syncing batch N, so the wire transfer hides behind
        the previous predict.  The base implementation is the sync fallback
        for plain numpy runtimes that override ``predict``.
        """
        return self.predict(x)

    def warmup(self, batch_sizes: Sequence[int]) -> int:
        """Compile predict for each batch bucket; returns shapes warmed.

        Serving latency SLOs are unmeetable if request N of a new shape
        pays an XLA compile (hundreds of ms) — so every shape the
        scheduler can emit is compiled before the listener opens.
        """
        warmed = 0
        # all warmup compiles share one trace (a fresh root unless the
        # process is already inside one, e.g. a DMLC_TRACEPARENT-rooted
        # server launch): "model load" reads as a single story in the
        # assembled timeline rather than N disconnected spans
        ctx = (tracecontext.new_root()
               if telemetry.enabled() and tracecontext.current() is None
               else None)
        with tracecontext.activate(ctx), \
                telemetry.span("serve.warmup_all", model=self.name):
            for b in sorted(set(int(b) for b in batch_sizes)):
                with telemetry.span("serve.warmup", model=self.name,
                                    batch=b):
                    self.predict(np.zeros((b, self.num_feature),
                                          np.float32))
                telemetry.count("dmlc_serve_warmup_total", model=self.name)
                warmed += 1
        log_info(f"serve: warmed {warmed} batch shape(s) for {self.name} "
                 f"({sorted(set(int(b) for b in batch_sizes))})")
        return warmed


class LinearRuntime(ModelRuntime):
    """Serving facade over LinearModel params (w, b)."""

    name = "linear"

    def __init__(self, param, params: Dict[str, Any]):
        super().__init__(param.num_feature)
        self.param = param
        self.params = params
        self._jit = None

    def _fn(self):
        # memoized on the instance, NOT lru_cache(self): a class-level
        # cache keyed by self would pin every runtime (params + compiled
        # executables) for the process lifetime — the knee bench builds
        # one runtime per sweep point
        if self._jit is None:
            import jax
            import jax.numpy as jnp

            logistic = self.param.loss == "logistic"

            def predict(params, x):
                margin = x @ params["w"] + params["b"]
                return (1.0 / (1.0 + jnp.exp(-margin)) if logistic
                        else margin)

            self._jit = jax.jit(predict)
        return self._jit

    def predict_async(self, x: np.ndarray):
        return self._fn()(self.params, x)


class MLPRuntime(ModelRuntime):
    """Serving facade over MLP params (softmax probs / regression head)."""

    name = "mlp"

    def __init__(self, model, params: Dict[str, Any]):
        super().__init__(model.param.num_feature)
        self.model = model
        self.params = params
        self._jit = None

    def _fn(self):
        if self._jit is None:  # instance-memoized (see LinearRuntime._fn)
            import jax

            regression = self.model.param.num_class == 1

            def predict(params, x):
                logits = self.model._apply(params, x)
                return (logits[:, 0] if regression
                        else jax.nn.softmax(logits, -1))

            self._jit = jax.jit(predict)
        return self._jit

    def predict_async(self, x: np.ndarray):
        return self._fn()(self.params, x)


class GBDTRuntime(ModelRuntime):
    """Serving facade over a trained TreeEnsemble + binning boundaries.

    Scoring goes through the **binned device feed** (ROADMAP train→serve
    item): features are quantized on the host by a
    :class:`~dmlc_core_tpu.bridge.binning.HostBinner` built from the
    model's own ``boundaries`` — the numpy ``searchsorted(side="right")``
    twin of the training-time :func:`~dmlc_core_tpu.ops.histogram.
    apply_bins` — and the wire ships the narrow uint8/uint16 ids, widened
    back to int32 inside the jit.  Serving therefore applies *the exact
    binning the model trained on*: bin ids (and so every split decision)
    are bitwise-equal to the float path by construction, asserted against
    :meth:`predict_float` and against ``apply_bins`` in
    tests/test_serve.py + tests/test_device_feed.py.
    """

    name = "gbdt"

    def __init__(self, gbdt, ensemble):
        from dmlc_core_tpu.bridge.binning import HostBinner

        CHECK(gbdt.boundaries is not None,
              "GBDTRuntime needs fitted binning boundaries (make_bins)")
        super().__init__(gbdt.num_feature)
        self.gbdt = gbdt
        self.ensemble = ensemble
        # the slot's binner edges: the train/serve-skew-free contract
        self.binner = HostBinner(np.asarray(gbdt.boundaries),
                                 gbdt.param.num_bins,
                                 handle_missing=gbdt.param.handle_missing)

    def predict_async(self, x: np.ndarray):
        # host binning -> narrow wire -> async device dispatch: the uint8
        # transfer for this batch queues behind the previous batch's
        # compute (the scheduler syncs that one only after this dispatch)
        bins = self.binner.transform(x)
        return self.gbdt.predict(self.ensemble, bins)

    def predict_float(self, x: np.ndarray) -> np.ndarray:
        """The training-time float path (device-side ``apply_bins``), kept
        as the reference the skew-free contract tests compare against."""
        bins = self.gbdt.bin_features(x)
        return np.asarray(self.gbdt.predict(self.ensemble, bins))


def build_runtime(kind: str, num_feature: int, *, seed: int = 0,
                  num_class: int = 2, hidden: str = "32,32",
                  checkpoint: Optional[str] = None) -> ModelRuntime:
    """Construct a runtime for serving: seeded-synthetic params by default,
    checkpointed params (``bridge/checkpoint.py`` URI) when given.

    ``gbdt`` fits a small seeded ensemble on synthetic data at build time
    (there is no meaningful "random ensemble"); linear/mlp use
    ``init_params(seed)`` — mechanically identical to a trained model for
    load/latency purposes.
    """
    if kind == "linear":
        from dmlc_core_tpu.models.linear import LinearModel, LinearParam

        param = LinearParam(num_feature=num_feature)
        model = LinearModel(param)
        params = model.init_params(seed)
        if checkpoint:
            from dmlc_core_tpu.bridge.checkpoint import load_checkpoint

            params = load_checkpoint(checkpoint, template=params)
        return LinearRuntime(param, params)
    if kind == "mlp":
        from dmlc_core_tpu.models.mlp import MLP, MLPParam

        param = MLPParam(num_feature=num_feature, hidden=hidden,
                         num_class=num_class)
        model = MLP(param)
        params = model.init_params(seed)
        if checkpoint:
            from dmlc_core_tpu.bridge.checkpoint import load_checkpoint

            params = load_checkpoint(checkpoint, template=params)
        return MLPRuntime(model, params)
    if kind == "gbdt":
        from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam

        if checkpoint:
            from dmlc_core_tpu.bridge.checkpoint import load_checkpoint

            # serving_state blobs are self-describing (trees + binner
            # edges + serve_meta in one pytree): no template needed
            gbdt, ensemble = GBDT.from_serving_state(
                load_checkpoint(checkpoint))
            CHECK(gbdt.num_feature == num_feature,
                  f"checkpoint {checkpoint!r} serves {gbdt.num_feature} "
                  f"features but the slot contract is {num_feature}")
            return GBDTRuntime(gbdt, ensemble)
        rng = np.random.RandomState(seed)
        x = rng.normal(size=(256, num_feature)).astype(np.float32)
        label = (x[:, 0] + 0.5 * x[:, min(1, num_feature - 1)]
                 > 0).astype(np.float32)
        gbdt = GBDT(GBDTParam(objective="logistic", num_boost_round=8,
                              max_depth=3, num_bins=16), num_feature)
        gbdt.make_bins(x)
        ensemble, _ = gbdt.fit_binned(gbdt.bin_features(x), label)
        return GBDTRuntime(gbdt, ensemble)
    raise ValueError(f"unknown model kind {kind!r} "
                     "(one of: linear, mlp, gbdt)")
