"""Model registry: named slots, per-slot budgets, zero-downtime swaps.

The multi-model half of the model-lifecycle subsystem (docs/serving.md
"Model lifecycle"): a :class:`ModelRegistry` owns one :class:`ModelSlot`
per served model name, and each slot owns **everything that model's
traffic touches** —

- the live :class:`~.model_runtime.ModelRuntime` (behind the slot's
  :class:`~.scheduler.MicroBatcher`, which snapshots it once per batch);
- the slot **version** (the checkpoint step it was built from);
- its own :class:`~.admission.AdmissionController` with a per-model
  queue-bytes budget, so one model's burst sheds that model's traffic and
  never a co-hosted neighbour's;
- the bucket-ladder warmup contract (every slot's shapes compiled before
  its batcher starts).

:meth:`ModelRegistry.swap` is the zero-downtime flip the checkpoint
watcher (:mod:`.lifecycle`) drives: the new runtime is fully built,
validated, and pre-warmed *off-path* before the registry is ever asked,
and the swap itself is a single pointer flip under the batcher's own lock
(:meth:`~.scheduler.MicroBatcher.set_runtime`) — in-flight batches finish
on the old runtime, queued requests ride onto the new one, and nothing is
dropped, crashed, or scored by a half-swapped model.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.serve.admission import (AdmissionController,
                                           queue_bytes_from_env)
from dmlc_core_tpu.serve.errors import UnknownModel
from dmlc_core_tpu.serve.model_runtime import ModelRuntime
from dmlc_core_tpu.serve.scheduler import MicroBatcher
from dmlc_core_tpu.telemetry import clock
from dmlc_core_tpu.utils.logging import CHECK, log_info

__all__ = ["ModelRegistry", "ModelSlot"]


class ModelSlot:
    """One served model name: runtime + version + batcher + budget."""

    def __init__(self, name: str, runtime: ModelRuntime, *,
                 version: int = 0, max_batch: int = 64,
                 max_delay_ms: float = 2.0,
                 max_queue_bytes: Optional[int] = None):
        self.name = name
        self.num_feature = runtime.num_feature
        self.version = version
        runtime.version = version
        self.admission = AdmissionController(
            max_queue_bytes if max_queue_bytes is not None
            else queue_bytes_from_env(), name=name)
        self.batcher = MicroBatcher(runtime, max_batch=max_batch,
                                    max_delay_ms=max_delay_ms,
                                    admission=self.admission, name=name)
        self.warmed = False
        self.swapped_at: Optional[float] = None

    @property
    def runtime(self) -> ModelRuntime:
        """The live runtime (reads the batcher's pointer — always whole:
        the flip is atomic and dispatch snapshots per batch)."""
        return self.batcher.runtime

    @property
    def family(self) -> str:
        return self.runtime.name

    def describe(self) -> Dict[str, object]:
        """The /healthz (and /stats) identity block for this slot."""
        return {"family": self.family, "version": self.version,
                "num_feature": self.num_feature,
                "max_batch": self.batcher.max_batch,
                "max_queue_bytes": self.admission.max_queue_bytes}


class ModelRegistry:
    """Named model slots behind one routing surface.

    Add every slot before :meth:`start`; the lifecycle watcher then only
    ever *swaps* runtimes inside existing slots — slot topology is a
    deploy-time decision, model versions are a runtime one.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._slots: Dict[str, ModelSlot] = {}
        self._default: Optional[str] = None

    # -- topology -------------------------------------------------------------

    def add(self, name: str, runtime: ModelRuntime, *, version: int = 0,
            max_batch: int = 64, max_delay_ms: float = 2.0,
            max_queue_bytes: Optional[int] = None,
            default: bool = False) -> ModelSlot:
        CHECK(bool(name) and "/" not in name,
              f"model name {name!r} must be non-empty and slash-free "
              "(it rides in the /v1/score/<model> path)")
        slot = ModelSlot(name, runtime, version=version,
                         max_batch=max_batch, max_delay_ms=max_delay_ms,
                         max_queue_bytes=max_queue_bytes)
        with self._lock:
            CHECK(name not in self._slots,
                  f"model slot {name!r} already registered")
            self._slots[name] = slot
            if default or self._default is None:
                self._default = name
        telemetry.gauge_set("dmlc_serve_swap_version", float(version),
                            model=name)
        return slot

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    @property
    def default_name(self) -> Optional[str]:
        return self._default

    def get(self, name: Optional[str] = None) -> ModelSlot:
        """Resolve a route: ``None`` means the default slot.  Raises the
        structured 404 (:class:`~.errors.UnknownModel`) for the transport
        to map straight onto the wire."""
        with self._lock:
            key = name if name is not None else self._default
            slot = self._slots.get(key) if key is not None else None
        if slot is None:
            raise UnknownModel(
                f"no model {name!r} is registered"
                if name is not None else "no models registered",
                details={"models": self.names()})
        return slot

    # -- lifecycle ------------------------------------------------------------

    def start(self, warmup: bool = True) -> None:
        """Warm every slot's bucket ladder, then start its batcher —
        steady-state requests never pay XLA compilation (the same
        contract single-model serving always had)."""
        for slot in self._all():
            if warmup and not slot.warmed:
                slot.runtime.warmup(slot.batcher.buckets)
                slot.warmed = True
            slot.batcher.start()

    def swap(self, name: str, runtime: ModelRuntime, version: int) -> None:
        """The zero-downtime flip: install a fully-built, pre-warmed
        runtime into ``name``'s slot.  Raises ``ValueError`` (feature
        contract) or :class:`~.errors.UnknownModel` without touching the
        live slot — the caller (the watcher) turns both into
        "previous-good keeps serving"."""
        slot = self.get(name)
        old_version = slot.version
        # the flip and the stamps happen under the registry lock: the
        # watcher thread swaps while the main thread reads describe()/
        # get(), and a torn version/warmed/swapped_at trio would report
        # a half-swapped slot
        with self._lock:
            # stamp BEFORE the flip: no batch can snapshot the new
            # runtime without its version riding along
            runtime.version = version
            slot.batcher.set_runtime(runtime)  # the atomic pointer flip
            slot.version = version
            slot.warmed = True
            slot.swapped_at = clock.monotonic()
        telemetry.gauge_set("dmlc_serve_swap_version", float(version),
                            model=name)
        log_info(f"serve: model {name!r} swapped "
                 f"v{old_version} -> v{version} ({runtime.name})")

    def close(self) -> None:
        for slot in self._all():
            slot.batcher.close()

    def _all(self) -> List[ModelSlot]:
        # snapshot under the lock, operate outside it: batcher start/close
        # block (thread join) and must not run under the registry lock
        with self._lock:
            return list(self._slots.values())

    def describe(self) -> Dict[str, Dict[str, object]]:
        return {slot.name: slot.describe() for slot in self._all()}
