"""Micro-batching scheduler: coalesce concurrent requests, pad to buckets.

The latency/throughput knee of an online scorer is set by two knobs:

- ``max_batch`` — the most rows one predict call may carry (throughput
  ceiling: bigger batches amortize dispatch + matmul overhead);
- ``max_delay_ms`` — how long the first request of a batch may wait for
  company (latency floor under light load: an idle server answers a lone
  request after at most this delay).

One **batcher thread** owns the assembly loop: it blocks for the first
pending request, then gathers more until the batch is full or the delay
budget is spent, pads the assembled rows up to the next rung of the bucket
ladder (:func:`batch_buckets` — the ``bridge.batching.bucket_size`` ladder
from 1), and runs the model runtime's compiled predict exactly once for
the whole batch.  Bucketing keeps the set of compiled shapes logarithmic
in ``max_batch``; warmup compiles all of them at load, so steady-state
requests never pay XLA compilation.

Failure discipline (the chaos suite drives these paths):

- a predict failure fails **that batch's** requests with a structured 503
  (:class:`~.errors.PredictFailed`, Retry-After 1) and the loop continues
  — one poisoned batch cannot take the server down;
- the batcher thread itself is crash-ferried: an escape from the loop body
  is recorded, pending requests are failed structurally, and the next
  ``submit`` restarts the thread (self-healing, same discipline as the
  PR 4 process-pool);
- shutdown fails queued-but-unbatched requests with ``Overloaded
  (shutting_down)`` rather than leaving their futures hanging.

**Double-buffered dispatch** (the device-feed discipline of
``bridge/loader.py`` applied to serving): a batch's predict is *dispatched*
(``runtime.predict_async`` — host binning, device transfer, compute, all
queued asynchronously) and only *synced* after the next batch has been
assembled and dispatched, so the next batch's wire transfer hides behind
the in-flight predict.  When the queue is idle the in-flight batch resolves
immediately — pipelining engages exactly when there is load to pipeline,
and light-load latency is unchanged.

**Hot swap** (docs/serving.md "Model lifecycle"): :meth:`MicroBatcher.
set_runtime` is the atomic pointer flip the model registry swaps through —
taken under the batcher's own ``_thread_lock``, with the dispatch loop
snapshotting ``self.runtime`` exactly once per batch, so an in-flight batch
always finishes on the runtime it was dispatched against and no request is
ever scored by a half-swapped model.

Fault sites: ``serve.queue`` fires once per batch assembly (a ``stall``
models a stuck consumer — the queue backs up and admission starts
shedding); ``serve.predict`` fires before the model call (``error`` models
a killed predict worker).
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import numpy as np

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.bridge.batching import bucket_size
from dmlc_core_tpu.serve.admission import AdmissionController
from dmlc_core_tpu.serve.errors import BadRequest, Overloaded, PredictFailed
from dmlc_core_tpu.serve.model_runtime import ModelRuntime
from dmlc_core_tpu.telemetry import clock, tracecontext
from dmlc_core_tpu.utils.logging import log_error, log_warning

__all__ = ["MicroBatcher", "batch_buckets"]

# histogram bounds for batch row counts (powers of two up to the practical
# serving range; the registry adds +Inf)
_BATCH_ROW_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)


def batch_buckets(max_batch: int) -> List[int]:
    """Ascending bucket ladder ``[1, 2, 3, 4, 6, 8, ...]`` capped so the
    largest rung is exactly ``max_batch`` (every padded shape the scheduler
    can emit, i.e. every shape warmup must compile)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out: List[int] = []
    b = 1
    while b < max_batch:
        out.append(b)
        b = bucket_size(b + 1, minimum=b)
    out.append(max_batch)
    return out


class _Pending:
    """One admitted request riding the queue toward a batch."""

    __slots__ = ("rows", "future", "nbytes", "enqueued_at", "ctx")

    def __init__(self, rows: np.ndarray, future, nbytes: int, now: float,
                 ctx=None):
        self.rows = rows
        self.future = future
        self.nbytes = nbytes
        self.enqueued_at = now
        # the submitting request's trace context (handler thread), so the
        # batcher thread can credit queue wait + predict share back to the
        # request's own trace even though it runs them on behalf of many
        self.ctx = ctx


class _InFlight:
    """A dispatched-but-unsynced batch riding the double buffer."""

    __slots__ = ("batch", "handle", "runtime", "bucket", "rows",
                 "t_dispatch", "ctx")

    def __init__(self, batch, handle, runtime, bucket, rows, t_dispatch,
                 ctx):
        self.batch = batch          # List[_Pending]
        self.handle = handle        # un-synced predict result
        self.runtime = runtime      # the runtime snapshot it ran on
        self.bucket = bucket
        self.rows = rows
        self.t_dispatch = t_dispatch
        self.ctx = ctx              # the serve.batch span's trace context


class MicroBatcher:
    """Request coalescer + the single predict consumer thread."""

    def __init__(self, runtime: ModelRuntime, *, max_batch: int = 64,
                 max_delay_ms: float = 2.0,
                 admission: Optional[AdmissionController] = None,
                 name: Optional[str] = None):
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        self.runtime = runtime
        #: the model-slot name riding every metric's ``model=`` label
        #: (defaults to the runtime family for single-model servers, so
        #: legacy series keys are unchanged)
        self.name = name or runtime.name
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.admission = admission or AdmissionController(name=self.name)
        self.buckets = batch_buckets(self.max_batch)
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._carry: Optional[_Pending] = None  # overflow from last assembly
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # reentrant: _ensure_thread locks for itself AND is called from
        # submit()'s stop-check/enqueue critical section
        self._thread_lock = threading.RLock()
        self._crash: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._ensure_thread()

    def set_runtime(self, runtime: ModelRuntime) -> None:
        """Atomically swap the model behind the queue (the hot-swap flip).

        The new runtime must honor the slot's feature contract — requests
        already validated against ``num_feature`` may still be queued.
        Taken under ``_thread_lock`` (the same lock submit/close/crash
        recovery use); the dispatch loop snapshots ``self.runtime`` once
        per batch, so in-flight batches finish on the old runtime and
        every later batch runs entirely on the new one — there is no
        half-swapped state a request could observe.
        """
        if runtime.num_feature != self.runtime.num_feature:
            raise ValueError(
                f"cannot swap in a runtime with num_feature="
                f"{runtime.num_feature}; this batcher's contract is "
                f"{self.runtime.num_feature}")
        with self._thread_lock:
            self.runtime = runtime

    def _ensure_thread(self) -> None:
        with self._thread_lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._crash = None
            self._thread = threading.Thread(
                target=self._loop, name="serve-batcher", daemon=False)
            self._thread.start()

    def close(self, timeout: float = 10.0) -> None:
        """Stop the batcher and fail anything still queued (structured)."""
        self._stop.set()
        with self._thread_lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                log_warning("serve-batcher did not stop within "
                            f"{timeout}s; abandoning it")
        self._drain_failed(Overloaded("server shutting down",
                                      retry_after=5.0), reason="shutdown")

    def _drain_failed(self, exc: Exception, *, reason: str) -> None:
        pending = []
        # close() drains from the caller's thread while the batcher loop
        # may still be parked in _assemble: the carry swap must hold the
        # same (reentrant) lock _assemble uses
        with self._thread_lock:
            if self._carry is not None:
                pending.append(self._carry)
                self._carry = None
        while True:
            try:
                pending.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if pending:
            self.admission.release(sum(i.nbytes for i in pending))
            for item in pending:
                _fail_future(item.future, exc)
            telemetry.count("dmlc_serve_shed_total", len(pending),
                            model=self.name, reason=reason)

    # -- producer side -------------------------------------------------------

    def submit(self, rows: np.ndarray):
        """Admit + enqueue ``rows`` ([n, F] float32); returns the Future
        resolving to this request's ``[n]``/``[n, K]`` predictions.

        Raises the structured rejections directly: ``BadRequest`` on a
        contract violation, ``Overloaded`` from admission.
        """
        rows = np.ascontiguousarray(rows, dtype=np.float32)
        if rows.ndim != 2 or rows.shape[1] != self.runtime.num_feature:
            raise BadRequest(
                f"instances must be [n, {self.runtime.num_feature}] "
                f"(got shape {tuple(rows.shape)})")
        n = rows.shape[0]
        if n == 0:
            raise BadRequest("empty instances")
        if n > self.max_batch:
            raise BadRequest(
                f"{n} instances exceed max_batch={self.max_batch}; "
                "split the request",
                details={"max_batch": self.max_batch})
        if self._crash is not None:
            # the previous thread died outside the per-batch guard: surface
            # once, then self-heal below
            log_warning(f"serve-batcher restarting after crash: "
                        f"{self._crash!r}")
        self.admission.try_admit(rows.nbytes)
        from concurrent.futures import Future

        ctx = tracecontext.current() if telemetry.enabled() else None
        item = _Pending(rows, Future(), rows.nbytes, clock.monotonic(),
                        ctx=ctx)
        with self._thread_lock:
            if self._stop.is_set():
                self.admission.release(item.nbytes)
                telemetry.count("dmlc_serve_shed_total", model=self.name,
                                reason="shutdown")
                raise Overloaded("server shutting down", retry_after=5.0)
            self._ensure_thread()  # self-heal a dead batcher
            # enqueue under the lock: a put after close()'s drain would
            # strand this item (future unresolved, bytes leaked)
            self._queue.put(item)
        telemetry.gauge_set("dmlc_serve_queue_depth", self._queue.qsize(),
                            model=self.name)
        return item.future

    # -- consumer side -------------------------------------------------------

    def _loop(self) -> None:
        # the whole-target try/except is the lockset-thread-leak discipline:
        # nothing may escape a serving thread silently
        try:
            self._run()
        except BaseException as exc:  # noqa: BLE001 — ferried, not swallowed
            log_error(f"serve-batcher crashed: {exc!r}")
            telemetry.count("dmlc_serve_batcher_crashes_total")
            # deregister + drain under the lock: a racing submit() either
            # lands before the drain (failed structurally here) or after
            # it, when _ensure_thread sees no thread and starts a fresh
            # batcher to consume it — nothing can strand in between
            with self._thread_lock:
                self._crash = exc
                if self._thread is threading.current_thread():
                    self._thread = None
                self._drain_failed(PredictFailed(
                    f"scoring backend crashed: {exc}", retry_after=2.0),
                    reason="predict_failed")

    def _run(self) -> None:
        # the double buffer: at most ONE dispatched-but-unsynced batch.
        # Under load the loop dispatches batch N+1 (its transfer queues
        # behind N's compute) before syncing N; when the queue goes idle
        # the in-flight batch resolves immediately (wait=False returns
        # empty without blocking), so pipelining never delays a lone
        # request.
        inflight: Optional[_InFlight] = None
        try:
            while not self._stop.is_set():
                try:
                    batch = self._assemble(wait=inflight is None)
                except BaseException:
                    # the in-flight predict was already dispatched: sync
                    # and answer it before the crash ferries out
                    if inflight is not None:
                        self._resolve(inflight)
                        inflight = None
                    raise
                started = self._dispatch(batch) if batch else None
                if inflight is not None:
                    self._resolve(inflight)
                inflight = started
        finally:
            if inflight is not None:
                self._resolve(inflight)

    def _assemble(self, wait: bool = True) -> List[_Pending]:
        """Gather the next batch: seed from the carry or the queue, then
        keep gathering until full or the delay budget is spent.  An item
        that would overflow ``max_batch`` carries over as the seed of the
        next batch.  With ``wait=False`` (a batch is in flight) an empty
        queue returns immediately instead of blocking — the in-flight
        batch must resolve, not sit behind a poll timeout.

        Crash-safe: requests already popped when an assembly fault fires
        are failed structurally before the crash ferries out — a popped
        item whose future never resolves would hang its client until the
        request timeout for no reason.
        """
        batch: List[_Pending] = []
        try:
            with self._thread_lock:
                first, self._carry = self._carry, None
            if first is None:
                try:
                    if wait:
                        first = self._queue.get(timeout=0.05)
                    else:
                        first = self._queue.get_nowait()
                except queue.Empty:
                    return []
            batch.append(first)
            # a stalled consumer: the one fault that makes admission shed
            fault.inject("serve.queue", depth=self._queue.qsize())
            rows = first.rows.shape[0]
            deadline = clock.monotonic() + self.max_delay_s
            while rows < self.max_batch:
                remaining = deadline - clock.monotonic()
                if remaining <= 0:
                    break
                try:
                    item = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if rows + item.rows.shape[0] > self.max_batch:
                    with self._thread_lock:
                        self._carry = item
                    break
                batch.append(item)
                rows += item.rows.shape[0]
        except BaseException as exc:
            failure = PredictFailed(f"batch assembly failed: {exc}",
                                    retry_after=2.0)
            telemetry.count("dmlc_serve_shed_total", len(batch),
                            model=self.name, reason="predict_failed")
            if batch:
                self.admission.release(sum(i.nbytes for i in batch))
            for item in batch:
                _fail_future(item.future, failure)
            raise
        telemetry.gauge_set("dmlc_serve_queue_depth", self._queue.qsize(),
                            model=self.name)
        return batch

    def _dispatch(self, batch: List[_Pending]) -> Optional[_InFlight]:
        """Assemble + pad one batch and dispatch its predict without
        syncing.  Returns the in-flight handle, or ``None`` when the
        dispatch itself failed (that batch is already failed
        structurally; the loop continues)."""
        # ONE runtime snapshot per batch: a concurrent set_runtime (hot
        # swap) lands either entirely before or entirely after this batch
        runtime = self.runtime
        n = sum(item.rows.shape[0] for item in batch)
        bucket = self.buckets[-1] if n >= self.max_batch \
            else next(b for b in self.buckets if b >= n)
        now = clock.monotonic()
        for item in batch:
            telemetry.observe("dmlc_serve_queue_seconds",
                              now - item.enqueued_at, model=self.name)
        try:
            with telemetry.span("serve.batch", model=self.name, rows=n,
                                bucket=bucket,
                                requests=len(batch)) as batch_span:
                ctx = tracecontext.current() if telemetry.enabled() else None
                if telemetry.enabled():
                    # the batch belongs to no single request: it LINKS the
                    # trace of every request it coalesced, so the assembler
                    # (and a human in Perfetto) can hop batch -> requests
                    linked = [item.ctx.trace_id for item in batch
                              if item.ctx is not None]
                    if linked:
                        batch_span.set(links=",".join(linked[:32]),
                                       linked_traces=len(linked))
                x = np.zeros((bucket, runtime.num_feature), np.float32)
                ofs = 0
                for item in batch:
                    x[ofs:ofs + item.rows.shape[0]] = item.rows
                    ofs += item.rows.shape[0]
                fault.inject("serve.predict", model=runtime.name,
                             slot=self.name, rows=n)
                t0 = clock.monotonic()
                handle = runtime.predict_async(x)
        except Exception as exc:
            self._fail_batch(batch, n, exc)
            return None
        return _InFlight(batch, handle, runtime, bucket, n, t0, ctx)

    def _resolve(self, f: _InFlight) -> None:
        """Sync the in-flight predict and answer its requests — the
        device round-trip this batch's transfer already overlapped."""
        try:
            y = np.asarray(f.handle)
        except Exception as exc:
            self._fail_batch(f.batch, f.rows, exc)
            return
        t1 = clock.monotonic()
        telemetry.observe("dmlc_serve_predict_seconds", t1 - f.t_dispatch,
                          model=self.name)
        if telemetry.enabled():
            # the predict span (dispatch -> synced) parents under the
            # serve.batch span it was dispatched from, even though that
            # span closed when the double buffer moved on
            trace = ((f.ctx.trace_id, tracecontext.new_span_id(),
                      f.ctx.span_id) if f.ctx is not None else None)
            telemetry.record_span("serve.predict", f.t_dispatch, t1,
                                  trace=trace, model=self.name,
                                  bucket=f.bucket)
            # per-request attribution INTO each request's own trace: its
            # queue wait and its share of the shared predict call,
            # parented under the request's serve.request span — the two
            # stages the critical-path analysis splits a scored request
            # into
            for item in f.batch:
                ctx = item.ctx
                if ctx is None or not ctx.span_id:
                    continue
                telemetry.record_span(
                    "serve.queue.wait", item.enqueued_at, f.t_dispatch,
                    trace=(ctx.trace_id, tracecontext.new_span_id(),
                           ctx.span_id))
                telemetry.record_span(
                    "serve.predict", f.t_dispatch, t1,
                    trace=(ctx.trace_id, tracecontext.new_span_id(),
                           ctx.span_id),
                    bucket=f.bucket, rows=item.rows.shape[0],
                    shared_requests=len(f.batch))
        telemetry.count("dmlc_serve_batches_total", model=self.name)
        telemetry.count("dmlc_serve_rows_total", f.rows, model=self.name)
        telemetry.observe("dmlc_serve_batch_rows", f.rows,
                          buckets=_BATCH_ROW_BUCKETS, model=self.name)
        # one release per batch: the admission drain-rate estimate samples
        # real consumption, not the microsecond spacing of a per-item loop
        self.admission.release(sum(i.nbytes for i in f.batch))
        # which model build scored this batch: the runtime snapshot's
        # checkpoint version (stamped by the registry), annotated on the
        # future BEFORE the result lands so a reader of the result always
        # sees it — the transport reports it per response
        version = getattr(f.runtime, "version", None)
        ofs = 0
        for item in f.batch:
            k = item.rows.shape[0]
            item.future.dmlc_served_version = version
            _set_future(item.future, np.asarray(y[ofs:ofs + k]))
            ofs += k

    def _fail_batch(self, batch: List[_Pending], n: int,
                    exc: BaseException) -> None:
        """Shed one poisoned batch structurally; the loop continues."""
        telemetry.count("dmlc_serve_predict_errors_total", model=self.name)
        telemetry.count("dmlc_serve_shed_total", len(batch),
                        model=self.name, reason="predict_failed")
        log_error(f"serve: predict failed for a {n}-row batch: {exc!r}")
        failure = PredictFailed(f"predict failed: {exc}")
        self.admission.release(sum(i.nbytes for i in batch))
        for item in batch:
            _fail_future(item.future, failure)


def _set_future(future, value) -> None:
    try:
        future.set_result(value)
    except Exception:  # already cancelled/timed out: the answer has no taker
        pass


def _fail_future(future, exc: Exception) -> None:
    try:
        future.set_exception(exc)
    except Exception:
        pass
