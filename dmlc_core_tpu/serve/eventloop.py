"""Event-driven scoring transport: a selectors-based non-blocking front end.

The threaded transport (:mod:`.server`) burns one handler thread per
connection; at 10k keep-alive clients that is 10k stacks pinned on
sockets that are idle 99% of the time.  This module replaces the
*transport* only — a small number of event-loop threads (default 1,
``DMLC_SERVE_EVLOOP_THREADS``) multiplex every connection through
:mod:`selectors`, while scoring still flows through the exact same
MicroBatcher/admission/registry stack.  The batcher already decouples
transport from predict (``submit`` returns a future), so the event loop
never blocks on a model: it parses a request incrementally, submits it,
and writes the response when the future's completion callback pokes the
loop awake through a pipe.

Contract parity with the threaded transport is byte-for-byte: the same
structured error envelope (400/404/408/413/503/504), the same keep-alive
close discipline (any response sent before the request body was read
closes the connection — an unread body would be parsed as the next
request line), the same W3C ``traceparent`` continuation into the
``serve.request`` span, the same ``/healthz`` / ``/metrics`` / ``/stats``
bodies, the same in-flight odometer that graceful drain waits on.

What the event loop adds over the threaded transport:

- **slowloris + stalled-body defense** — a per-request assembly deadline
  (``DMLC_SERVE_HEADER_S``, first byte to full body) answers a
  byte-at-a-time client with a structured 408 and closes, instead of
  pinning a thread for the socket timeout;
- **connection observability** — ``dmlc_serve_connections{state=...}``
  gauges, open/close lifecycle counters, and ``serve.accept`` /
  ``serve.read`` / ``serve.write`` spans (read/write parented to the
  request's ``serve.request`` span when the request is traced);
- **c10k** — one loop thread holds >=10,000 keep-alive connections
  (see ``benchmarks/bench_serving.py c10k``); ``TCP_NODELAY`` is set on
  every accepted socket so small JSON responses never sit out a Nagle /
  delayed-ACK round trip (a flat +40ms tail on the threaded transport's
  default-config cousins).

Threading model (the races/deadlock passes lean on this shape):

- ``serve_forever`` spawns every loop thread from one
  ``Thread(target=self._run_loop)`` site and then just waits on a stop
  event;
- per-connection state (:class:`_Conn`) is constructed and mutated only
  on its owning loop thread — thread-confined, no locks;
- the shared connection table ``self._conns`` is the one cross-thread
  structure: every *write* (register on accept, pop on close, clear on
  ``server_close``) holds ``self._lock``; completion/inbox handoff
  deques take the same lock, and nothing under the lock calls into the
  batcher, admission, or telemetry.
"""

from __future__ import annotations

import functools
import json
import os
import selectors
import socket
import threading
import time
from collections import deque
from http.client import responses as _REASON
from itertools import count as _serial
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.serve import server as server_mod
from dmlc_core_tpu.serve.errors import (BadRequest, ClientTimeout,
                                        RequestTimeout, ServeError)
from dmlc_core_tpu.serve.server import (healthz_payload, parse_instances,
                                        route_slot)
from dmlc_core_tpu.telemetry import clock, tracecontext
from dmlc_core_tpu.utils.logging import log_debug, log_warning

__all__ = ["EventLoopServer"]

# request head (request line + headers) cap: same order as http.server's
# 64KiB line limit — a head that large is hostile, not a scoring client
_HEAD_CAP = 64 * 1024
_MAX_HEADERS = 128
# while a request is in flight we keep reading (to see EOF/RST early) but
# a client that pipelines megabytes ahead gets its READ interest dropped
# until the in-flight response drains — TCP backpressure, not RAM
_PIPELINE_CAP = 1 << 20
_RECV_CHUNK = 65536
# accepted sockets per accept-readiness wake: bounds time-per-loop-tick
# so a connect storm cannot starve in-flight connections
_ACCEPT_BURST = 512

_SERVER_LINE = b"Server: dmlc-serve/0.1\r\n"


def _fenv(raw: Optional[str], default: float) -> float:
    try:
        return float(raw) if raw not in (None, "") else default
    except ValueError:
        return default


_date_cache: Tuple[int, bytes] = (0, b"")


def _http_date(now: float) -> bytes:
    """``Date:`` header bytes, cached per wall-clock second (formatting a
    GMT date 10k times a second is measurable; reusing a 1s-stale string
    is not).  Benign if two loops race the cache: both write the same
    value for the same second."""
    global _date_cache
    sec = int(now)
    cached_sec, cached = _date_cache
    if sec != cached_sec:
        cached = time.strftime("Date: %a, %d %b %Y %H:%M:%S GMT\r\n",
                               time.gmtime(sec)).encode("latin-1")
        _date_cache = (sec, cached)
    return cached


def _head_bytes(status: int, length: int,
                headers: Optional[Dict[str, str]],
                content_type: str) -> bytes:
    # NB: no "Connection: close" is ever announced, even on paths that
    # close — the threaded transport (BaseHTTPRequestHandler) closes
    # silently too, and the keep-alive contract tests pin that exact
    # behavior (the client discovers the close on its next request)
    parts = [f"HTTP/1.1 {status} {_REASON.get(status, '')}\r\n"
             .encode("latin-1"),
             _SERVER_LINE, _http_date(time.time()),
             f"Content-Type: {content_type}\r\n".encode("latin-1"),
             f"Content-Length: {length}\r\n".encode("latin-1")]
    for k, v in (headers or {}).items():
        if k.lower() not in ("content-type", "content-length"):
            parts.append(f"{k}: {v}\r\n".encode("latin-1"))
    parts.append(b"\r\n")
    return b"".join(parts)


class _Conn:
    """One client connection: buffers + incremental parse state.

    Constructed and mutated only on its owning event-loop thread
    (thread-confined — no locks guard these attributes).  ``state``
    walks ``idle -> head -> body -> busy -> flush -> idle`` for a POST
    (GETs skip ``body``/``busy``), and any close path parks it at
    ``closed`` so stale selector events and late future callbacks
    become no-ops.
    """

    __slots__ = ("sock", "fd", "addr", "loop_idx", "rbuf", "wbuf",
                 "state", "opened_at", "last_active", "assembly_t0",
                 "close_after_write", "mask", "paused",
                 "method", "path", "headers", "http10",
                 "body_need", "num_rows", "odometer", "req_seq",
                 "t0", "span_t0", "model_label", "trace", "slot",
                 "deadline", "write_t0")

    def __init__(self, sock: socket.socket, addr: Any, now: float):
        self.sock = sock
        self.fd = sock.fileno()
        self.addr = addr
        self.loop_idx = 0
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.state = "idle"
        self.opened_at = now
        self.last_active = now
        self.assembly_t0 = now
        self.close_after_write = False
        self.mask = 0
        self.paused = False
        self.method = ""
        self.path = ""
        self.headers: Dict[str, str] = {}
        self.http10 = False
        self.body_need = 0
        self.num_rows = 0
        self.odometer = False
        self.req_seq = -1
        self.t0: Optional[float] = None
        self.span_t0: Optional[float] = None
        self.model_label = "_unrouted"
        self.trace: Optional[Tuple[str, str, Optional[str]]] = None
        self.slot = None
        self.deadline = 0.0
        self.write_t0: Optional[float] = None


class EventLoopServer:
    """Selectors-based non-blocking HTTP/1.1 server for ScoringServer.

    Exposes the slice of the ``socketserver`` surface ScoringServer
    drives — ``server_address``, ``serve_forever(poll_interval=...)``,
    ``shutdown()``, ``server_close()`` — so the rest of the serving
    stack (start/drain/close, the router, ReplicaFleet) cannot tell the
    transports apart.
    """

    def __init__(self, server_address: Tuple[str, int],
                 app: Optional["server_mod.ScoringServer"] = None, *,
                 threads: Optional[int] = None,
                 idle_timeout_s: Optional[float] = None,
                 header_timeout_s: Optional[float] = None,
                 backlog: int = 1024):
        self.app = app
        if threads is None:
            try:
                threads = int(os.environ.get("DMLC_SERVE_EVLOOP_THREADS",
                                             "1") or 1)
            except ValueError:
                threads = 1
        self.num_loops = max(1, int(threads))
        # keep-alive idle deadline between requests: mirrors the threaded
        # handler's 30s socket timeout (silent close — the client simply
        # went away)
        if idle_timeout_s is None:
            idle_timeout_s = _fenv(os.environ.get("DMLC_SERVE_IDLE_S"),
                                   30.0)
        self.idle_timeout_s = float(idle_timeout_s)
        # request-assembly deadline, first byte to full head+body: the
        # slowloris/stalled-body bound (structured 408, then close)
        if header_timeout_s is None:
            header_timeout_s = _fenv(os.environ.get("DMLC_SERVE_HEADER_S"),
                                     10.0)
        self.header_timeout_s = float(header_timeout_s)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._wake_r: List[int] = []
        self._wake_w: List[int] = []
        try:
            self._listen.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_REUSEADDR, 1)
            self._listen.bind(server_address)
            # deeper than the threaded transport's 128: the c10k ramp
            # connects in bursts and a kernel RST is the one shed form a
            # client cannot tell from a crash (capped by somaxconn)
            self._listen.listen(backlog)
            self._listen.setblocking(False)
            self.server_address = self._listen.getsockname()
            self._lock = threading.Lock()
            # the one cross-thread table: fd -> _Conn.  Reads are
            # lock-free snapshots; every write holds self._lock (accept-
            # register, close-pop, server_close-clear) — the races pass
            # pins exactly this.
            self._conns: Dict[int, _Conn] = {}
            # per-loop handoff queues, same lock: completed futures and
            # cross-loop accepted connections land here, the wake pipe
            # makes the owning loop drain them
            self._done: List[Deque[Tuple[int, int, Any]]] = \
                [deque() for _ in range(self.num_loops)]
            self._inbox: List[Deque[_Conn]] = \
                [deque() for _ in range(self.num_loops)]
            for _ in range(self.num_loops):
                r, w = os.pipe()
                os.set_blocking(r, False)
                os.set_blocking(w, False)
                self._wake_r.append(r)
                self._wake_w.append(w)
            self._stop = threading.Event()
            self._stopped = threading.Event()
            self._stopped.set()  # not serving yet: shutdown() can't hang
            self._threads: List[threading.Thread] = []
            self._seq = _serial(1)
            self._accept_rr = 0
            self._poll = 0.1
            self._closed = False
        except Exception:
            # a failed constructor orphans the instance: release the
            # listen socket + any wake pipes here or nothing else can
            self._listen.close()
            for fd in self._wake_r + self._wake_w:
                try:
                    os.close(fd)
                except OSError:
                    pass
            raise

    def fileno(self) -> int:
        return self._listen.fileno()

    # -- lifecycle (the socketserver surface ScoringServer drives) ------------

    def serve_forever(self, poll_interval: float = 0.1) -> None:
        self._poll = min(max(float(poll_interval), 0.005), 0.1)
        self._stopped.clear()
        try:
            for i in range(self.num_loops):
                t = threading.Thread(target=self._run_loop, args=(i,),
                                     name=f"serve-evloop-{i}", daemon=True)
                self._threads.append(t)
                t.start()
            log_debug(1, f"serve: evloop transport up "
                         f"({self.num_loops} loop thread(s), "
                         f"idle={self.idle_timeout_s:g}s, "
                         f"assembly={self.header_timeout_s:g}s)")
            self._stop.wait()
            for t in self._threads:
                t.join(5.0)
        finally:
            self._stopped.set()

    def shutdown(self) -> None:
        self._stop.set()
        for w in self._wake_w:
            self._wake_fd(w)
        self._stopped.wait(10.0)

    def server_close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._listen.close()
        except OSError:
            pass
        # normally the loops already closed their connections on the way
        # out; this is the fallback for a loop that died abnormally
        with self._lock:
            leftovers = list(self._conns.values())
            self._conns.clear()
        for conn in leftovers:
            try:
                conn.sock.close()
            except OSError:
                pass
        for fd in self._wake_r + self._wake_w:
            try:
                os.close(fd)
            except OSError:
                pass

    # -- cross-thread pokes ----------------------------------------------------

    @staticmethod
    def _wake_fd(w: int) -> None:
        try:
            os.write(w, b"\x01")
        except (BlockingIOError, OSError):
            pass  # pipe full == a wake is already pending; closed == exiting

    def _wake(self, idx: int) -> None:
        self._wake_fd(self._wake_w[idx])

    def _future_done(self, loop_idx: int, fd: int, seq: int,
                     future: Any) -> None:
        # runs on the batcher thread (or inline on the loop thread when
        # the future is already done): hand off, wake, never touch conn
        # state from here
        with self._lock:
            self._done[loop_idx].append((fd, seq, future))
        self._wake(loop_idx)

    # -- the loop --------------------------------------------------------------

    def _run_loop(self, idx: int) -> None:
        sel = selectors.DefaultSelector()
        try:
            sel.register(self._wake_r[idx], selectors.EVENT_READ, "wake")
            if idx == 0:
                sel.register(self._listen, selectors.EVENT_READ, "accept")
            last_sweep = clock.monotonic()
            while not self._stop.is_set():
                try:
                    events = sel.select(self._poll)
                except OSError:
                    break
                now = clock.monotonic()
                for key, mask in events:
                    data = key.data
                    if data == "wake":
                        self._drain_wake(idx)
                    elif data == "accept":
                        self._accept(sel, idx, now)
                    else:
                        conn = data
                        if mask & selectors.EVENT_WRITE \
                                and conn.state != "closed":
                            self._writable(sel, conn)
                        if mask & selectors.EVENT_READ \
                                and conn.state != "closed":
                            self._readable(sel, conn, now)
                self._drain_inbox(sel, idx)
                self._drain_done(sel, idx)
                now = clock.monotonic()
                if now - last_sweep >= 0.25:
                    last_sweep = now
                    self._sweep(sel, idx, now)
        except Exception as exc:  # noqa: BLE001 — a dead loop must say so
            log_warning(f"serve: evloop thread {idx} died: {exc!r}")
        finally:
            with self._lock:
                mine = [c for c in self._conns.values()
                        if c.loop_idx == idx]
                for c in mine:
                    self._conns.pop(c.fd, None)
            for c in mine:
                if c.odometer and self.app is not None:
                    self.app._request_end()
                    c.odometer = False
                try:
                    c.sock.close()
                except OSError:
                    pass
            if mine:
                telemetry.count("dmlc_serve_connections_closed_total",
                                len(mine), reason="server_shutdown")
            sel.close()

    def _drain_wake(self, idx: int) -> None:
        try:
            while os.read(self._wake_r[idx], 4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _drain_inbox(self, sel: selectors.BaseSelector, idx: int) -> None:
        items: List[_Conn] = []
        with self._lock:
            dq = self._inbox[idx]
            while dq:
                items.append(dq.popleft())
        for conn in items:
            try:
                sel.register(conn.sock, selectors.EVENT_READ, conn)
                conn.mask = selectors.EVENT_READ
            except (OSError, KeyError, ValueError):
                self._close(sel, conn, "error")

    def _drain_done(self, sel: selectors.BaseSelector, idx: int) -> None:
        items: List[Tuple[int, int, Any]] = []
        with self._lock:
            dq = self._done[idx]
            while dq:
                items.append(dq.popleft())
        for fd, seq, future in items:
            conn = self._conns.get(fd)
            # the seq guard is what makes fd reuse and request timeouts
            # safe: a late completion for a request already answered (or
            # a connection already gone) is dropped on the floor
            if conn is None or conn.loop_idx != idx \
                    or conn.req_seq != seq or conn.state != "busy":
                continue
            self._complete(sel, conn, future)
            if conn.state == "idle" and conn.rbuf:
                self._advance(sel, conn, clock.monotonic())

    # -- accept ----------------------------------------------------------------

    def _accept(self, sel: selectors.BaseSelector, idx: int,
                now: float) -> None:
        for _ in range(_ACCEPT_BURST):
            t0 = clock.monotonic()
            try:
                s, addr = self._listen.accept()
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                break
            try:
                s.setblocking(False)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
                continue
            conn = _Conn(s, addr, now)
            target = self._accept_rr % self.num_loops
            self._accept_rr += 1
            conn.loop_idx = target
            with self._lock:
                self._conns[conn.fd] = conn
                if target != idx:
                    self._inbox[target].append(conn)
            if target == idx:
                try:
                    sel.register(s, selectors.EVENT_READ, conn)
                    conn.mask = selectors.EVENT_READ
                except (OSError, KeyError, ValueError):
                    self._close(sel, conn, "error")
                    continue
            else:
                self._wake(target)
            telemetry.count("dmlc_serve_connections_opened_total")
            if telemetry.enabled():
                telemetry.record_span("serve.accept", t0, clock.monotonic())

    # -- read side -------------------------------------------------------------

    def _readable(self, sel: selectors.BaseSelector, conn: _Conn,
                  now: float) -> None:
        try:
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as exc:
            self._client_gone(sel, conn, type(exc).__name__)
            return
        if not data:
            self._client_gone(sel, conn, "ClientDisconnect")
            return
        conn.last_active = now
        if conn.state == "idle":
            conn.state = "head"
            conn.assembly_t0 = now
        conn.rbuf += data
        if conn.state in ("busy", "flush"):
            # response pending: hold the pipelined bytes, and drop READ
            # interest past the cap so the kernel pushes back instead of
            # this buffer growing unboundedly
            if len(conn.rbuf) > _PIPELINE_CAP:
                conn.paused = True
                self._set_events(sel, conn, read=False,
                                 write=bool(conn.wbuf))
            return
        self._advance(sel, conn, now)

    def _client_gone(self, sel: selectors.BaseSelector, conn: _Conn,
                     excname: str) -> None:
        """EOF or reset from the client.  Between requests that is just a
        close; mid-request there is no one left to answer — mirror the
        threaded transport's abort accounting (status-0 metrics + the
        aborts counter) and drop the connection."""
        if conn.state in ("idle", "flush") \
                or (conn.state == "head" and not conn.odometer):
            self._close(sel, conn, "client_close")
            return
        telemetry.count("dmlc_serve_connection_aborts_total")
        if conn.odometer:
            self._end_post(conn, 0, excname)
        self._close(sel, conn, "aborted")

    # -- the request state machine --------------------------------------------

    def _advance(self, sel: selectors.BaseSelector, conn: _Conn,
                 now: float) -> None:
        """Drive parse/dispatch until the connection needs more bytes, a
        response is in flight, or it closed.  Loops (never recurses) so a
        pipelined burst of N requests is N iterations, not N frames."""
        while True:
            if conn.state == "idle":
                if not conn.rbuf:
                    return
                conn.state = "head"
                conn.assembly_t0 = now
            if conn.state == "head":
                if not self._parse_head(sel, conn):
                    return
                self._dispatch(sel, conn, now)
            if conn.state == "body":
                if len(conn.rbuf) < conn.body_need:
                    return
                self._score_body(sel, conn, now)
            if conn.state != "idle":
                return

    def _parse_head(self, sel: selectors.BaseSelector,
                    conn: _Conn) -> bool:
        """Incremental head parse; True once ``method/path/headers`` are
        populated.  Malformed or oversized heads answer a structured 400
        and close (no metrics: nothing was routed — the threaded
        transport's stdlib parser is equally silent here)."""
        end = conn.rbuf.find(b"\r\n\r\n")
        if end < 0:
            if len(conn.rbuf) > _HEAD_CAP:
                self._head_error(sel, conn, BadRequest(
                    f"request head exceeds {_HEAD_CAP} bytes"))
            return False
        head = bytes(conn.rbuf[:end])
        del conn.rbuf[:end + 4]
        lines = head.split(b"\r\n")
        try:
            parts = lines[0].decode("latin-1").split()
        except UnicodeDecodeError:  # pragma: no cover — latin-1 total
            parts = []
        if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
            self._head_error(sel, conn,
                             BadRequest("malformed request line"))
            return False
        method, target, version = parts
        headers: Dict[str, str] = {}
        for raw in lines[1:]:
            if not raw:
                continue
            if len(headers) >= _MAX_HEADERS:
                self._head_error(sel, conn,
                                 BadRequest("too many request headers"))
                return False
            name, sep, value = raw.partition(b":")
            if not sep:
                self._head_error(sel, conn,
                                 BadRequest("malformed request header"))
                return False
            headers[name.strip().lower().decode("latin-1")] = \
                value.strip().decode("latin-1")
        conn.method = method
        conn.path = target
        conn.headers = headers
        conn.http10 = version == "HTTP/1.0"
        token = headers.get("connection", "").lower()
        conn.close_after_write = ("close" in token
                                  or (conn.http10
                                      and "keep-alive" not in token))
        return conn.state == "head"

    def _head_error(self, sel: selectors.BaseSelector, conn: _Conn,
                    exc: ServeError) -> None:
        excname = self._queue_response(conn, exc.status, exc.body(),
                                       exc.headers(), close=True)
        if excname is not None:
            self._close(sel, conn, "aborted")
            return
        self._after_respond(sel, conn)

    def _dispatch(self, sel: selectors.BaseSelector, conn: _Conn,
                  now: float) -> None:
        if conn.method == "GET":
            self._dispatch_get(sel, conn)
        elif conn.method == "POST":
            self._begin_post(sel, conn, now)
        else:
            self._head_error(sel, conn, BadRequest(
                f"unsupported method {conn.method!r}"))

    # -- GET -------------------------------------------------------------------

    def _dispatch_get(self, sel: selectors.BaseSelector,
                      conn: _Conn) -> None:
        app = self.app
        # a GET announcing a body would desync keep-alive framing (we do
        # not read bodies on GET): answer, then drop the link
        if conn.headers.get("content-length", "0") not in ("", "0"):
            conn.close_after_write = True
        try:
            if conn.path == "/healthz":
                body = json.dumps(healthz_payload(app),
                                  sort_keys=True).encode()
                excname = self._queue_response(conn, 200, body)
            elif conn.path == "/metrics":
                excname = self._queue_response(
                    conn, 200, telemetry.prometheus_text().encode(),
                    content_type="text/plain; version=0.0.4")
            elif conn.path == "/stats":
                body = json.dumps(app.stats(), sort_keys=True).encode()
                excname = self._queue_response(conn, 200, body)
            else:
                exc = BadRequest(f"no such path {conn.path!r}")
                excname = self._queue_response(conn, exc.status,
                                               exc.body(), exc.headers())
        except ServeError as exc:
            # e.g. /healthz on a registry with no slots: the probe must
            # read a structured error, not a dropped connection
            excname = self._queue_response(conn, exc.status, exc.body(),
                                           exc.headers())
        if excname is not None:
            self._close(sel, conn, "aborted")
            return
        self._after_respond(sel, conn)

    # -- POST ------------------------------------------------------------------

    def _begin_post(self, sel: selectors.BaseSelector, conn: _Conn,
                    now: float) -> None:
        app = self.app
        # the in-flight odometer brackets the whole request so drain only
        # exits once every admitted request has been answered
        app._request_begin()
        conn.odometer = True
        conn.t0 = clock.monotonic()
        conn.span_t0 = None
        conn.trace = None
        conn.model_label = "_unrouted"
        try:
            slot = route_slot(app, conn.path)
        except ServeError as exc:
            # body never read: an early response on a keep-alive
            # connection must close it (threaded parity, incl. the
            # metrics-without-span accounting)
            self._respond_error_post(sel, conn, exc, close=True)
            return
        conn.slot = slot
        conn.model_label = slot.name
        # trace continuation: an announced traceparent wins, else the
        # process-root context (env propagation), else untraced — the
        # same resolution the threaded handler's activate()+span() does
        incoming = tracecontext.from_traceparent(
            conn.headers.get("traceparent"))
        base = incoming if incoming is not None else tracecontext.current()
        conn.span_t0 = clock.monotonic()
        if telemetry.enabled() and base is not None:
            conn.trace = (base.trace_id, tracecontext.new_span_id(),
                          base.span_id)
        injected = fault.http_response("serve.request")
        if injected is not None:
            i_status, i_headers, i_body = injected
            if i_status == 503:
                telemetry.count("dmlc_serve_shed_total",
                                model=conn.model_label,
                                reason="injected_503")
            self._finish_post(sel, conn, i_status,
                              i_body or b'{"error": {"code": "injected"}}',
                              i_headers, errname=None, close=True)
            return
        try:
            # act kinds: delay/stall = a slow server; reset = the
            # connection dying mid-request.  NB: a sleeping act blocks
            # this loop thread — chaos drills only, never production.
            fault.inject("serve.request")
        except (BrokenPipeError, ConnectionResetError) as exc:
            telemetry.count("dmlc_serve_connection_aborts_total")
            self._end_post(conn, 0, type(exc).__name__)
            self._close(sel, conn, "aborted")
            return
        except ServeError as exc:
            self._respond_error_post(sel, conn, exc)
            return
        except Exception as exc:  # noqa: BLE001 — the 500 of last resort
            self._internal_error(sel, conn, exc)
            return
        try:
            length = int(conn.headers.get("content-length", ""))
        except ValueError:
            self._respond_error_post(sel, conn,
                                     BadRequest("Content-Length required"),
                                     close=True)
            return
        if length < 0:
            self._respond_error_post(
                sel, conn, BadRequest(f"invalid Content-Length {length}"),
                close=True)
            return
        max_body = server_mod.MAX_BODY_BYTES
        if length > max_body:
            exc = BadRequest(f"body of {length} bytes exceeds {max_body}")
            exc.status = 413
            exc.code = "payload_too_large"
            self._respond_error_post(sel, conn, exc, close=True)
            return
        conn.body_need = length
        conn.state = "body"

    def _score_body(self, sel: selectors.BaseSelector, conn: _Conn,
                    now: float) -> None:
        raw = bytes(conn.rbuf[:conn.body_need])
        del conn.rbuf[:conn.body_need]
        if telemetry.enabled():
            telemetry.record_span("serve.read", conn.assembly_t0,
                                  clock.monotonic(),
                                  trace=self._child_trace(conn),
                                  bytes=len(raw))
        try:
            obj = json.loads(raw)
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            self._respond_error_post(
                sel, conn, BadRequest(f"body is not valid JSON: {e}"))
            return
        try:
            rows = parse_instances(obj, conn.slot.num_feature)
        except ServeError as exc:
            self._respond_error_post(sel, conn, exc)
            return
        conn.num_rows = int(rows.shape[0])
        try:
            if conn.trace is not None:
                # activate the serve.request span's identity around
                # submit so the batcher's queue-wait/predict attribution
                # spans parent to it (threaded parity: submit runs inside
                # the span's dynamic extent)
                ident = tracecontext.TraceContext(conn.trace[0],
                                                  conn.trace[1])
                with tracecontext.activate(ident):
                    future = conn.slot.batcher.submit(rows)
            else:
                future = conn.slot.batcher.submit(rows)
        except ServeError as exc:
            self._respond_error_post(sel, conn, exc)
            return
        except Exception as exc:  # noqa: BLE001
            self._internal_error(sel, conn, exc)
            return
        conn.req_seq = next(self._seq)
        conn.deadline = now + self.app.request_timeout_s
        conn.state = "busy"
        future.add_done_callback(
            functools.partial(self._future_done, conn.loop_idx, conn.fd,
                              conn.req_seq))

    def _complete(self, sel: selectors.BaseSelector, conn: _Conn,
                  future: Any) -> None:
        """The batcher future landed: build the success payload (or map
        the failure) exactly as the threaded ``_score`` tail does."""
        conn.req_seq = -1
        try:
            preds = np.asarray(future.result())
            if not np.isfinite(preds).all():
                # finite inputs produced a non-finite score (model
                # overflow): a structured 500 beats a 200 body of
                # RFC-invalid Infinity
                raise ServeError("model produced a non-finite prediction")
            version = getattr(future, "dmlc_served_version", None)
            payload = {"predictions": preds.tolist(),
                       "model": conn.slot.name,
                       "version": version if version is not None
                       else conn.slot.version,
                       "num_rows": conn.num_rows}
            body = json.dumps(payload, sort_keys=True).encode()
            self._finish_post(sel, conn, 200, body, None, errname=None)
        except ServeError as exc:
            self._respond_error_post(sel, conn, exc)
        except Exception as exc:  # noqa: BLE001 — the 500 of last resort
            self._internal_error(sel, conn, exc)

    def _timeout_request(self, sel: selectors.BaseSelector,
                         conn: _Conn) -> None:
        """The request-deadline sweep's 504: admitted but not answered in
        time.  The future is left to finish into the void (the seq guard
        drops its completion), exactly like the threaded transport's
        ``future.result(timeout=...)`` abandoning the slot."""
        conn.req_seq = -1
        timeout_s = self.app.request_timeout_s
        telemetry.count("dmlc_serve_shed_total", model=conn.model_label,
                        reason="timeout")
        self._respond_error_post(sel, conn, RequestTimeout(
            f"not answered within {timeout_s}s (queue + predict)",
            details={"timeout_s": timeout_s}))

    # -- response plumbing -----------------------------------------------------

    def _respond_error_post(self, sel: selectors.BaseSelector, conn: _Conn,
                            exc: ServeError, close: bool = False) -> None:
        self._finish_post(sel, conn, exc.status, exc.body(), exc.headers(),
                          errname=type(exc).__name__, close=close)

    def _internal_error(self, sel: selectors.BaseSelector, conn: _Conn,
                        exc: Exception) -> None:
        log_warning(f"serve: unexpected error handling request: {exc!r}")
        wrapped = ServeError(f"internal error: {exc}")
        # the body may be partially read or unread: keeping the
        # keep-alive connection would desync its framing
        self._finish_post(sel, conn, wrapped.status, wrapped.body(),
                          wrapped.headers(), errname=type(exc).__name__,
                          close=True)

    def _finish_post(self, sel: selectors.BaseSelector, conn: _Conn,
                     status: int, body: bytes,
                     headers: Optional[Dict[str, str]],
                     errname: Optional[str], close: bool = False) -> None:
        excname = self._queue_response(conn, status, body, headers,
                                       close=close)
        if excname is not None:
            # client tore the socket down before the answer landed
            telemetry.count("dmlc_serve_connection_aborts_total")
            self._end_post(conn, 0, excname)
            self._close(sel, conn, "aborted")
            return
        self._end_post(conn, status, errname)
        self._after_respond(sel, conn)

    def _end_post(self, conn: _Conn, status: int,
                  errname: Optional[str]) -> None:
        """The threaded handler's ``finally`` block: serve.request span +
        request metrics, exactly once per POST."""
        if conn.t0 is None:
            return
        t1 = clock.monotonic()
        if conn.span_t0 is not None:
            attrs: Dict[str, Any] = {"model": conn.model_label}
            if errname:
                attrs["error"] = errname
            telemetry.record_span("serve.request", conn.span_t0, t1,
                                  trace=conn.trace, **attrs)
            conn.span_t0 = None
        telemetry.count("dmlc_serve_requests_total",
                        model=conn.model_label, status=status)
        telemetry.observe("dmlc_serve_request_seconds", t1 - conn.t0,
                          model=conn.model_label, status=status)
        conn.t0 = None

    def _queue_response(self, conn: _Conn, status: int, body: bytes,
                        headers: Optional[Dict[str, str]] = None,
                        content_type: str = "application/json",
                        close: bool = False) -> Optional[str]:
        """Queue head+body and flush opportunistically; returns the
        exception name if the socket is already dead, else None."""
        if close:
            conn.close_after_write = True
        conn.wbuf += _head_bytes(status, len(body), headers, content_type)
        conn.wbuf += body
        if conn.write_t0 is None:
            conn.write_t0 = clock.monotonic()
        return self._try_flush(conn)

    @staticmethod
    def _try_flush(conn: _Conn) -> Optional[str]:
        try:
            while conn.wbuf:
                n = conn.sock.send(conn.wbuf)
                del conn.wbuf[:n]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as exc:
            return type(exc).__name__
        return None

    def _after_respond(self, sel: selectors.BaseSelector,
                       conn: _Conn) -> None:
        if conn.wbuf:
            conn.state = "flush"
            self._set_events(sel, conn, read=not conn.paused, write=True)
            return
        self._cycle_done(sel, conn)

    def _writable(self, sel: selectors.BaseSelector, conn: _Conn) -> None:
        excname = self._try_flush(conn)
        if excname is not None:
            telemetry.count("dmlc_serve_connection_aborts_total")
            self._close(sel, conn, "aborted")
            return
        if conn.wbuf:
            return
        if conn.state == "flush":
            self._cycle_done(sel, conn)
            if conn.state == "idle" and conn.rbuf:
                self._advance(sel, conn, clock.monotonic())
        else:
            self._set_events(sel, conn, read=not conn.paused, write=False)

    def _cycle_done(self, sel: selectors.BaseSelector,
                    conn: _Conn) -> None:
        """Response fully flushed: emit serve.write, settle the odometer,
        then close or re-arm for the next (possibly pipelined) request."""
        now = clock.monotonic()
        if conn.write_t0 is not None:
            if telemetry.enabled():
                telemetry.record_span("serve.write", conn.write_t0, now,
                                      trace=self._child_trace(conn))
            conn.write_t0 = None
        if conn.odometer:
            self.app._request_end()
            conn.odometer = False
        if conn.close_after_write:
            self._close(sel, conn, "request_close")
            return
        conn.state = "idle"
        conn.trace = None
        conn.slot = None
        conn.req_seq = -1
        conn.last_active = now
        conn.paused = False
        self._set_events(sel, conn, read=True, write=False)

    def _child_trace(self, conn: _Conn) \
            -> Optional[Tuple[str, str, Optional[str]]]:
        if conn.trace is None:
            return None
        return (conn.trace[0], tracecontext.new_span_id(), conn.trace[1])

    # -- deadlines + gauges ----------------------------------------------------

    def _sweep(self, sel: selectors.BaseSelector, idx: int,
               now: float) -> None:
        snapshot = list(self._conns.values())
        if idx == 0 and telemetry.enabled():
            idle = sum(1 for c in snapshot if c.state == "idle")
            total = len(snapshot)
            telemetry.gauge_set("dmlc_serve_connections", total,
                                state="open")
            telemetry.gauge_set("dmlc_serve_connections", idle,
                                state="idle")
            telemetry.gauge_set("dmlc_serve_connections", total - idle,
                                state="active")
        for conn in snapshot:
            if conn.loop_idx != idx or conn.state == "closed":
                continue
            if conn.state == "idle":
                if now - conn.last_active >= self.idle_timeout_s:
                    # between requests: a silent close, same as the
                    # threaded handler's socket timeout
                    self._close(sel, conn, "idle_timeout")
            elif conn.state in ("head", "body"):
                if now - conn.assembly_t0 >= self.header_timeout_s:
                    self._slow_client(sel, conn)
            elif conn.state == "busy":
                if now >= conn.deadline:
                    self._timeout_request(sel, conn)
                    # the 504 keeps the connection alive: a pipelined
                    # request may already be buffered
                    if conn.state == "idle" and conn.rbuf:
                        self._advance(sel, conn, now)
            elif conn.state == "flush":
                if conn.write_t0 is not None \
                        and now - conn.write_t0 >= self.idle_timeout_s:
                    # client stopped reading its response
                    self._close(sel, conn, "write_stall")

    def _slow_client(self, sel: selectors.BaseSelector,
                     conn: _Conn) -> None:
        exc = ClientTimeout(
            f"request not received within {self.header_timeout_s:g}s",
            details={"timeout_s": self.header_timeout_s})
        if conn.odometer:
            # POST head already parsed (stalled mid-body): full abort
            # accounting, then the structured 408
            self._respond_error_post(sel, conn, exc, close=True)
        else:
            self._head_error(sel, conn, exc)

    # -- close -----------------------------------------------------------------

    def _close(self, sel: selectors.BaseSelector, conn: _Conn,
               reason: str) -> None:
        if conn.state == "closed":
            return
        conn.state = "closed"
        try:
            sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        with self._lock:
            self._conns.pop(conn.fd, None)
        if conn.odometer:
            self.app._request_end()
            conn.odometer = False
        try:
            conn.sock.close()
        except OSError:
            pass
        telemetry.count("dmlc_serve_connections_closed_total",
                        reason=reason)

    # -- selector interest -----------------------------------------------------

    @staticmethod
    def _set_events(sel: selectors.BaseSelector, conn: _Conn,
                    read: bool, write: bool) -> None:
        mask = (selectors.EVENT_READ if read else 0) \
            | (selectors.EVENT_WRITE if write else 0)
        if mask == conn.mask:
            return
        try:
            if mask:
                sel.modify(conn.sock, mask, conn)
            else:
                sel.unregister(conn.sock)
            conn.mask = mask
        except (KeyError, ValueError, OSError):
            pass
