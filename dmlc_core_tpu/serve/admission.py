"""Admission control: shed on queue-bytes so the server degrades, not OOMs.

The same byte-cost discipline PR 4 gave the ingest pipeline
(``ThreadedIter(max_bytes=, cost_fn=)``) applied at the serving front door:
every admitted request *reserves* its payload bytes, every completed (or
failed) batch *releases* them, and a request that would push the
reservation past ``max_queue_bytes`` is **shed** with a structured 503
(:class:`~dmlc_core_tpu.serve.errors.Overloaded`) carrying a ``Retry-After``
estimated from the observed drain rate — the header the client-side retry
layer (:mod:`dmlc_core_tpu.io.net_retry`) already honors, so a fleet of
well-behaved clients self-paces instead of retry-storming.

Why bytes, not request count: requests carry wildly different row counts;
counting them bounds nothing.  Bytes are what OOM the process.

Default bound: ``DMLC_SERVE_QUEUE_BYTES`` (64 MiB).  A request larger than
the whole bound is rejected 400 — no amount of retrying fits it.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.serve.errors import BadRequest, Overloaded
from dmlc_core_tpu.telemetry import clock

__all__ = ["AdmissionController", "DEFAULT_QUEUE_BYTES", "queue_bytes_from_env"]

DEFAULT_QUEUE_BYTES = 64 << 20

# Retry-After clamps: never tell a client "0" (it would hot-loop) and never
# park it past what a drain-rate estimate can honestly promise
RETRY_AFTER_FLOOR = 1.0
RETRY_AFTER_CAP = 30.0

_EWMA_ALPHA = 0.3  # drain-rate smoothing: responsive but not twitchy
# shed-fraction smoothing: slower than the drain rate on purpose — the
# router reads this from /healthz as "how hot has this slot been lately",
# and a single admitted request must not erase a shedding episode
_SHED_EWMA_ALPHA = 0.05
# minimum sampling window for a drain-rate observation: releases landing
# microseconds apart (batches completing back-to-back) would otherwise
# produce absurd instantaneous rates that swamp the EWMA
_RATE_WINDOW_S = 0.05


def queue_bytes_from_env() -> int:
    raw = os.environ.get("DMLC_SERVE_QUEUE_BYTES", "").strip()
    if not raw:
        return DEFAULT_QUEUE_BYTES
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"DMLC_SERVE_QUEUE_BYTES must be an integer byte count, "
            f"got {raw!r}") from None
    if v <= 0:
        raise ValueError(f"DMLC_SERVE_QUEUE_BYTES must be > 0, got {v}")
    return v


class AdmissionController:
    """Byte-reservation gate in front of the micro-batch queue.

    Every model slot owns its own controller (``name`` labels the metrics)
    with its own byte budget — the per-model admission discipline of
    docs/serving.md "Model lifecycle": one model's burst sheds *that
    model's* traffic, never a co-hosted neighbour's.
    """

    def __init__(self, max_queue_bytes: int = DEFAULT_QUEUE_BYTES,
                 name: str = "default"):
        if max_queue_bytes <= 0:
            raise ValueError(
                f"max_queue_bytes must be > 0, got {max_queue_bytes}")
        self.name = name
        self.max_queue_bytes = int(max_queue_bytes)
        self._lock = threading.Lock()
        self._queued = 0
        self._drain_rate: Optional[float] = None  # EWMA bytes/second
        self._window_start: Optional[float] = None
        self._window_bytes = 0  # drained since _window_start
        self._shed_ewma = 0.0   # EWMA of shed-vs-admit decisions in [0, 1]

    @property
    def queued_bytes(self) -> int:
        with self._lock:
            return self._queued

    def try_admit(self, nbytes: int) -> None:
        """Reserve ``nbytes`` or raise the structured rejection.

        Raises :class:`BadRequest` (400) when the request alone exceeds the
        whole bound, :class:`Overloaded` (503 + Retry-After) when the queue
        is full — the caller maps these straight onto the wire.
        """
        nbytes = int(nbytes)
        if nbytes > self.max_queue_bytes:
            telemetry.count("dmlc_serve_shed_total", model=self.name,
                            reason="oversized")
            raise BadRequest(
                f"request payload ({nbytes} bytes) exceeds the server's "
                f"whole queue bound ({self.max_queue_bytes}); split it",
                details={"payload_bytes": nbytes,
                         "max_queue_bytes": self.max_queue_bytes})
        with self._lock:
            if self._queued + nbytes > self.max_queue_bytes:
                retry = self._retry_after_locked(nbytes)
                queued = self._queued
                self._shed_ewma += _SHED_EWMA_ALPHA * (1.0 - self._shed_ewma)
            else:
                self._queued += nbytes
                self._shed_ewma -= _SHED_EWMA_ALPHA * self._shed_ewma
                telemetry.gauge_set("dmlc_serve_queue_bytes", self._queued,
                                    model=self.name)
                return
        telemetry.count("dmlc_serve_shed_total", model=self.name,
                        reason="queue_bytes")
        raise Overloaded(
            f"scoring queue full ({queued}/{self.max_queue_bytes} bytes "
            f"reserved); retry after {retry:.0f}s",
            retry_after=retry,
            details={"queued_bytes": queued,
                     "max_queue_bytes": self.max_queue_bytes})

    def release(self, nbytes: int) -> None:
        """Return ``nbytes`` to the budget (batch completed or failed) and
        feed the drain-rate estimate the Retry-After hints come from.

        Rate observations are taken over windows of at least
        ``_RATE_WINDOW_S``: bytes accumulate until the window closes, so
        back-to-back releases cannot fabricate gigabytes-per-second
        samples out of microsecond spacing.
        """
        nbytes = int(nbytes)
        now = clock.monotonic()
        with self._lock:
            self._queued = max(0, self._queued - nbytes)
            telemetry.gauge_set("dmlc_serve_queue_bytes", self._queued,
                                model=self.name)
            if self._window_start is None:
                self._window_start = now
                self._window_bytes = nbytes
                return
            self._window_bytes += nbytes
            dt = now - self._window_start
            if dt >= _RATE_WINDOW_S:
                rate = self._window_bytes / dt
                self._drain_rate = (
                    rate if self._drain_rate is None
                    else _EWMA_ALPHA * rate
                    + (1 - _EWMA_ALPHA) * self._drain_rate)
                self._window_start = now
                self._window_bytes = 0

    def describe(self) -> Dict[str, Any]:
        """The admission snapshot ``/healthz`` publishes per model slot —
        what the multi-replica router routes on (least-loaded by queue
        fraction) instead of bare liveness."""
        with self._lock:
            return {"queue_bytes": self._queued,
                    "max_queue_bytes": self.max_queue_bytes,
                    "drain_rate_bps": (round(self._drain_rate, 1)
                                       if self._drain_rate else None),
                    "shed_ewma": round(self._shed_ewma, 6)}

    def _retry_after_locked(self, nbytes: int) -> float:
        """Seconds until ``nbytes`` plausibly fits, from the drain EWMA.

        With no drain observed yet (cold start under burst) the floor is
        the honest answer: anything else is invented precision.
        """
        if not self._drain_rate or self._drain_rate <= 0:
            return RETRY_AFTER_FLOOR
        excess = self._queued + nbytes - self.max_queue_bytes
        est = excess / self._drain_rate
        return min(max(est, RETRY_AFTER_FLOOR), RETRY_AFTER_CAP)
