"""The multi-replica routing tier: health-checked failover + hedging.

One :class:`RouterServer` fronts N scoring replicas (each a
:class:`~.server.ScoringServer` process, normally launched by
:class:`~.fleet.ReplicaFleet`).  The router owns no model — it owns the
*robustness* contract docs/serving.md states for the tier: a client keeps
getting structured answers while replicas die, straggle, saturate, and
roll-restart underneath it.

Per-replica health state machine (each transition logged and exported as
``dmlc_router_replica_state{replica}``)::

    healthy --1 consecutive connect failure--> degraded
    degraded/healthy --3 consecutive failures--> ejected
    ejected --/healthz probe ok (half-open trial)--> healthy
    any --/healthz says "draining"--> draining (not routed; a fresh
        process answering "ok" on the same port recovers via half-open)

Failure counting is **passive** (every forward attempt that dies at the
connect level feeds the counter) plus **active**: a prober thread GETs
each replica's ``/healthz`` every ``DMLC_ROUTER_PROBE_S`` seconds, which
both accelerates ejection of a dead replica and is the only road back —
an ejected replica that answers probes enters *half-open*: it is offered
at most one in-flight trial request at a time (no thundering herd on a
cold restart), and either that trial or enough consecutive probe
successes promote it back to healthy.

Forwarding discipline:

- per-try deadline ``DMLC_ROUTER_TRY_TIMEOUT_S`` on every replica hop;
- bounded retries (``DMLC_ROUTER_RETRIES``) with full-jitter backoff, on
  **connect-level failures only**: refused/reset/timed-out before any
  response byte was read.  Scoring is idempotent, but the router still
  never replays a request after response bytes were read — a half-read
  answer becomes a structured 503 ``replica_failed`` and the *client*
  decides (it can retry; the router will not guess);
- each retry runs on a freshly picked replica (the failed one is
  excluded) — the retry budget buys failover, not hammering a corpse;
- replica 503s are relayed verbatim AND recorded router-side: the
  ``Retry-After`` marks that replica saturated, and :meth:`RouterServer.
  _pick` routes around it until the mark expires.  When **all** replicas
  are saturated the router sheds with its own structured 503
  (``reason=all_saturated``, Retry-After = the earliest expiry) — the
  tier degrades visibly, never with a refused connection;
- least-loaded routing: among routable replicas, pick by (state rank,
  in-flight count, queue fraction from the enriched ``/healthz``).

Request hedging ("tail at scale"): after a self-tuned delay tracking the
router's own p95 forward latency (an EWMA-style stochastic quantile
estimator — no sample buffer), a second attempt is launched on a
different replica.  First response wins and is the only one delivered
(the handler thread is the sole writer to the client socket, so a
duplicate can never be double-delivered); the loser is discarded and
counted in ``dmlc_router_hedges_total{outcome}``.

Chaos: the ``serve.router.forward`` fault site fires once per forward
attempt (``reset``/``delay``/``stall``/``error``/``http_status``), and
``bench_serving.py router`` drives the committed
``benchmarks/router_fault_plan.json`` through a live fleet.
"""

from __future__ import annotations

import http.client
import json
import os
import queue
import random
import socket
import threading
import time
from typing import Any, Dict, FrozenSet, List, Optional, Tuple
from urllib.parse import urlsplit

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.param import _parse_bool
from dmlc_core_tpu.serve.errors import (BadRequest, Overloaded,
                                        RequestTimeout, ServeError,
                                        UpstreamFailed)
from dmlc_core_tpu.serve.server import MAX_BODY_BYTES, _Handler, _Server
from dmlc_core_tpu.telemetry import clock, tracecontext
from dmlc_core_tpu.telemetry.report import (REPORT_QUANTILES, _label_str,
                                            estimate_quantiles)
from dmlc_core_tpu.utils.logging import log_debug, log_info, log_warning

__all__ = ["Replica", "RouterServer"]

# health state machine thresholds
DEGRADE_AFTER = 1    # consecutive connect failures -> deprioritized
EJECT_AFTER = 3      # consecutive connect failures -> not routed at all
HALF_OPEN_PROBES = 2  # consecutive probe successes to re-enter healthy

# hedging: clamp the self-tuned delay so a cold estimator can neither
# hedge every request (floor) nor never hedge (cap)
_HEDGE_MIN_S = 0.02
_HEDGE_MAX_S = 2.0
_HEDGE_INIT_S = 0.25  # until the first latency sample lands
_P95_Q = 0.95
_P95_ETA = 0.05       # estimator step, scaled by the current estimate

# full-jitter retry backoff (AWS-style: sleep U(0, min(cap, base*2^n)))
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 0.5

_STATE_CODES = {"healthy": 0, "degraded": 1, "ejected": 2, "draining": 3}


class _Retryable(Exception):
    """Internal: a forward attempt died before any response byte was read
    — the one class of failure the router is allowed to retry."""

    def __init__(self, reason: str, detail: str):
        super().__init__(detail)
        self.reason = reason


def _retry_after_s(value: Optional[str]) -> float:
    """Delta-seconds Retry-After -> float, clamped to [1, 30]."""
    try:
        secs = float(value) if value is not None else 1.0
    except ValueError:
        secs = 1.0
    return min(max(secs, 1.0), 30.0)


class Replica:
    """Router-side record of one backend: address + health odometers.

    Every mutable field is written only under ``self._lock`` — handler
    threads (passive failure counting), the prober thread, and hedge
    threads all feed the same state machine concurrently.
    """

    def __init__(self, url: str, name: str):
        parts = urlsplit(url if "//" in url else f"http://{url}")
        if not parts.hostname or not parts.port:
            raise ValueError(f"replica URL needs host:port, got {url!r}")
        self.url = f"http://{parts.hostname}:{parts.port}"
        self.host = parts.hostname
        self.port = int(parts.port)
        self.name = name
        self._lock = threading.Lock()
        self.state = "healthy"
        self.failures = 0          # consecutive connect-level failures
        self.half_open = False     # ejected/draining but answering probes
        self.probe_successes = 0   # consecutive, while half-open
        self.saturated_until = 0.0  # monotonic deadline from a 503
        self.in_flight = 0
        self.queue_bytes = 0       # sum over models, from /healthz
        self.queue_fraction = 0.0  # worst slot's queue_bytes/max
        self.version: Optional[int] = None

    def _set_state_locked(self, state: str) -> None:
        if state != self.state:
            log_info(f"router: replica {self.name} ({self.url}) "
                     f"{self.state} -> {state}")
            self.state = state
        telemetry.gauge_set("dmlc_router_replica_state",
                            _STATE_CODES[state], replica=self.name)

    def begin(self) -> None:
        with self._lock:
            self.in_flight += 1

    def end(self) -> None:
        with self._lock:
            self.in_flight -= 1

    def note_success(self) -> None:
        """A forward attempt got an HTTP response: the transport works."""
        with self._lock:
            self.failures = 0
            if self.state in ("degraded", "ejected"):
                # a half-open trial (or a deprioritized replica) answered
                # real traffic — that IS the recovery proof
                self._set_state_locked("healthy")
            self.half_open = False
            self.probe_successes = 0

    def note_failure(self) -> None:
        """A forward attempt (or probe) failed at the connect level."""
        with self._lock:
            self.failures += 1
            self.half_open = False
            self.probe_successes = 0
            if self.failures >= EJECT_AFTER:
                self._set_state_locked("ejected")
            elif self.failures >= DEGRADE_AFTER \
                    and self.state == "healthy":
                self._set_state_locked("degraded")

    def note_saturated(self, retry_after_s: float) -> None:
        """The replica shed with a 503: honor its Retry-After as shared
        admission state (route around it, don't eject — it's healthy,
        just full)."""
        with self._lock:
            self.saturated_until = clock.monotonic() + retry_after_s

    def note_probe(self, payload: Optional[Dict[str, Any]]) -> None:
        """Fold one /healthz probe result into the state machine.

        ``payload`` is the parsed JSON on success, None on any failure
        (refused, timeout, non-200, unparseable).
        """
        if payload is None:
            self.note_failure()
            return
        queue_bytes = 0
        fraction = 0.0
        admission = payload.get("admission")
        if isinstance(admission, dict):
            for info in admission.values():
                if not isinstance(info, dict):
                    continue
                qb = int(info.get("queue_bytes") or 0)
                queue_bytes += qb
                cap = info.get("max_queue_bytes")
                if cap:
                    fraction = max(fraction, qb / float(cap))
        with self._lock:
            self.queue_bytes = queue_bytes
            self.queue_fraction = fraction
            version = payload.get("version")
            if version is not None:
                self.version = version
            telemetry.gauge_set("dmlc_router_replica_queue_bytes",
                                queue_bytes, replica=self.name)
            if payload.get("status") == "draining":
                # the replica asked to be taken out of rotation BEFORE it
                # stops serving — the zero-downtime half of rolling restart
                self._set_state_locked("draining")
                self.half_open = False
                self.probe_successes = 0
                return
            self.failures = 0
            if self.state in ("ejected", "draining"):
                # half-open: routable for one trial at a time; promoted
                # after enough consecutive probe successes even without
                # traffic (an idle fleet must still converge to healthy)
                self.probe_successes += 1
                if self.probe_successes >= HALF_OPEN_PROBES:
                    self._set_state_locked("healthy")
                    self.half_open = False
                    self.probe_successes = 0
                else:
                    self.half_open = True
            elif self.state == "degraded":
                self._set_state_locked("healthy")

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"name": self.name, "url": self.url,
                    "state": self.state, "half_open": self.half_open,
                    "failures": self.failures,
                    "in_flight": self.in_flight,
                    "queue_bytes": self.queue_bytes,
                    "queue_fraction": round(self.queue_fraction, 4),
                    "saturated_until": self.saturated_until,
                    "version": self.version}


class _RouterHandler(_Handler):
    """Router transport: same plumbing as the replica handler (keep-alive
    desync discipline included), different routes."""

    server_version = "dmlc-router/0.1"

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        app = self.app
        try:
            if self.path == "/healthz":
                self._respond_json(200, app.health())
            elif self.path == "/metrics":
                self._respond(200, telemetry.prometheus_text().encode(),
                              content_type="text/plain; version=0.0.4")
            elif self.path == "/stats":
                self._respond_json(200, app.stats())
            else:
                self._respond_error(BadRequest(f"no such path "
                                               f"{self.path!r}"))
        except ServeError as exc:
            self._respond_error(exc)

    def do_POST(self) -> None:  # noqa: N802 (http.server contract)
        app = self.app
        t0 = clock.monotonic()
        status = 500
        try:
            if self.path != "/v1/score" \
                    and not self.path.startswith("/v1/score/"):
                # body unread: keep-alive would parse it as the next
                # request line
                self.close_connection = True
                raise BadRequest(f"no such path {self.path!r}")
            body = self._read_body()
            # continue the caller's W3C trace through the router hop: the
            # router.request span joins the client trace, router.forward
            # children join it, and the replica's serve.request continues
            # from the traceparent the forward attempt sends
            ctx = tracecontext.from_traceparent(
                self.headers.get("traceparent"))
            with tracecontext.activate(ctx), \
                    telemetry.span("router.request", path=self.path):
                status, headers, data = app.forward(self.path, body)
                self._respond(status, data, headers)
        except ServeError as exc:
            status = exc.status
            if status == 503:
                telemetry.count("dmlc_router_shed_total",
                                reason=exc.details.get("reason", exc.code))
            self._respond_error(exc)
        except (BrokenPipeError, ConnectionResetError):
            # the CLIENT side of the socket died — nobody left to answer
            status = 0
            telemetry.count("dmlc_router_connection_aborts_total")
            self.close_connection = True
        except Exception as exc:  # noqa: BLE001 — the 500 of last resort
            status = 500
            log_warning(f"router: unexpected error handling request: "
                        f"{exc!r}")
            self.close_connection = True
            try:
                self._respond_error(ServeError(f"internal error: {exc}"))
            except OSError:
                pass
        finally:
            telemetry.count("dmlc_router_requests_total", status=status)
            telemetry.observe("dmlc_router_request_seconds",
                              clock.monotonic() - t0, status=status)

    def _read_body(self) -> bytes:
        try:
            length = int(self.headers.get("Content-Length", ""))
        except ValueError:
            self.close_connection = True  # unread body would desync keep-alive
            raise BadRequest("Content-Length required") from None
        if length < 0:
            # rfile.read(-1) would block until client EOF — a hostile
            # header must not pin a handler thread
            self.close_connection = True
            raise BadRequest(f"invalid Content-Length {length}")
        if length > MAX_BODY_BYTES:
            self.close_connection = True
            exc = BadRequest(
                f"body of {length} bytes exceeds {MAX_BODY_BYTES}")
            exc.status = 413
            exc.code = "payload_too_large"
            raise exc
        return self.rfile.read(length)


class RouterServer:
    """HTTP front for N scoring replicas: health, failover, hedging.

    ``replica_urls`` are ``http://host:port`` (or bare ``host:port``)
    addresses of already-launched :class:`~.server.ScoringServer`
    processes — see :class:`~.fleet.ReplicaFleet` for the supervised
    form.  Knob arguments default from the environment:
    ``DMLC_ROUTER_RETRIES`` (2), ``DMLC_ROUTER_TRY_TIMEOUT_S`` (5),
    ``DMLC_ROUTER_PROBE_S`` (0.25), ``DMLC_ROUTER_HEDGE`` (1).
    """

    def __init__(self, replica_urls: List[str], *,
                 host: str = "127.0.0.1", port: int = 0,
                 hedge: Optional[bool] = None,
                 retries: Optional[int] = None,
                 try_timeout_s: Optional[float] = None,
                 probe_interval_s: Optional[float] = None,
                 request_timeout_s: float = 15.0):
        if not replica_urls:
            raise ValueError("RouterServer needs at least one replica URL")
        self.replicas = [Replica(url, f"r{i}")
                         for i, url in enumerate(replica_urls)]
        if len({r.url for r in self.replicas}) != len(self.replicas):
            raise ValueError(f"duplicate replica URLs in {replica_urls}")
        self.hedge = (_parse_bool(os.environ.get("DMLC_ROUTER_HEDGE", "1"))
                      if hedge is None else bool(hedge))
        self.retries = (int(os.environ.get("DMLC_ROUTER_RETRIES", "2"))
                        if retries is None else int(retries))
        self.try_timeout_s = (
            float(os.environ.get("DMLC_ROUTER_TRY_TIMEOUT_S", "5"))
            if try_timeout_s is None else float(try_timeout_s))
        self.probe_interval_s = (
            float(os.environ.get("DMLC_ROUTER_PROBE_S", "0.25"))
            if probe_interval_s is None else float(probe_interval_s))
        if self.retries < 0 or self.try_timeout_s <= 0 \
                or self.probe_interval_s <= 0:
            raise ValueError(
                "retries must be >= 0 and timeouts/intervals > 0 "
                f"(got retries={self.retries}, "
                f"try_timeout_s={self.try_timeout_s}, "
                f"probe_interval_s={self.probe_interval_s})")
        self.request_timeout_s = float(request_timeout_s)
        self._lock = threading.Lock()   # guards the hedge-delay estimator
        self._p95_s: Optional[float] = None
        self._stop = threading.Event()
        self._httpd = _Server((host, port), _RouterHandler)
        self._httpd.app = self  # type: ignore[attr-defined]
        self._serve_thread: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self.started_at = clock.monotonic()

    # -- lifecycle ------------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "RouterServer":
        # prime health + queue state synchronously so the first routed
        # request already knows who is alive and how loaded
        for rep in self.replicas:
            self._probe_one(rep)
        self.started_at = clock.monotonic()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="router-prober", daemon=True)
        self._probe_thread.start()
        self._serve_thread = threading.Thread(
            target=self._serve, name="router-http", daemon=False)
        self._serve_thread.start()
        log_info(f"router: listening on {self.url} fronting "
                 f"{len(self.replicas)} replica(s) "
                 f"(hedge={'on' if self.hedge else 'off'}, "
                 f"retries={self.retries}, "
                 f"try_timeout_s={self.try_timeout_s:g}, "
                 f"probe_s={self.probe_interval_s:g})")
        return self

    def _serve(self) -> None:
        try:
            self._httpd.serve_forever(poll_interval=0.1)
        except Exception as exc:  # noqa: BLE001 — ferried, not swallowed
            log_warning(f"router: listener exited abnormally: {exc!r}")

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._httpd.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(10.0)
            self._serve_thread = None
        if self._probe_thread is not None:
            self._probe_thread.join(5.0)
            self._probe_thread = None
        self._httpd.server_close()

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- the hedge-delay estimator --------------------------------------------

    def _observe_latency(self, lat_s: float) -> None:
        """Fold one delivered-response latency into the p95 estimate
        (stochastic quantile approximation: step up on the 5% of samples
        above the estimate, down on the 95% below — fixed memory, adapts
        when the fleet's latency regime shifts)."""
        with self._lock:
            if self._p95_s is None:
                self._p95_s = max(lat_s, _HEDGE_MIN_S)
            else:
                step = _P95_ETA * max(self._p95_s, _HEDGE_MIN_S)
                if lat_s > self._p95_s:
                    self._p95_s += step * _P95_Q
                else:
                    self._p95_s -= step * (1.0 - _P95_Q)
                self._p95_s = max(self._p95_s, 1e-4)
            est = self._p95_s
        telemetry.gauge_set("dmlc_router_hedge_delay_seconds",
                            min(max(est, _HEDGE_MIN_S), _HEDGE_MAX_S))

    def hedge_delay_s(self) -> float:
        with self._lock:
            est = self._p95_s
        if est is None:
            est = _HEDGE_INIT_S
        return min(max(est, _HEDGE_MIN_S), _HEDGE_MAX_S)

    # -- routing --------------------------------------------------------------

    def _pick(self, exclude: FrozenSet[str]) -> Replica:
        """Least-loaded routable replica, or a structured 503.

        Routable = not ejected/draining (half-open admits one trial at a
        time), not excluded, and not inside a 503 Retry-After window.
        """
        now = clock.monotonic()
        candidates: List[Tuple[int, int, float, float, int]] = []
        saturated_until: List[float] = []
        for idx, rep in enumerate(self.replicas):
            snap = rep.snapshot()
            if snap["name"] in exclude:
                continue
            trial = snap["half_open"]
            if snap["state"] in ("ejected", "draining") and not trial:
                continue
            if trial and snap["in_flight"] > 0:
                continue  # half-open: one trial at a time, no herd
            if snap["saturated_until"] > now:
                saturated_until.append(snap["saturated_until"])
                continue
            rank = 0 if snap["state"] == "healthy" and not trial else 1
            candidates.append((rank, snap["in_flight"],
                               snap["queue_fraction"], random.random(),
                               idx))
        if candidates:
            return self.replicas[min(candidates)[-1]]
        if saturated_until:
            retry_after = min(max(min(saturated_until) - now, 1.0), 30.0)
            raise Overloaded(
                "all replicas saturated; retry later",
                retry_after=retry_after,
                details={"reason": "all_saturated",
                         "replicas": len(self.replicas)})
        raise Overloaded(
            "no routable replicas (all ejected, draining, or excluded)",
            retry_after=1.0,
            details={"reason": "no_replicas",
                     "replicas": len(self.replicas)})

    # -- forwarding -----------------------------------------------------------

    def forward(self, path: str, body: bytes) \
            -> Tuple[int, Dict[str, str], bytes]:
        """Forward one fully-read request body; returns the winning
        replica response (status, relay headers, body).

        Runs the primary attempt chain in a worker thread and waits on a
        result queue; if no result lands within the hedge delay, launches
        one hedge attempt on a different replica.  The calling handler
        thread is the only writer to the client socket, so the losing
        response is structurally impossible to double-deliver — it is
        drained, counted, and dropped.
        """
        t0 = clock.monotonic()
        parent = tracecontext.current()
        results: "queue.Queue[Tuple[str, Any, Dict[str, Any]]]" = \
            queue.Queue()
        first = self._pick(frozenset())
        self._spawn_attempts(first, path, body, parent, "primary",
                             results, self.retries + 1)
        outstanding = 1
        hedged = False
        deadline = t0 + self.request_timeout_s
        last_err: Optional[ServeError] = None
        winner: Optional[Tuple[Tuple[int, Dict[str, str], bytes],
                               Dict[str, Any]]] = None
        while outstanding > 0 and winner is None:
            now = clock.monotonic()
            if now >= deadline:
                break
            if self.hedge and not hedged:
                wait = min(self.hedge_delay_s(), deadline - now)
            else:
                wait = deadline - now
            try:
                kind, payload, meta = results.get(timeout=max(wait, 1e-3))
            except queue.Empty:
                if self.hedge and not hedged:
                    hedged = True
                    try:
                        rep = self._pick(frozenset({first.name}))
                    except ServeError:
                        continue  # nowhere to hedge: keep waiting
                    telemetry.count("dmlc_router_hedges_total",
                                    outcome="fired")
                    log_debug(1, f"router: hedging to {rep.name} after "
                                 f"{clock.monotonic() - t0:.3f}s")
                    self._spawn_attempts(rep, path, body, parent, "hedge",
                                         results, 1)
                    outstanding += 1
                continue
            outstanding -= 1
            if kind == "response":
                winner = (payload, meta)
            else:
                last_err = payload
        if winner is not None:
            (status, headers, data), meta = winner
            if hedged:
                telemetry.count(
                    "dmlc_router_hedges_total",
                    outcome=("hedge_won" if meta.get("tag") == "hedge"
                             else "primary_won"))
                # the loser (still outstanding) will finish, ferry its
                # result into this request-local queue, and be GC'd with
                # it — never delivered
                for _ in range(outstanding):
                    telemetry.count("dmlc_router_hedges_total",
                                    outcome="discarded")
            self._observe_latency(clock.monotonic() - t0)
            headers = dict(headers)
            if meta.get("replica"):
                headers["X-Dmlc-Replica"] = meta["replica"]
            return status, headers, data
        if last_err is not None:
            raise last_err
        raise RequestTimeout(
            f"no replica answered within {self.request_timeout_s}s",
            details={"timeout_s": self.request_timeout_s,
                     "hedged": hedged})

    def _spawn_attempts(self, rep: Replica, path: str, body: bytes,
                        parent: Optional[tracecontext.TraceContext],
                        tag: str,
                        results: "queue.Queue[Tuple[str, Any, Dict[str, Any]]]",
                        tries: int) -> None:
        worker = threading.Thread(
            target=self._run_attempts,
            args=(rep, path, body, parent, tag, results, tries),
            name=f"router-{tag}", daemon=True)
        worker.start()

    def _run_attempts(self, rep: Replica, path: str, body: bytes,
                      parent: Optional[tracecontext.TraceContext],
                      tag: str,
                      results: "queue.Queue[Tuple[str, Any, Dict[str, Any]]]",
                      tries: int) -> None:
        """One attempt chain: try, retry on connect-level failure (fresh
        replica each time, full-jitter backoff), ferry the outcome into
        the waiter's queue.  Never raises — a dead worker thread would
        strand the handler until its deadline."""
        try:
            used = {rep.name}
            last_detail = ""
            for attempt in range(tries):
                if attempt:
                    telemetry.count("dmlc_router_retries_total", tag=tag)
                    time.sleep(random.uniform(0.0, min(
                        _BACKOFF_CAP_S,
                        _BACKOFF_BASE_S * (2 ** (attempt - 1)))))
                    try:
                        rep = self._pick(frozenset(used))
                    except ServeError as exc:
                        results.put(("error", exc, {"tag": tag}))
                        return
                    used.add(rep.name)
                try:
                    response = self._attempt(rep, path, body, parent, tag,
                                             attempt)
                except _Retryable as exc:
                    last_detail = str(exc)
                    log_debug(1, f"router: {tag} attempt {attempt} on "
                                 f"{rep.name} failed retryably: "
                                 f"{last_detail}")
                    continue
                except ServeError as exc:
                    results.put(("error", exc, {"tag": tag}))
                    return
                results.put(("response", response,
                             {"tag": tag, "replica": rep.name}))
                return
            results.put(("error", UpstreamFailed(
                f"no replica reachable after {tries} attempt(s): "
                f"{last_detail}",
                details={"attempts": tries, "tried": sorted(used)}),
                {"tag": tag}))
        except Exception as exc:  # noqa: BLE001 — ferried to the waiter
            results.put(("error",
                         ServeError(f"router internal error: {exc!r}"),
                         {"tag": tag}))

    def _attempt(self, rep: Replica, path: str, body: bytes,
                 parent: Optional[tracecontext.TraceContext], tag: str,
                 attempt: int) -> Tuple[int, Dict[str, str], bytes]:
        """One forward hop to one replica under the per-try deadline.

        Raises :class:`_Retryable` only when **zero response bytes** were
        read (refused, reset pre-response, connect timeout, replica died
        before the status line) — past that point a failure is terminal
        and structured, because the request may already have been scored.
        """
        rep.begin()
        conn: Optional[http.client.HTTPConnection] = None
        t0 = clock.monotonic()
        outcome = "ok"
        phase = "connect"
        try:
            with tracecontext.activate(parent), \
                    telemetry.span("router.forward", replica=rep.name,
                                   tag=tag, attempt=attempt):
                injected = fault.http_response(
                    "serve.router.forward", replica=rep.name, tag=tag,
                    attempt=attempt)
                if injected is not None:
                    outcome = "injected"
                    i_status, i_headers, i_body = injected
                    return i_status, dict(i_headers), \
                        i_body or b'{"error": {"code": "injected"}}'
                # act kinds fire before the connection opens: 'reset'
                # models a replica dying at connect time (retryable),
                # 'stall'/'delay' a slow link, 'error' a router bug
                fault.inject("serve.router.forward", replica=rep.name,
                             tag=tag, attempt=attempt)
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self.try_timeout_s)
                conn.connect()
                phase = "send"
                headers = {"Content-Type": "application/json"}
                traceparent = tracecontext.current_traceparent()
                if traceparent:
                    headers["traceparent"] = traceparent
                conn.request("POST", path, body=body, headers=headers)
                phase = "status"
                resp = conn.getresponse()
                phase = "read"  # status line read: the no-replay point
                data = resp.read()
                rep.note_success()
                relay: Dict[str, str] = {}
                for key in ("Content-Type", "Retry-After"):
                    value = resp.getheader(key)
                    if value is not None:
                        relay[key] = value
                if resp.status == 503:
                    # a saturated-but-healthy replica: honor its
                    # Retry-After router-side as shared admission state
                    rep.note_saturated(
                        _retry_after_s(relay.get("Retry-After")))
                return resp.status, relay, data
        except http.client.RemoteDisconnected as exc:
            # zero response bytes: the replica never answered this body
            outcome = "connect_failed"
            rep.note_failure()
            raise _Retryable("disconnected",
                             f"{rep.name}: {exc!r}") from None
        except socket.timeout:
            outcome = "timeout"
            rep.note_failure()
            if phase == "connect":
                raise _Retryable(
                    "connect_timeout",
                    f"{rep.name}: connect timed out") from None
            raise RequestTimeout(
                f"replica {rep.name} exceeded the {self.try_timeout_s:g}s "
                "per-try deadline",
                details={"replica": rep.name, "phase": phase}) from None
        except OSError as exc:
            rep.note_failure()
            if phase in ("connect", "send", "status"):
                # refused / reset before any response byte was read
                outcome = "connect_failed"
                raise _Retryable(phase, f"{rep.name}: {exc!r}") from None
            outcome = "failed"
            raise UpstreamFailed(
                f"replica {rep.name} failed after response bytes were "
                f"read: {exc!r}",
                details={"replica": rep.name, "phase": phase}) from None
        except http.client.HTTPException as exc:
            # partial/garbled status line: bytes WERE read, never replay
            outcome = "failed"
            rep.note_failure()
            raise UpstreamFailed(
                f"replica {rep.name} sent an unparseable response: "
                f"{exc!r}",
                details={"replica": rep.name}) from None
        except ServeError:
            raise
        except Exception as exc:  # noqa: BLE001 — injected 'error' et al.
            outcome = "error"
            rep.note_failure()
            raise UpstreamFailed(
                f"forwarding to {rep.name} failed: {exc!r}",
                details={"replica": rep.name}) from None
        finally:
            if conn is not None:
                conn.close()
            rep.end()
            telemetry.observe("dmlc_router_forward_seconds",
                              clock.monotonic() - t0, replica=rep.name)
            telemetry.count("dmlc_router_forward_total", replica=rep.name,
                            outcome=outcome)

    # -- active probing -------------------------------------------------------

    def _probe_loop(self) -> None:
        try:
            while not self._stop.is_set():
                for rep in self.replicas:
                    if self._stop.is_set():
                        break
                    self._probe_one(rep)
                self._stop.wait(self.probe_interval_s)
        except Exception as exc:  # noqa: BLE001 — ferried, not swallowed
            log_warning(f"router: prober exited abnormally: {exc!r}")

    def _probe_one(self, rep: Replica) -> None:
        conn: Optional[http.client.HTTPConnection] = None
        payload: Optional[Dict[str, Any]] = None
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port,
                timeout=min(1.0, self.try_timeout_s))
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status == 200:
                parsed = json.loads(raw)
                if isinstance(parsed, dict):
                    payload = parsed
        except (OSError, http.client.HTTPException, ValueError):
            payload = None
        finally:
            if conn is not None:
                conn.close()
        telemetry.count("dmlc_router_probes_total", replica=rep.name,
                        outcome="ok" if payload is not None else "fail")
        rep.note_probe(payload)

    # -- introspection --------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        snaps = {rep.name: rep.snapshot() for rep in self.replicas}
        routable = sum(1 for s in snaps.values()
                       if s["state"] in ("healthy", "degraded")
                       or s["half_open"])
        return {"status": "ok", "role": "router",
                "replicas": snaps, "routable": routable,
                "hedge": self.hedge,
                "hedge_delay_s": round(self.hedge_delay_s(), 4),
                "uptime_s": round(clock.monotonic() - self.started_at, 3)}

    def stats(self) -> Dict[str, Any]:
        """Router SLO snapshot: replica states + every dmlc_router_*
        series (same quantile math as the replica's /stats)."""
        out: Dict[str, Any] = dict(self.health())
        out["metrics"] = {}
        for fam in telemetry.get_registry().families():
            if not fam.name.startswith("dmlc_router_"):
                continue
            for key, child in fam.samples():
                series = fam.name + _label_str(dict(key))
                if fam.kind in ("counter", "gauge"):
                    out["metrics"][series] = child.value
                else:
                    counts = child.bucket_counts
                    ests = estimate_quantiles(
                        child.buckets, counts,
                        [q for _, q in REPORT_QUANTILES])
                    entry: Dict[str, Any] = {
                        "count": child.count,
                        "mean": (child.sum / child.count
                                 if child.count else None)}
                    for (qname, _), est in zip(REPORT_QUANTILES, ests):
                        entry[qname] = est
                    out["metrics"][series] = entry
        return out
