"""Open-loop load generator + the SLO accounting it reports.

**Open-loop matters**: a closed-loop client (send, wait, send) slows down
exactly when the server does, hiding the latency it came to measure
(coordinated omission).  Here arrivals are a seeded Poisson process at the
offered rate, dispatched on schedule regardless of how many requests are
still in flight, and each request's latency is measured **from its
scheduled arrival time** — a dispatch that couldn't start on time counts
against the server, not for it.

Outcome taxonomy (the SLO vocabulary of docs/serving.md):

=============  ==============================================================
``ok``         200 with a parseable predictions body of the right length
``shed``       structured 503 (admission, predict-failure, injected storm)
``timeout``    structured 504, or the client-side deadline elapsed
``rejected``   structured 4xx (the load was malformed — a client bug), or
               connection **refused**: nothing was listening, which in the
               multi-replica era means a restart window (the OS said "not
               here" before any bytes moved — cleanly retryable, nothing
               was lost mid-flight)
``error``      any other structured 5xx
``crashed``    no structured answer at all: connection reset mid-request,
               truncated body, unparseable response
``invalid``    200 whose body fails the caller's ``response_check`` —
               the answer arrived but is WRONG (the hot-swap drill uses
               this to catch a response whose predictions do not match
               the model version it claims served them)
=============  ==============================================================

The graceful-degradation proof is ``crashed == 0`` under an active fault
plan: every request got *an* answer, even if that answer was "not now".

Every request additionally carries a fresh W3C ``traceparent`` (the client
is each trace's root), the per-request sample records its ``trace_id``,
and the report lists the **top-5 slowest trace ids** — so the worst-p99
offenders in an SLO report can be looked up directly in the merged trace
(``python -m dmlc_core_tpu.telemetry trace <dir>``) instead of being
anonymous latency numbers.

The report also carries a **scoring-drift canary**: every ``ok``
response's mean prediction is bucketed by its *scheduled* arrival window
(``drift_window_s``, default 1 s), and the ``drift`` block reports the
per-window mean-prediction series.  Against a fixed model the series is
flat noise; under continuous training it visibly tracks the data
distribution the trainer is absorbing — the continuous chaos drill gates
on the series moving monotonically with its shifted label rate
(docs/serving.md "Scoring-drift canary").
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.telemetry import clock, tracecontext

__all__ = ["run_load", "run_churn", "percentile", "LoadReport"]

# how many worst-latency samples the report names by trace id
SLOWEST_TRACES = 5

OUTCOMES = ("ok", "shed", "timeout", "rejected", "error", "crashed",
            "invalid")

LoadReport = Dict[str, Any]


def percentile(sorted_values: List[float], q: float) -> Optional[float]:
    """Exact (linear-interpolated) percentile of a pre-sorted sample."""
    if not sorted_values:
        return None
    if len(sorted_values) == 1:
        return sorted_values[0]
    pos = q * (len(sorted_values) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = pos - lo
    return sorted_values[lo] * (1 - frac) + sorted_values[hi] * frac


def _gen_rows(rng: random.Random, n: int, num_feature: int) -> List[List[float]]:
    return [[rng.uniform(-1.0, 1.0) for _ in range(num_feature)]
            for _ in range(n)]


class _Recorder:
    """Thread-safe outcome/latency sink (one sample per request, with the
    request's trace_id so any latency can be found in the merged trace)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.counts = {k: 0 for k in OUTCOMES}
        self.statuses: Dict[str, int] = {}
        # connection accounting: every SLO report states how many sockets
        # were in flight at once and whether the transport ever slammed
        # the door (refused = nothing listening; reset = RST mid-request)
        self.inflight = 0
        self.peak_inflight = 0
        self.refused = 0
        self.resets = 0
        # (latency_s, trace_id, outcome, status) per request — the single
        # store every latency view (quantiles, slowest table) derives from
        self.samples: List[Tuple[float, str, str, Optional[int]]] = []
        # drift canary: window index -> [n_requests, sum of per-request
        # mean predictions] over ok responses only
        self.drift: Dict[int, List[float]] = {}
        # per-window outcome counts, keyed by SCHEDULED arrival window —
        # what availability-during-a-kill-window gates are computed from
        self.windows: Dict[int, Dict[str, int]] = {}

    def begin(self) -> None:
        with self.lock:
            self.inflight += 1
            if self.inflight > self.peak_inflight:
                self.peak_inflight = self.inflight

    def end(self, conn_event: Optional[str]) -> None:
        with self.lock:
            self.inflight -= 1
            if conn_event == "refused":
                self.refused += 1
            elif conn_event == "reset":
                self.resets += 1

    def record(self, outcome: str, latency_s: float,
               status: Optional[int], trace_id: str,
               window: Optional[int] = None) -> None:
        with self.lock:
            self.counts[outcome] += 1
            if status is not None:
                key = str(status)
                self.statuses[key] = self.statuses.get(key, 0) + 1
            self.samples.append((latency_s, trace_id, outcome, status))
            if window is not None:
                acc = self.windows.setdefault(window,
                                              {k: 0 for k in OUTCOMES})
                acc[outcome] += 1

    def window_series(self, window_s: float) -> List[Dict[str, Any]]:
        with self.lock:
            items = sorted((w, dict(c)) for w, c in self.windows.items())
        return [dict({"window": w, "t_s": round(w * window_s, 3)}, **c)
                for w, c in items]

    def record_drift(self, window: int, mean_prediction: float) -> None:
        with self.lock:
            acc = self.drift.setdefault(window, [0, 0.0])
            acc[0] += 1
            acc[1] += mean_prediction

    def drift_series(self, window_s: float) -> List[Dict[str, Any]]:
        with self.lock:
            items = sorted(self.drift.items())
        return [{"window": w, "t_s": round(w * window_s, 3), "n": n,
                 "mean_prediction": round(total / n, 6)}
                for w, (n, total) in items if n]

    def latencies(self, outcome: Optional[str] = None) -> List[float]:
        with self.lock:
            return [s[0] for s in self.samples
                    if outcome is None or s[2] == outcome]

    def slowest(self, n: int) -> List[Dict[str, Any]]:
        with self.lock:
            worst = sorted(self.samples, key=lambda s: -s[0])[:n]
        return [{"trace_id": t, "latency_ms": round(lat * 1e3, 3),
                 "outcome": outcome, "status": status}
                for lat, t, outcome, status in worst]


def _mean_prediction(preds: List[Any]) -> Optional[float]:
    """Mean over a predictions list (scalars, or per-class rows for
    softmax — flattened); None when nothing numeric is there."""
    flat: List[float] = []
    for p in preds:
        if isinstance(p, (int, float)):
            flat.append(float(p))
        elif isinstance(p, list):
            flat.extend(float(v) for v in p
                        if isinstance(v, (int, float)))
    return sum(flat) / len(flat) if flat else None


def _issue(url: str, path: str, body: bytes, timeout_s: float,
           expect_rows: int, traceparent: str, rows=None,
           response_check=None) -> tuple:
    """One POST; returns (outcome, status|None, mean_prediction|None,
    conn_event|None) where conn_event is ``"refused"`` (nothing was
    listening) or ``"reset"`` (the transport tore the connection)."""
    req = urllib.request.Request(
        url + path, data=body,
        headers={"Content-Type": "application/json",
                 "traceparent": traceparent}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            payload = json.load(resp)
            preds = payload.get("predictions")
            if isinstance(preds, list) and len(preds) == expect_rows:
                if response_check is not None \
                        and not response_check(payload, rows):
                    # a well-formed 200 that is WRONG (e.g. predictions
                    # inconsistent with the version it claims): worse
                    # than a shed, and the one outcome a half-swapped
                    # model could produce
                    return "invalid", resp.status, None, None
                return "ok", resp.status, _mean_prediction(preds), None
            # 200 with a wrong-shaped body
            return "crashed", resp.status, None, None
    except urllib.error.HTTPError as e:
        status = e.code
        try:
            err = json.load(e)
            structured = isinstance(err, dict) and "error" in err
        except Exception:
            structured = False
        if not structured:
            return "crashed", status, None, None
        if status == 503:
            return "shed", status, None, None
        if status == 504:
            return "timeout", status, None, None
        if 400 <= status < 500:
            return "rejected", status, None, None
        return "error", status, None, None
    except TimeoutError:
        return "timeout", None, None, None
    except urllib.error.URLError as e:
        # urllib wraps connect-phase deadline expiry in URLError: that is
        # the client's deadline, not a server crash
        reason = getattr(e, "reason", None)
        if isinstance(reason, TimeoutError):
            return "timeout", None, None, None
        if isinstance(reason, ConnectionRefusedError):
            # nothing listening on the port: a replica/router restart
            # window, not a dropped in-flight request
            return "rejected", None, None, "refused"
        if isinstance(reason, ConnectionResetError):
            return "crashed", None, None, "reset"
        return "crashed", None, None, None
    except ConnectionRefusedError:
        return "rejected", None, None, "refused"
    except ConnectionResetError:
        return "crashed", None, None, "reset"
    except (ConnectionError, OSError):
        return "crashed", None, None, None
    except Exception:
        return "crashed", None, None, None


def run_load(url: str, *, qps: float, duration_s: float, num_feature: int,
             rows_per_request: int = 1, seed: int = 0,
             timeout_s: float = 10.0, max_workers: int = 64,
             model: Optional[str] = None,
             response_check=None,
             drift_window_s: float = 1.0) -> LoadReport:
    """Drive open-loop traffic at ``qps`` for ``duration_s``; returns the
    SLO report dict (see module docstring for the outcome taxonomy).

    ``model`` routes every request to ``/v1/score/<model>`` (multi-model
    serving); ``response_check(payload, rows) -> bool`` (``rows`` = the
    instances this request sent) classifies a well-formed 200 whose body
    is semantically wrong as ``invalid``; ``drift_window_s`` sets the
    scoring-drift canary's bucketing (report ``drift`` block)."""
    from concurrent.futures import ThreadPoolExecutor

    path = "/v1/score" if model is None else f"/v1/score/{model}"

    rng = random.Random(seed)
    # Poisson arrival offsets within [0, duration)
    arrivals: List[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(qps)
        if t >= duration_s:
            break
        arrivals.append(t)
    rows_sent = [_gen_rows(rng, rows_per_request, num_feature)
                 for _ in arrivals]
    bodies = [json.dumps({"instances": rows}).encode()
              for rows in rows_sent]
    rec = _Recorder()
    start = clock.monotonic()

    def fire(scheduled_at: float, body: bytes, rows) -> None:
        # each request roots a fresh trace.  The header is attached even
        # when THIS process collects nothing (the W3C propagation norm:
        # the server side may be tracing — its spans then carry ids the
        # report names); with local telemetry on, the client span is
        # recorded under these exact ids so the server's serve.request
        # parents to a span that really exists in the assembled trace.
        trace_id = tracecontext.new_trace_id()
        span_id = tracecontext.new_span_id()
        tp = tracecontext.format_traceparent(
            tracecontext.TraceContext(trace_id, span_id))
        t0 = clock.monotonic()
        rec.begin()
        outcome, status, mean_pred, conn_event = _issue(
            url, path, body, timeout_s, rows_per_request, tp, rows,
            response_check)
        t1 = clock.monotonic()
        rec.end(conn_event)
        telemetry.record_span("client.request", t0, t1,
                              trace=(trace_id, span_id, None),
                              outcome=outcome, status=status or 0)
        rec.record(outcome, t1 - start - scheduled_at, status, trace_id,
                   window=int(scheduled_at // drift_window_s))
        if mean_pred is not None:
            # bucket by SCHEDULED time: the canary plots what the model
            # answered for traffic offered at t, not when it got around
            # to answering it
            rec.record_drift(int(scheduled_at // drift_window_s),
                             mean_pred)

    with ThreadPoolExecutor(max_workers=max_workers,
                            thread_name_prefix="loadgen") as pool:
        for at, body, rows in zip(arrivals, bodies, rows_sent):
            delay = at - (clock.monotonic() - start)
            if delay > 0:
                time.sleep(delay)
            pool.submit(fire, at, body, rows)
        # pool __exit__ joins all in-flight requests
    wall = clock.monotonic() - start

    lat_ok = sorted(rec.latencies("ok"))
    lat_all = sorted(rec.latencies())
    n = len(arrivals)
    report: LoadReport = {
        "offered_qps": qps,
        "duration_s": duration_s,
        "wall_s": round(wall, 3),
        "rows_per_request": rows_per_request,
        "requests": n,
        "counts": dict(rec.counts),
        "statuses": dict(sorted(rec.statuses.items())),
        "achieved_qps": round(rec.counts["ok"] / wall, 2) if wall else 0.0,
        "shed_rate": round(rec.counts["shed"] / n, 4) if n else 0.0,
        "error_rate": round((rec.counts["error"] + rec.counts["crashed"]
                             + rec.counts["invalid"]) / n, 4) if n else 0.0,
        "model": model,
        "latency_ms": {
            "p50": _ms(percentile(lat_ok, 0.50)),
            "p95": _ms(percentile(lat_ok, 0.95)),
            "p99": _ms(percentile(lat_ok, 0.99)),
            "max": _ms(lat_ok[-1] if lat_ok else None),
        },
        "latency_all_ms": {
            "p50": _ms(percentile(lat_all, 0.50)),
            "p99": _ms(percentile(lat_all, 0.99)),
        },
        # connection accounting in EVERY report: how many sockets were in
        # flight at the peak, and whether the transport ever slammed the
        # door.  refused = connect got ECONNREFUSED (restart window or an
        # exhausted backlog); resets = the socket was torn (RST) after
        # bytes moved.  The c10k gate reads refused == resets == 0.
        "connections": {
            "peak_inflight": rec.peak_inflight,
            "refused": rec.refused,
            "resets": rec.resets,
        },
        # the worst offenders BY NAME: feed these ids to
        # `telemetry trace <dir>` to see where each one's time went
        "slowest_traces": rec.slowest(SLOWEST_TRACES),
        # scoring-drift canary: per-window mean prediction of ok answers
        "drift": {
            "window_s": drift_window_s,
            "series": rec.drift_series(drift_window_s),
        },
        # outcome counts bucketed by scheduled arrival window: what a
        # "availability >= X% during the kill window" gate reads
        "outcome_windows": {
            "window_s": drift_window_s,
            "series": rec.window_series(drift_window_s),
        },
        # exactly-once accounting: one recorded outcome per issued
        # request.  A hedged router response that somehow got delivered
        # twice (double-counted) would make recorded > requests and flip
        # ok to false — the chaos drill gates on it.
        "accounting": {
            "requests": n,
            "recorded": sum(rec.counts.values()),
            "ok": sum(rec.counts.values()) == n,
        },
    }
    server_stats = _fetch_stats(url, timeout_s)
    if server_stats is not None:
        report["server"] = server_stats
    return report


def _ms(seconds: Optional[float]) -> Optional[float]:
    return None if seconds is None else round(seconds * 1e3, 3)


def _raise_nofile_limit(need: int) -> None:
    """Best-effort bump of RLIMIT_NOFILE toward ``need`` descriptors so a
    c10k client army doesn't die on the default soft limit; silently does
    nothing where resource limits are unavailable or capped below need."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = min(hard, max(soft, need))
        if want > soft:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
    except Exception:
        pass


def run_churn(url: str, *, connections: int, duration_s: float,
              num_feature: int, active: int = 32,
              churn_per_s: float = 0.0, seed: int = 0,
              timeout_s: float = 10.0) -> Dict[str, Any]:
    """High-concurrency connection-churn scenario: the c10k drill.

    Opens ``connections`` raw keep-alive sockets that sit **idle** (the
    realistic shape of 10k+ concurrent clients: most are between
    requests), while ``active`` keep-alive HTTP workers score requests
    continuously over their own persistent connections.  Optionally
    churns the idle army at ``churn_per_s`` (close one, open a fresh
    one) to exercise accept/close pressure under load.

    The verdict the report carries:

    - ``connections.refused`` — connects the OS bounced (full backlog or
      nothing listening).  Must be 0 for the c10k claim.
    - ``connections.resets`` — sockets torn mid-request (RST).  Must be 0.
    - ``connections.closed_by_server`` — idle army sockets the server
      dropped during the window (idle-timeout misfires show up here).
    - ``connections.peak_open`` — idle army + active workers actually
      connected at once: the concurrency actually demonstrated.

    The caller is responsible for a server whose idle timeout
    (``DMLC_SERVE_IDLE_S``) exceeds ``duration_s``.
    """
    import socket
    from urllib.parse import urlsplit

    parts = urlsplit(url)
    host, port = parts.hostname or "127.0.0.1", parts.port or 80
    _raise_nofile_limit(connections + active + 64)
    rng = random.Random(seed)

    idle: List[Any] = []
    refused = 0
    open_errors = 0
    opened_total = 0
    for _ in range(connections):
        try:
            s = socket.create_connection((host, port), timeout=timeout_s)
            idle.append(s)
            opened_total += 1
        except ConnectionRefusedError:
            refused += 1
        except OSError:
            open_errors += 1

    body = json.dumps(
        {"instances": _gen_rows(rng, 1, num_feature)}).encode()
    lock = threading.Lock()
    stats = {"ok": 0, "errors": 0, "resets": 0}
    lats: List[float] = []
    start = clock.monotonic()
    stop_at = start + duration_s

    def worker() -> None:
        import http.client

        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        try:
            while clock.monotonic() < stop_at:
                t0 = clock.monotonic()
                try:
                    conn.request("POST", "/v1/score", body,
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    resp.read()
                    status = resp.status
                except (ConnectionResetError, BrokenPipeError):
                    with lock:
                        stats["resets"] += 1
                    conn.close()
                    continue
                except (ConnectionError, OSError,
                        http.client.HTTPException):
                    with lock:
                        stats["errors"] += 1
                    conn.close()
                    continue
                with lock:
                    if status == 200:
                        stats["ok"] += 1
                        lats.append(clock.monotonic() - t0)
                    else:
                        stats["errors"] += 1
        finally:
            conn.close()

    workers = [threading.Thread(target=worker, daemon=True)
               for _ in range(active)]
    for t in workers:
        t.start()

    churned = 0
    peak_open = len(idle) + active
    while clock.monotonic() < stop_at:
        if churn_per_s > 0 and idle:
            # swap one idle soldier: close + reconnect (accept pressure
            # while the request path is busy)
            victim = idle.pop(rng.randrange(len(idle)))
            try:
                victim.close()
            except OSError:
                pass
            try:
                s = socket.create_connection((host, port),
                                             timeout=timeout_s)
                idle.append(s)
                opened_total += 1
                churned += 1
            except ConnectionRefusedError:
                refused += 1
            except OSError:
                open_errors += 1
            peak_open = max(peak_open, len(idle) + active)
            time.sleep(1.0 / churn_per_s)
        else:
            time.sleep(0.05)
    for t in workers:
        t.join(timeout_s + 5.0)

    # roll call: any idle soldier the server dropped (EOF/RST waiting in
    # its buffer) is a broken keep-alive promise
    closed_by_server = 0
    for s in idle:
        try:
            s.setblocking(False)
            if s.recv(1) == b"":
                closed_by_server += 1
        except (BlockingIOError, InterruptedError):
            pass  # still open and silent: the healthy case
        except OSError:
            closed_by_server += 1
        finally:
            try:
                s.close()
            except OSError:
                pass

    wall = clock.monotonic() - start
    lat = sorted(lats)
    report: Dict[str, Any] = {
        "target_connections": connections,
        "active_workers": active,
        "duration_s": duration_s,
        "wall_s": round(wall, 3),
        "connections": {
            "peak_open": peak_open,
            "opened_total": opened_total + active,
            "churned": churned,
            "refused": refused,
            "resets": stats["resets"],
            "open_errors": open_errors,
            "closed_by_server": closed_by_server,
        },
        "requests": {"ok": stats["ok"], "errors": stats["errors"]},
        "achieved_qps": round(stats["ok"] / wall, 2) if wall else 0.0,
        "latency_ms": {
            "p50": _ms(percentile(lat, 0.50)),
            "p95": _ms(percentile(lat, 0.95)),
            "p99": _ms(percentile(lat, 0.99)),
            "max": _ms(lat[-1] if lat else None),
        },
    }
    server_stats = _fetch_stats(url, timeout_s)
    if server_stats is not None:
        report["server"] = server_stats
    return report


def _fetch_stats(url: str, timeout_s: float) -> Optional[Dict[str, Any]]:
    try:
        with urllib.request.urlopen(url + "/stats",
                                    timeout=timeout_s) as resp:
            return json.load(resp)
    except Exception:
        return None
