"""Checkpoint watcher: the train→serve loop, closed with zero downtime.

A continuously trained model lands as numbered checkpoints
(:class:`~dmlc_core_tpu.bridge.checkpoint.CheckpointManager` layout:
``ckpt-XXXXXXXX`` + its ``.manifest.json``) on any URI-dispatched store —
local disk, S3, the mock fleet store.  :class:`CheckpointWatcher` polls
that directory and walks every new step through a four-stage state
machine, each stage a ``model.*`` span and a ``serve.swap`` fault-site
hit, before the live slot is ever touched:

``watch``
    list steps, pick the newest one above the slot's current version, and
    read its **manifest first** — a step without a manifest is still
    being written (the manager publishes the manifest only after the blob
    is durable), so a partially written checkpoint on a non-atomic remote
    store is *never even opened*.
``validate``
    re-hash the blob against the manifest (magic / byte count / CRC-32 —
    :func:`~dmlc_core_tpu.bridge.checkpoint.verify_checkpoint`, zero jax
    work), then build the candidate runtime **off-path** via the slot's
    builder and check the structural contract (feature width).
``warmup``
    pre-compile the *entire* jit bucket ladder on the shadow runtime —
    after the swap, no request shape ever pays XLA compilation.
``swap``
    :meth:`~.registry.ModelRegistry.swap` — the atomic pointer flip under
    the batcher's lock.  In-flight batches finish on the old runtime;
    everything after runs whole on the new one.

A failure at any stage (corrupt bytes, a builder error, an injected
fault) leaves **previous-good serving**: the candidate is counted
(``dmlc_serve_swap_total{outcome="failed"}`` +
``dmlc_serve_swap_failures_total{stage=...}``), remembered so a bad step
is not re-validated every poll, and retried only when the store shows a
newer step (or the same step's bytes change).  The chaos drill in
tests/test_lifecycle.py hot-swaps repeatedly during a 503 storm under a
committed fault plan and asserts zero crashed requests and zero requests
answered by a half-swapped model.

Knobs: ``DMLC_SERVE_WATCH_S`` (poll interval, default 2.0 s).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Optional

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.bridge.checkpoint import (CheckpointManager,
                                             verify_checkpoint)
from dmlc_core_tpu.serve.model_runtime import ModelRuntime, build_runtime
from dmlc_core_tpu.serve.registry import ModelRegistry
from dmlc_core_tpu.telemetry import clock
from dmlc_core_tpu.utils.logging import CHECK, log_info, log_warning

__all__ = ["CheckpointWatcher", "runtime_builder", "watch_interval_from_env"]

DEFAULT_WATCH_S = 2.0

# histogram bounds for whole-cycle swap latency (validate + warmup + flip;
# warmup compiles the bucket ladder, so seconds-scale buckets)
_SWAP_SECONDS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def watch_interval_from_env() -> float:
    raw = os.environ.get("DMLC_SERVE_WATCH_S", "").strip()
    if not raw:
        return DEFAULT_WATCH_S
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"DMLC_SERVE_WATCH_S must be a number of seconds, "
                         f"got {raw!r}") from None
    if v <= 0:
        raise ValueError(f"DMLC_SERVE_WATCH_S must be > 0, got {v}")
    return v


def runtime_builder(kind: str, num_feature: int,
                    **kwargs: Any) -> Callable[[str], ModelRuntime]:
    """The standard builder a watcher validates candidates with:
    ``build_runtime(kind, num_feature, checkpoint=<step uri>, ...)``.
    GBDT checkpoints are self-describing (``GBDT.serving_state``);
    linear/mlp restore into the declared architecture."""
    def build(checkpoint_uri: str) -> ModelRuntime:
        return build_runtime(kind, num_feature, checkpoint=checkpoint_uri,
                             **kwargs)
    return build


class CheckpointWatcher:
    """Poll a checkpoint directory; validate off-path; swap atomically.

    ``builder`` maps a checkpoint URI to a ready (unwarmed)
    :class:`~.model_runtime.ModelRuntime` — usually
    :func:`runtime_builder`.  One watcher serves one slot; multi-model
    deployments run one watcher per watched slot.
    """

    def __init__(self, registry: ModelRegistry, model: str,
                 directory: str, builder: Callable[[str], ModelRuntime],
                 *, poll_s: Optional[float] = None,
                 manager: Optional[CheckpointManager] = None):
        self.registry = registry
        self.model = model
        self.builder = builder
        self.manager = manager or CheckpointManager(directory)
        self.poll_s = poll_s if poll_s is not None \
            else watch_interval_from_env()
        CHECK(self.poll_s > 0, "poll_s must be > 0")
        # guards the progress odometers and the known-bad set: poll_once
        # is public API (tests/operators drive it inline) and also runs
        # on the watcher thread, so these are written from both sides
        self._lock = threading.Lock()
        self.swaps_completed = 0
        #: candidates rejected (validation/warmup/swap failures) — with
        #: ``swaps_completed``, the watcher's public progress odometer
        self.rejections = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (step, crc32) of every rejected candidate: bad bytes are never
        # re-validated on later polls (no hot loop), and the candidate
        # scan falls back PAST them to the next-newest published step —
        # bounded by retention's cap on how many steps the store keeps
        self._rejected: set = set()

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "CheckpointWatcher":
        CHECK(self._thread is None or not self._thread.is_alive(),
              "watcher already running")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"serve-watch-{self.model}",
            daemon=False)
        self._thread.start()
        log_info(f"serve: watching {self.manager.directory!r} for model "
                 f"{self.model!r} every {self.poll_s:g}s")
        return self

    def close(self, timeout: float = 30.0) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                log_warning(f"serve-watch-{self.model} did not stop within "
                            f"{timeout}s; abandoning it")

    def __enter__(self) -> "CheckpointWatcher":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as exc:  # noqa: BLE001 — ferried, not fatal
                # poll_once already classifies per-stage failures; this
                # guard is for the unexpected (the watcher thread must
                # survive anything short of interpreter teardown)
                log_warning(f"serve: watcher poll for {self.model!r} "
                            f"failed: {exc!r}")
            self._stop.wait(self.poll_s)

    # -- one poll -------------------------------------------------------------

    def poll_once(self) -> Optional[int]:
        """One watch→validate→warmup→swap cycle; returns the swapped-in
        step, or ``None`` (nothing new, not yet published, or rejected —
        with previous-good untouched in every non-swap outcome)."""
        slot = self.registry.get(self.model)
        stage = "watch"
        try:
            with telemetry.span("model.watch", model=self.model):
                fault.inject("serve.swap", stage="watch", model=self.model)
                step, manifest = self._candidate(slot)
        except Exception as exc:
            self._reject(None, None, stage, exc, slot)
            return None
        if step is None:
            return None
        uri = self.manager.step_uri(step)
        t0 = clock.monotonic()
        try:
            stage = "validate"
            with telemetry.span("model.validate", model=self.model,
                                step=step):
                fault.inject("serve.swap", stage="validate",
                             model=self.model)
                # bytes first (magic/size/CRC, no jax), then the build,
                # then the structural contract — all off-path
                verify_checkpoint(uri, manifest)
                runtime = self.builder(uri)
                CHECK(runtime.num_feature == slot.num_feature,
                      f"candidate serves {runtime.num_feature} features; "
                      f"slot contract is {slot.num_feature}")
            stage = "warmup"
            with telemetry.span("model.warmup", model=self.model,
                                step=step):
                fault.inject("serve.swap", stage="warmup",
                             model=self.model)
                runtime.warmup(slot.batcher.buckets)
            stage = "swap"
            with telemetry.span("model.swap", model=self.model, step=step):
                fault.inject("serve.swap", stage="swap", model=self.model)
                self.registry.swap(self.model, runtime, version=step)
        except Exception as exc:
            self._reject(step, manifest, stage, exc, slot)
            return None
        with self._lock:
            self.swaps_completed += 1
        telemetry.count("dmlc_serve_swap_total", model=self.model,
                        outcome="ok")
        telemetry.observe("dmlc_serve_swap_seconds",
                          clock.monotonic() - t0,
                          buckets=_SWAP_SECONDS_BUCKETS, model=self.model)
        return step

    def _candidate(self, slot):
        """The newest *published, not-known-bad* step above the slot's
        version, manifest included — or ``(None, None)``.

        The scan itself is :meth:`CheckpointManager.latest_valid` (shared
        with the continuous trainer's crash-resume so the fallback-past-
        bad-steps logic exists exactly once).  Newest-first with fallback:
        a rejected newest step must not pin the slot to stale
        previous-good forever when an older valid unswapped step sits in
        the store — e.g. the trainer published v2 then a corrupt v3 and
        stopped.  A step with no manifest yet stops the scan instead of
        being leapfrogged: its write is in flight and swapping to an
        older step now would just churn.
        """
        current = slot.version if isinstance(slot.version, int) else -1
        with self._lock:
            known_bad = frozenset(self._rejected)
        return self.manager.latest_valid(above=current, known_bad=known_bad)

    def _reject(self, step, manifest, stage: str, exc: Exception,
                slot) -> None:
        with self._lock:
            self.rejections += 1
            if step is not None and manifest is not None:
                self._rejected.add((step, manifest.get("crc32")))
        telemetry.count("dmlc_serve_swap_total", model=self.model,
                        outcome="failed")
        telemetry.count("dmlc_serve_swap_failures_total", model=self.model,
                        stage=stage)
        log_warning(
            f"serve: model {self.model!r} candidate "
            f"{'step ' + str(step) if step is not None else 'scan'} "
            f"rejected at {stage}: {exc!r}; previous-good "
            f"(v{slot.version}) keeps serving")
